"""Measure the BASELINE.md accuracy rows beyond digits (VERDICT r2 item 4).

Runs the reference-config workloads end-to-end through the REAL parsers
(LEAF femnist, CIFAR binary) on format-faithful generated files (see
``tools/make_format_datasets.py`` — content synthetic, provenance stamped)
plus the fednlp synthetic fallback, and prints one JSON line per row:
round-accuracy curve, rounds/min, dataset provenance.

Reference configs mirrored:
- femnist_cnn   — FedAvg CNN, natural LEAF user partition, 10 clients/round
  (reference ``config/simulation_sp/fedml_config.yaml`` scaled to FEMNIST)
- cifar100_resnet18 — FedProx ResNet-18(GN), Dirichlet(0.5)
- fednlp_20news — text transformer classification

Usage: python tools/run_baseline_rows.py [--fast] [--rows a,b,c]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# these rows are CPU workloads (accuracy dynamics, not device perf); skip
# the TPU liveness probe unless the caller explicitly overrides
os.environ.setdefault("FEDML_TPU_PLATFORM", "cpu")


def _run_row(name, overrides, backend="sp"):
    import fedml_tpu
    from fedml_tpu.arguments import load_arguments
    from fedml_tpu import data as data_mod, device as device_mod, \
        model as model_mod
    from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI
    from fedml_tpu.simulation.mesh.mesh_simulator import MeshFedAvgAPI

    args = load_arguments()
    args.update(**overrides)
    args = fedml_tpu.init(args, should_init_logs=False)
    dev = device_mod.get_device(args)
    dataset, out_dim = data_mod.load(args)
    model = model_mod.create(args, out_dim)
    api_cls = MeshFedAvgAPI if backend == "mesh" else FedAvgAPI
    api = api_cls(args, dev, dataset, model, client_mode="vmap")
    t0 = time.time()
    api.train()
    wall = time.time() - t0
    curve = [(r["round"], round(r["test_acc"], 4))
             for r in api.metrics_history if "test_acc" in r]
    return {
        "row": name,
        "backend": backend,
        "provenance": dataset.provenance,
        "clients": dataset.num_clients,
        "train_n": dataset.train_data_num,
        "rounds": int(overrides["comm_round"]),
        "acc_curve": curve,
        "final_acc": curve[-1][1] if curve else None,
        "rounds_per_min": round(overrides["comm_round"] / (wall / 60.0), 2),
        "wall_s": round(wall, 1),
        "config": {k: v for k, v in overrides.items()
                   if isinstance(v, (int, float, str, bool))},
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tiny shapes for CI smoke")
    ap.add_argument("--rows", default="femnist_cnn,cifar100_resnet18,"
                    "fednlp_20news")
    ap.add_argument("--cache", default=None,
                    help="dataset cache root (default: fresh temp dir)")
    ap.add_argument("--cifar-rounds", type=int, default=None,
                    help="override cifar100 comm rounds (full=10; the "
                         "resnet18 row costs ~20 CPU-min/round on the "
                         "1-core build box)")
    ap.add_argument("--cifar-train-n", type=int, default=None,
                    help="override cifar100 train set size (full=6000)")
    ap.add_argument("--cifar-model", default=None,
                    help="override the cifar100 model (e.g. resnet18_gn_w16:"
                         " same 2-2-2-2 resnet at 1/4 width — ~16x fewer "
                         "conv FLOPs, the honestly-labeled reduction that "
                         "makes 20+ rounds feasible on the 1-core box)")
    ap.add_argument("--news-rounds", type=int, default=None,
                    help="override fednlp_20news comm rounds (full=40; the "
                         "calibrated task is still rising there — longer "
                         "horizons approach the 0.82 NB ceiling)")
    ap.add_argument("--femnist-rounds", type=int, default=None,
                    help="override femnist comm rounds (full=30; the "
                         "round-3 curve was still rising at 30 — plateau "
                         "needs ~60)")
    args = ap.parse_args()
    rows = args.rows.split(",")
    cache = args.cache or tempfile.mkdtemp(prefix="fedml_tpu_rows_")

    from tools.make_format_datasets import make_cifar_bin, make_femnist_leaf

    results = []
    if "femnist_cnn" in rows:
        make_femnist_leaf(cache, n_users=20 if args.fast else 100)
        r = _run_row("femnist_cnn", dict(
            dataset="femnist", data_cache_dir=cache, model="cnn",
            client_num_in_total=100,  # ignored: natural LEAF partition wins
            client_num_per_round=4 if args.fast else 10,
            comm_round=(args.femnist_rounds if args.femnist_rounds
                        is not None else (3 if args.fast else 30)),
            epochs=1, batch_size=20,
            learning_rate=0.03 if args.fast else 0.06,
            frequency_of_the_test=1 if args.fast else 5, random_seed=0))
        r["config_delta_from_reference"] = (
            "reference simulation_sp/fedml_config.yaml:20-28 is MNIST-LR "
            "1000 clients/10 per round/200 rounds/batch 10/lr 0.03; this "
            "row keeps 10 clients/round and batch~20 on the natural LEAF "
            "femnist partition with CNN, lr 0.06, fewer rounds")
        results.append(r)
        print(json.dumps(r), flush=True)

    if "cifar100_resnet18" in rows:
        croot = os.path.join(cache, "cifar100")
        make_cifar_bin(croot, "cifar100",
                       train_n=args.cifar_train_n
                       or (1000 if args.fast else 6000),
                       test_n=200 if args.fast else 1000)
        r = _run_row("cifar100_resnet18", dict(
            dataset="cifar100", data_cache_dir=croot,
            model=args.cifar_model or "resnet18_gn",
            federated_optimizer="FedProx", fedprox_mu=0.1,
            client_num_in_total=8 if args.fast else 32,
            client_num_per_round=2 if args.fast else 4,
            comm_round=args.cifar_rounds
            or (2 if args.fast else 10), epochs=1, batch_size=20,
            learning_rate=0.05, partition_method="hetero",
            partition_alpha=0.5,
            frequency_of_the_test=1 if args.fast else 2, random_seed=0))
        cifar_model = args.cifar_model or "resnet18_gn"
        delta = ("reference cross_silo.hierarchical CIFAR uses full "
                 "resnet18_gn over GPUs; this row runs FedProx(mu=0.1) "
                 f"Dirichlet(0.5) with model={cifar_model}, "
                 f"{r['rounds']} rounds")
        if cifar_model.startswith("resnet18_gn_w"):
            delta += (" — the same 2-2-2-2 architecture at reduced width, "
                      "so many rounds fit the 1-core CPU box")
        if args.fast:
            delta += " [--fast smoke shapes: NOT a baseline measurement]"
        r["config_delta_from_reference"] = delta
        results.append(r)
        print(json.dumps(r), flush=True)

    if "fednlp_20news" in rows:
        r = _run_row("fednlp_20news", dict(
            dataset="20news", model="text_transformer",
            vocab_size=2000, seq_len=64,
            train_size=1000 if args.fast else 4000,
            test_size=200 if args.fast else 800,
            client_num_in_total=8 if args.fast else 20,
            client_num_per_round=2 if args.fast else 5,
            # 40 adam rounds: the round-5 calibrated generator needs a
            # longer horizon AND adam to approach its plateau (SGD lr=0.1
            # reached only 0.15 by round 24).  NB ceiling measured at THIS
            # row's reduced vocab=2000/seq=64: 0.82 (the spec-default
            # 30000/128 shape probes at 0.74) — judge the curve against
            # 0.82, not 1.0
            comm_round=(2 if args.fast
                        else (args.news_rounds or 40)), epochs=1,
            batch_size=16,
            learning_rate=3e-3, client_optimizer="adam",
            clip_grad_norm=1.0, partition_method="hetero",
            partition_alpha=0.5,
            frequency_of_the_test=1 if args.fast else 2, random_seed=0))
        results.append(r)
        print(json.dumps(r), flush=True)

    if "agnews" in rows:
        r = _run_row("agnews", dict(
            dataset="agnews", model="text_transformer",
            vocab_size=2000, seq_len=64,
            train_size=1000 if args.fast else 4000,
            test_size=200 if args.fast else 800,
            client_num_in_total=8 if args.fast else 12,
            client_num_per_round=2 if args.fast else 4,
            # NB ceiling measured at this row's vocab=2000: 0.936 (denser
            # evidence than the 30000-vocab spec shape, whose per-dataset
            # calibration probes at 0.68) — judge the curve against 0.94
            comm_round=2 if args.fast else 24, epochs=1, batch_size=16,
            learning_rate=3e-3, client_optimizer="adam",
            clip_grad_norm=1.0, partition_method="hetero",
            partition_alpha=0.5,
            frequency_of_the_test=1 if args.fast else 2, random_seed=0))
        results.append(r)
        print(json.dumps(r), flush=True)

    # REAL-bytes rows (round-4 VERDICT missing #4): ingestion-through-
    # accuracy on genuine bytes for image + text, from the committed
    # data_shards/ (tools/make_real_shards.py).  Small corpora, so these
    # run in minutes, not hours.
    if "digits_leaf_real" in rows:
        r = _run_row("digits_leaf_real", dict(
            dataset="digits", model="cnn", input_shape=(8, 8, 1),
            data_cache_dir=os.path.join(REPO, "data_shards"),
            client_num_in_total=15, client_num_per_round=5,
            comm_round=3 if args.fast else 30, epochs=1, batch_size=16,
            learning_rate=0.05, client_optimizer="sgd",
            frequency_of_the_test=1 if args.fast else 2, random_seed=0))
        r["config_delta_from_reference"] = (
            "real handwritten-digit bytes (sklearn/UCI optdigits) through "
            "the LEAF parser with the natural per-user partition — the "
            "in-image stand-in for the FEMNIST download")
        results.append(r)
        print(json.dumps(r), flush=True)

    if "realtext_docs" in rows:
        r = _run_row("realtext_docs", dict(
            dataset="realtext", model="text_transformer",
            seq_len=128, vocab_size=8192,     # match the shard's token space
            data_cache_dir=os.path.join(REPO, "data_shards", "realtext"),
            client_num_in_total=10, client_num_per_round=5,
            # adam, like the 20news row: SGD lr=0.1 was measured to leave
            # text_transformer near chance at this horizon
            comm_round=3 if args.fast else 24, epochs=1, batch_size=16,
            learning_rate=3e-3, client_optimizer="adam",
            clip_grad_norm=1.0, partition_method="hetero",
            partition_alpha=0.5,
            frequency_of_the_test=1 if args.fast else 2, random_seed=0))
        r["config_delta_from_reference"] = (
            "real technical prose (installed-package docs, 10 classes) "
            "through the npz text path — the in-image stand-in for the "
            "20news download; NB unigram ceiling probes at ~0.82")
        results.append(r)
        print(json.dumps(r), flush=True)

    out = os.path.join(REPO, "BASELINE_ROWS.json")
    # merge by row name so partial reruns (--rows subset) compose instead
    # of clobbering rows measured earlier
    merged = {}
    if os.path.exists(out):
        try:
            with open(out) as f:
                merged = {r["row"]: r for r in json.load(f)}
        except Exception:
            merged = {}
    merged.update({r["row"]: r for r in results})
    with open(out, "w") as f:
        json.dump(list(merged.values()), f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
