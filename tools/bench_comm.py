"""Communication-backend benchmark (reference
``python/tests/grpc_benchmark/``: gRPC vs torch-RPC throughput harness with
PDF plots; here a table over this repo's backends, runnable anywhere).

Measures round-trip request/response latency and bulk-tensor throughput for
each backend between two endpoints in one host:

    python tools/bench_comm.py [--backends local,grpc,filestore]
                               [--payload-mb 4] [--reps 20]
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
import types

import numpy as np

sys.path.insert(0, ".")

from fedml_tpu.core.distributed.communication.message import Message  # noqa: E402
from fedml_tpu.core.distributed.fedml_comm_manager import (  # noqa: E402
    create_comm_backend)

MSG_PING = 901
MSG_PONG = 902


def bench_backend(backend: str, payload_mb: float, reps: int) -> dict:
    args = types.SimpleNamespace(
        run_id=f"bench-{backend}-{time.time_ns()}",
        filestore_dir=tempfile.mkdtemp(prefix="fedml_bench_fs_"),
        grpc_base_port=18890)
    m0 = create_comm_backend(args, 0, 2, backend)
    m1 = create_comm_backend(args, 1, 2, backend)

    done = threading.Event()
    latencies = []

    class Echo:  # rank 1: bounce every ping back
        def receive_message(self, mtype, msg):
            if mtype == MSG_PING:
                out = Message(MSG_PONG, 1, 0)
                out.add_params("payload", msg.get("payload"))
                m1.send_message(out)

    class Timer:  # rank 0: record round trips
        def receive_message(self, mtype, msg):
            if mtype == MSG_PONG:
                latencies.append(time.perf_counter() - t_send[0])
                done.set()

    m1.add_observer(Echo())
    m0.add_observer(Timer())
    threads = [threading.Thread(target=m.handle_receive_message, daemon=True)
               for m in (m0, m1)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # channels up

    payload = np.random.default_rng(0).random(
        int(payload_mb * 1024 * 1024 / 8))
    t_send = [0.0]
    # warmup
    done.clear()
    t_send[0] = time.perf_counter()
    msg = Message(MSG_PING, 0, 1)
    msg.add_params("payload", payload)
    m0.send_message(msg)
    done.wait(30)
    latencies.clear()

    for _ in range(reps):
        done.clear()
        t_send[0] = time.perf_counter()
        msg = Message(MSG_PING, 0, 1)
        msg.add_params("payload", payload)
        m0.send_message(msg)
        if not done.wait(60):
            raise TimeoutError(f"{backend}: echo never returned")
    for m in (m0, m1):
        try:
            m.stop_receive_message()
        except Exception:
            pass

    lat = np.array(latencies)
    mb_roundtrip = 2 * payload.nbytes / 1e6
    return {
        "backend": backend,
        "payload_mb": round(payload.nbytes / 1e6, 2),
        "rtt_ms_p50": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "rtt_ms_p95": round(float(np.percentile(lat, 95)) * 1e3, 2),
        "throughput_MBps": round(mb_roundtrip / float(np.mean(lat)), 1),
        "msgs_per_sec": round(1.0 / float(np.mean(lat)), 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backends", default="local,GRPC,filestore")
    ap.add_argument("--payload-mb", type=float, default=4.0)
    ap.add_argument("--reps", type=int, default=20)
    opts = ap.parse_args()
    rows = []
    for b in opts.backends.split(","):
        try:
            rows.append(bench_backend(b.strip(), opts.payload_mb, opts.reps))
        except Exception as e:
            rows.append({"backend": b, "error": repr(e)})
    print(json.dumps(rows, indent=1))


if __name__ == "__main__":
    main()
