#!/usr/bin/env python
"""fedverify CLI — AOT lowering-level contract checks over the canonical
program registry (sharding, collective census, donation, HBM fit,
recompile surface; docs/FEDVERIFY.md).

Usage:
    python tools/fedverify.py                          # verify everything
    python tools/fedverify.py --programs mesh1d_scatter,mesh_block8
    python tools/fedverify.py --json                   # machine output
    python tools/fedverify.py --update-manifest        # refresh census
    python tools/fedverify.py --list-programs
    python tools/fedverify.py --list-rules

Exit codes mirror fedlint: 0 = no unsuppressed errors, 1 = at least one
(or any unsuppressed finding with --strict), 2 = usage error.

Unlike ``tools/fedlint.py`` (pure stdlib) this CLI lowers real programs,
so it needs jax + the package; it forces the 8-virtual-device CPU host
platform up front so every mesh program compiles hermetically on any
machine — no TPU required (the whole point: these contracts gate in CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _force_cpu_mesh():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("FEDML_TPU_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedverify", description="AOT lowering-level contract "
        "checks (sharding, collectives, donation, HBM, recompiles)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of registered programs")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings + census as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--manifest", default=None,
                    help="contracts.json path (default: "
                         "tests/data/fedverify/contracts.json)")
    ap.add_argument("--update-manifest", action="store_true",
                    help="rewrite the manifest's measured census fields "
                         "from this run (budgets/bands/suppressions are "
                         "preserved); the git diff is the review surface")
    ap.add_argument("--list-programs", action="store_true",
                    help="print the program registry and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the contract-rule catalog and exit")
    args = ap.parse_args(argv)

    _force_cpu_mesh()
    from fedml_tpu.analysis import fedverify as fv

    if args.list_rules:
        for r in fv.VERIFY_RULES.values():
            print(f"{r.name:24s} [{r.severity}] {r.doc}")
        return 0
    if args.list_programs:
        for name, builder in fv.PROGRAMS.items():
            doc = (builder.__doc__ or "").split("\n")[0].strip()
            print(f"{name:24s} {doc}")
        return 0

    names = None
    if args.programs:
        names = [n.strip() for n in args.programs.split(",") if n.strip()]
        unknown = set(names) - set(fv.PROGRAMS)
        if unknown:
            print(f"fedverify: unknown program(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    findings, reports = fv.verify_programs(
        names, manifest_path=args.manifest, update=args.update_manifest)

    if args.as_json:
        print(json.dumps({
            "findings": json.loads(fv.findings_to_json(findings)),
            "census": {r.name: r.to_manifest_entry() for r in reports},
        }, indent=2))
    else:
        print(fv.render_findings(findings,
                                 show_suppressed=args.show_suppressed,
                                 tool="fedverify"))
    return fv.exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
