#!/usr/bin/env python
"""fedproto CLI — static protocol checks + runtime trace conformance for
the distributed message-FSM plane (docs/FEDPROTO.md).

Usage:
    python tools/fedproto.py check                       # whole package
    python tools/fedproto.py check --families secagg,vertical
    python tools/fedproto.py check --json
    python tools/fedproto.py check --update-manifest     # refresh pins
    python tools/fedproto.py check-trace TRACE.json [...] \
        --family store_hierarchy
    python tools/fedproto.py --list-rules
    python tools/fedproto.py --list-families

Exit codes mirror fedlint/fedverify: 0 = no unsuppressed errors, 1 = at
least one (or any unsuppressed finding with --strict), 2 = usage error.

Pure stdlib like ``tools/fedlint.py``: the analyzer is loaded by file path
(fedlint first, then fedproto, which imports it), so protocol checking
needs no jax install — it runs on CI lint shards and pre-commit hooks.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fedproto():
    """Load fedlint + fedproto directly, bypassing fedml_tpu/__init__
    (which imports jax and initializes a backend)."""
    analysis = os.path.join(REPO, "fedml_tpu", "analysis")

    def load(name, fname):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(analysis, fname))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        return mod

    load("fedlint", "fedlint.py")   # fedproto's ImportError fallback name
    return load("fedproto", "fedproto.py")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedproto", description="static protocol checker + runtime "
        "conformance for the message-FSM plane (coverage, param "
        "contracts, liveness, trace replay)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--list-families", action="store_true",
                    help="print the protocol family table and exit")
    sub = ap.add_subparsers(dest="cmd")

    chk = sub.add_parser("check", help="extract + statically check the "
                         "protocol families")
    chk.add_argument("paths", nargs="*", default=None,
                     help="files/dirs to analyze (default: fedml_tpu/)")
    chk.add_argument("--families", default=None,
                     help="comma-separated subset of families")
    chk.add_argument("--json", action="store_true", dest="as_json")
    chk.add_argument("--strict", action="store_true",
                     help="exit 1 on warnings too")
    chk.add_argument("--show-suppressed", action="store_true")
    chk.add_argument("--manifest", default=None,
                     help="protocols.json path (default: "
                          "tests/data/fedproto/protocols.json)")
    chk.add_argument("--update-manifest", action="store_true",
                     help="rewrite the manifest's extracted protocols "
                          "(suppressions are preserved); the git diff is "
                          "the review surface")

    trc = sub.add_parser("check-trace", help="replay fedscope comm spans "
                         "against a pinned protocol")
    trc.add_argument("traces", nargs="+", help="fedscope capture(s) — "
                     "per-process or merged Chrome trace JSON")
    trc.add_argument("--family", default="store_hierarchy",
                     help="protocol family to validate against")
    trc.add_argument("--manifest", default=None)
    trc.add_argument("--json", action="store_true", dest="as_json")
    trc.add_argument("--strict", action="store_true")
    trc.add_argument("--show-suppressed", action="store_true")

    args = ap.parse_args(argv)
    fp = _load_fedproto()

    if args.list_rules:
        for r in fp.PROTO_RULES.values():
            print(f"{r.name:26s} [{r.severity}] {r.doc}")
        return 0
    if args.list_families:
        for name, cfg in fp.PROTOCOL_FAMILIES.items():
            roles = {}
            for member, (role, _path) in cfg["members"].items():
                roles.setdefault(role, []).append(member)
            desc = "; ".join(f"{role}: {', '.join(ms)}"
                             for role, ms in sorted(roles.items()))
            print(f"{name:20s} {desc}")
        return 0
    if args.cmd is None:
        ap.print_usage(sys.stderr)
        print("fedproto: error: choose a subcommand (check | check-trace)",
              file=sys.stderr)
        return 2

    if args.cmd == "check":
        paths = args.paths or [os.path.join(REPO, "fedml_tpu")]
        families = fp.PROTOCOL_FAMILIES
        if args.families:
            names = [n.strip() for n in args.families.split(",")
                     if n.strip()]
            unknown = set(names) - set(families)
            if unknown:
                print(f"fedproto: unknown family(ies): "
                      f"{', '.join(sorted(unknown))}", file=sys.stderr)
                return 2
            families = {n: families[n] for n in names}
        fams, warnings = fp.extract_protocols(paths, families)
        if args.update_manifest:
            fp.update_manifest(fams, args.manifest)
        manifest = fp.load_manifest(args.manifest)
        findings = fp.check_protocols(fams, manifest, warnings)
        if args.as_json:
            print(json.dumps({
                "findings": json.loads(fp.findings_to_json(findings)),
                "families": {n: fp.family_to_manifest(f)
                             for n, f in sorted(fams.items())},
            }, indent=2))
        else:
            print(fp.render_findings(
                findings, show_suppressed=args.show_suppressed,
                tool="fedproto"))
        return fp.exit_code(findings, strict=args.strict)

    # check-trace
    traces = []
    for path in args.traces:
        try:
            with open(path) as fh:
                traces.append(json.load(fh))
        except (OSError, json.JSONDecodeError) as exc:
            print(f"fedproto: cannot read trace {path}: {exc}",
                  file=sys.stderr)
            return 2
    manifest = fp.load_manifest(args.manifest)
    if manifest is None:
        print("fedproto: no manifest to replay against (run "
              "'check --update-manifest' first)", file=sys.stderr)
        return 2
    findings = fp.check_trace(traces, args.family, manifest)
    if args.as_json:
        print(fp.findings_to_json(findings))
    else:
        print(fp.render_findings(findings,
                                 show_suppressed=args.show_suppressed,
                                 tool="fedproto"))
    return fp.exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
