"""Real-TPU probe for the attention stack.

1. Lower + run the fixed Pallas flash fwd/bwd at bh>1 shapes (the round-3
   block-spec fix) and check parity against blockwise.
2. Minimal bf16 NaN bisection INSIDE attention: blockwise grads with
   rope on/off, f32 vs bf16 qkv, masked-softmax alone.

Run: python tools/tpu_attn_probe.py
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from fedml_tpu.ops.attention import (blockwise_attention, flash_attention,
                                     flash_attention_fwd_pallas)


def gnorm_finite(fn, *args):
    g = jax.jit(jax.grad(lambda *a: jnp.sum(fn(*a).astype(jnp.float32))))(*args)
    gn = float(np.asarray(jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                                       for x in jax.tree.leaves(g)))))
    return np.isfinite(gn), gn


def main():
    print("backend:", jax.default_backend())
    b, h, kvh, s, d = 2, 8, 4, 512, 64

    for dtype in (jnp.float32, jnp.bfloat16):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (b, h, s, d), dtype)
        k = jax.random.normal(ks[1], (b, kvh, s, d), dtype)
        v = jax.random.normal(ks[2], (b, kvh, s, d), dtype)

        # 1. pallas fwd lowers + parity
        out, lse = flash_attention_fwd_pallas(q, k, v, True, return_lse=True)
        ref = blockwise_attention(q, k, v, True)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        print(f"[{dtype.__name__}] pallas fwd max_abs_err vs blockwise: {err:.2e}")

        # 2. full custom-vjp path (pallas fwd + pallas bwd) grads
        ok, gn = gnorm_finite(lambda q, k, v: flash_attention(q, k, v, True),
                              q, k, v)
        print(f"[{dtype.__name__}] pallas fwd+bwd gnorm={gn:.4f} "
              f"{'ok' if ok else '*** NaN ***'}")

        # 3. blockwise XLA vjp grads
        ok, gn = gnorm_finite(
            lambda q, k, v: blockwise_attention(q, k, v, True), q, k, v)
        print(f"[{dtype.__name__}] blockwise vjp  gnorm={gn:.4f} "
              f"{'ok' if ok else '*** NaN ***'}")

    # 4. rope ablation, bf16
    from fedml_tpu.llm.model import _rope
    x = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
    pos = jnp.arange(s)
    ok, gn = gnorm_finite(lambda x: _rope(x, pos, 10000.0), x)
    print(f"[bf16] rope alone gnorm={gn:.4f} {'ok' if ok else '*** NaN ***'}")

    # 5. rope + blockwise
    q = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(3), (b, kvh, s, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(4), (b, kvh, s, d), jnp.bfloat16)
    ok, gn = gnorm_finite(
        lambda q, k, v: blockwise_attention(_rope(q, pos, 10000.0), _rope(k, pos, 10000.0), v,
                                            True), q, k, v)
    print(f"[bf16] rope+blockwise gnorm={gn:.4f} {'ok' if ok else '*** NaN ***'}")


if __name__ == "__main__":
    main()
