"""Real-bytes federated data shards for a zero-egress environment.

Round-4 VERDICT missing #4: the image/text BASELINE rows all ran on
synthetic bytes (the environment cannot download FEMNIST/CIFAR); the
real-bytes precedent was tabular-only (sklearn).  This tool writes two
REAL datasets through the SAME ingestion formats the reference uses, so
the parser→partition→train→accuracy pipeline is exercised on genuine
bytes end to end:

- ``make_digits_leaf``: sklearn ``load_digits`` — 1,797 REAL handwritten
  digit images (the UCI optical-digits corpus bundled inside sklearn,
  8x8 grayscale) — written as a LEAF train/test JSON shard layout
  (``data/femnist``-style: users / num_samples / user_data), the format
  ``fedml_tpu.data.leaf`` parses.  The corpus has no writer ids, so users
  are a deterministic round-robin split (documented in PROVENANCE).

- ``make_realtext_npz``: a REAL text-classification corpus harvested from
  documentation shipped inside installed packages (numpy/jax/sklearn/...):
  label = which package a doc chunk came from.  Real English/technical
  prose, hash-tokenized to the loader's npz contract (train_x/train_y/
  test_x/test_y int32 token matrices).

Both stamp a PROVENANCE file so ``FederatedDataset.provenance`` reports
``real:...`` (never ``synthetic``).  Shards are small (<4 MB total) and
committed under ``data_shards/``.
"""

from __future__ import annotations

import json
import os
import re
import zlib

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_digits_leaf(root: str, n_users: int = 15,
                     test_frac: float = 0.15) -> str:
    """Write sklearn's real handwritten-digit images as a LEAF shard."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images / 16.0).astype(np.float32).reshape(len(d.target), -1)
    y = d.target.astype(int)

    out = os.path.join(root, "digits")
    for split in ("train", "test"):
        os.makedirs(os.path.join(out, split), exist_ok=True)
    rng = np.random.default_rng(0)          # split only; bytes untouched
    order = rng.permutation(len(y))
    users = {f"u{u:03d}": order[u::n_users] for u in range(n_users)}
    for split in ("train", "test"):
        blob = {"users": [], "num_samples": [], "user_data": {}}
        for u, idxs in users.items():
            cut = int(round(len(idxs) * (1 - test_frac)))
            sel = idxs[:cut] if split == "train" else idxs[cut:]
            blob["users"].append(u)
            blob["num_samples"].append(len(sel))
            blob["user_data"][u] = {
                "x": [[round(float(v), 4) for v in x[i]] for i in sel],
                "y": [int(y[i]) for i in sel],
            }
        with open(os.path.join(out, split, "all_data.json"), "w") as f:
            json.dump(blob, f)
    with open(os.path.join(out, "PROVENANCE"), "w") as f:
        f.write("real:sklearn-digits(uci-optdigits, leaf-format; users are "
                "a deterministic round-robin split — the corpus ships no "
                "writer ids)")
    return out


# packages whose installed documentation provides the real text corpus;
# chosen for distinct-but-overlapping technical vocabulary (numeric
# stack members share plenty of terms, so the task is not trivial)
_TEXT_PACKAGES = ("numpy", "jax", "sklearn", "scipy", "torch", "flax",
                  "optax", "pandas", "setuptools", "chex")


def _harvest_package_text(pkg: str, max_bytes: int = 400_000) -> str:
    import importlib

    try:
        mod = importlib.import_module(pkg)
    except Exception:
        return ""
    root = os.path.dirname(getattr(mod, "__file__", "") or "")
    if not root:
        return ""
    chunks, total = [], 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in
                       ("__pycache__", "tests", "test")]
        for fn in sorted(filenames):
            if not fn.endswith((".rst", ".md", ".txt", ".py")):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, errors="ignore") as f:
                    text = f.read()
            except OSError:
                continue
            if fn.endswith(".py"):
                # docstrings + comments only: prose, not code syntax
                # (classifying code by syntax tokens would be trivial)
                parts = re.findall(r'"""(.*?)"""', text, re.S)
                parts += [ln.lstrip()[1:].strip() for ln in text.splitlines()
                          if ln.lstrip().startswith("#")]
                text = "\n".join(parts)
            chunks.append(text)
            total += len(text)
            if total >= max_bytes:
                return "\n".join(chunks)[:max_bytes]
    return "\n".join(chunks)[:max_bytes]


def _tokenize(text: str, vocab: int, seq_len: int, drop_pkg_names=()):
    """Hash-tokenize prose into fixed windows; ids 2.. (0=pad, 1=oov-ish).
    Package self-references are dropped — the label must not literally
    appear in the features."""
    words = re.findall(r"[A-Za-z][A-Za-z0-9_]+", text.lower())
    drop = {p.lower() for p in drop_pkg_names}
    # crc32, not hash(): Python's hash is salted per process, and the
    # shard must be reproducible byte-for-byte
    ids = [2 + (zlib.crc32(w.encode()) % (vocab - 2))
           for w in words if w not in drop]
    rows = []
    for i in range(0, len(ids) - seq_len + 1, seq_len):
        rows.append(ids[i:i + seq_len])
    return rows


def make_realtext_npz(root: str, vocab: int = 8192, seq_len: int = 128,
                      test_frac: float = 0.15) -> str:
    os.makedirs(root, exist_ok=True)
    tx, ty, vx, vy = [], [], [], []
    kept = []
    for label, pkg in enumerate(_TEXT_PACKAGES):
        text = _harvest_package_text(pkg)
        rows = _tokenize(text, vocab, seq_len, drop_pkg_names=_TEXT_PACKAGES)
        if len(rows) < 40:
            # fail LOUDLY: the class count is pinned in _TEXTCLS_SPECS and
            # by tests — silently dropping a package would regenerate a
            # shard whose labels no longer match the registered spec
            raise RuntimeError(
                f"package {pkg!r} yielded only {len(rows)} rows — the "
                "realtext spec pins 10 classes; fix the package list or "
                "update _TEXTCLS_SPECS + tests together")
        kept.append(pkg)
        lbl = len(kept) - 1
        cut = int(len(rows) * (1 - test_frac))
        tx.extend(rows[:cut])
        ty.extend([lbl] * cut)
        vx.extend(rows[cut:])
        vy.extend([lbl] * (len(rows) - cut))
    path = os.path.join(root, "realtext.npz")
    np.savez_compressed(
        path,
        train_x=np.asarray(tx, np.int32), train_y=np.asarray(ty, np.int64),
        test_x=np.asarray(vx, np.int32), test_y=np.asarray(vy, np.int64))
    # dataset-scoped marker (PROVENANCE.<name>) so the loader's
    # name-mention rule attributes it to realtext.npz specifically
    with open(os.path.join(root, "PROVENANCE.realtext"), "w") as f:
        f.write("real:installed-package-docs(classes=" + ",".join(kept)
                + "; docstrings/comments/rst prose, hash-tokenized, "
                "package self-references dropped)")
    return path


if __name__ == "__main__":
    import sys

    root = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO,
                                                              "data_shards")
    print(make_digits_leaf(root))
    print(make_realtext_npz(os.path.join(root, "realtext")))
