"""Billion-parameter FedLLM execution probe (VERDICT r2 item 3: the
flagship had never executed above ~3.4M params).

Runs REAL federated LoRA rounds through the shipped ``FedLLMAPI`` on a
>=1B-parameter Llama config (bf16 base, fp32 adapters), measuring:

- wall-clock per federated round + tokens/sec + analytic MFU with
  LoRA-aware FLOPs ((4*N + 6*r)*T over the device peak — nominal for TPU,
  measured-matmul for CPU; see bench.py rationale);
- live array bytes (``jax.live_arrays``) vs the closed-form prediction in
  ``core/memory_estimate.py`` — the estimator must be an UPPER bound that
  is not wildly loose (checked: actual <= estimate <= 4x actual).

Default config ~1.08B params (dim 2048, 20 layers, GQA 16q/8kv, ffn 5632,
vocab 32000).  On one CPU core a round is minutes — run detached; on a TPU
chip it is seconds.  ``--dim``/``--layers``/... override; ``--fast`` is a
CI-scale smoke (still >1B lookup-bound? no: fast drops to ~120M params).

Usage: python tools/llm_scale_run.py [--rounds 2] [--seq 256] [--fast]
       python tools/llm_scale_run.py --layer7b   # true-7B per-layer bench
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("FEDML_TPU_PLATFORM") is None \
        and os.environ.get("LLM_SCALE_TPU") is None:
    # default CPU: the TPU tunnel wedges for hours; set LLM_SCALE_TPU=1 to
    # let the normal backend probe run (tools/tpu_watchdog.py does)
    os.environ["FEDML_TPU_PLATFORM"] = "cpu"


def layer7b_bench(args_cli):
    """One Llama-2-7B transformer layer (true 7B dims), LoRA step: measures
    the per-layer cost a 7B fine-tune pays 32x per step.  Fits one v5e chip
    (layer params 202M bf16 = 0.4 GiB) where the full 7B (13.5 GiB weights
    + activations) does not leave room for benching."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import fedml_tpu  # noqa: F401  (backend + compile-cache setup)
    from fedml_tpu.llm.model import Block, LlamaConfig

    cfg = LlamaConfig(vocab_size=32000, dim=4096, n_layers=1, n_heads=32,
                      n_kv_heads=32, ffn_dim=11008,
                      max_seq_len=args_cli.seq, dtype=jnp.bfloat16,
                      lora_rank=args_cli.lora_rank)
    batch, seq = 1, args_cli.seq
    block = Block(cfg)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, seq, cfg.dim), jnp.bfloat16)
    positions = jnp.arange(seq)
    variables = block.init(key, x, positions)
    params, lora = variables["params"], variables.get("lora", {})
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    n_lora = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(lora))
    tx = optax.sgd(1e-3)
    opt = tx.init(lora)

    # params ride as a jit ARGUMENT: closing over the 0.4 GiB weight tree
    # would inline it into the HLO constants and blow the tunnel's
    # remote-compile request limit (HTTP 413, observed 2026-08-01)
    def loss_fn(lora, params, x):
        out = block.apply({"params": params, "lora": lora}, x, positions)
        return jnp.mean(jnp.square(out.astype(jnp.float32)))

    @jax.jit
    def step(lora, opt, params, x):
        loss, g = jax.value_and_grad(loss_fn)(lora, params, x)
        upd, opt = tx.update(g, opt)
        return optax.apply_updates(lora, upd), opt, loss

    from bench import _measured_matmul_peak, _peak_flops, _readback, \
        _timed_chain, measure_rtt
    state = [step(lora, opt, params, x)]
    _readback(state[0][2])
    rtt = measure_rtt()

    def run_n(k):
        lo, op, _ = state[0]
        for _ in range(k):
            lo, op, loss = step(lo, op, params, x)
        state[0] = (lo, op, loss)

    dt = _timed_chain(run_n, lambda: _readback(state[0][2]), n0=5, rtt=rtt)
    dev = jax.devices()[0]
    peak = _peak_flops(dev) or _measured_matmul_peak()
    tokens = batch * seq
    flops = (4.0 * n_params + 6.0 * n_lora) * tokens
    result = {
        "metric": "llama7b_layer_step",
        "value": round(dt, 5),
        "unit": "s/layer-step",
        "vs_baseline": None,
        "n_layer_params": n_params,
        "n_lora_params": n_lora,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "mfu": round(flops / dt / peak, 4),
        "tokens_per_sec_layer": round(tokens / dt, 1),
        "extrapolated_32layer_stack_step_s": round(dt * 32, 3),
        "extrapolated_32layer_stack_tokens_per_sec": round(
            tokens / (dt * 32), 1),
        "note": ("transformer stack only: tok_embed + lm_head "
                 "(2 x 32000 x 4096 = 262M params, ~1.3 layer-equivalents "
                 "of matmul for the head) are excluded from the x32 "
                 "extrapolation"),
        "config": {"dim": 4096, "ffn": 11008, "heads": 32, "seq": seq,
                   "batch": batch, "lora_rank": args_cli.lora_rank,
                   "dtype": "bfloat16"},
    }
    print(json.dumps(result))
    with open(os.path.join(REPO, "LLM_7B_LAYER.json"), "w") as f:
        json.dump(result, f, indent=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=20)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--ffn", type=int, default=5632)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--clients-per-round", type=int, default=2)
    ap.add_argument("--local-steps", type=int, default=2)
    ap.add_argument("--lora-rank", type=int, default=16)
    ap.add_argument("--xent-chunk", type=int, default=8192,
                    help="vocab chunk for the streaming fused cross-entropy "
                         "(ops/xent.py); 0 = dense logits path")
    ap.add_argument("--remat", default="full",
                    choices=("full", "dots", "none"),
                    help="block recompute policy (llm.model.LlamaConfig."
                         "remat); the memory estimate prices the same "
                         "policy, so the upper-bound check stays valid")
    ap.add_argument("--fast", action="store_true",
                    help="~120M-param smoke for CI")
    ap.add_argument("--dump-live", action="store_true",
                    help="print every live jax array (shape/dtype/bytes) "
                         "grouped by size — estimator calibration aid")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="run the SAME config through the GSPMD mesh "
                         "regime on N virtual CPU devices (client x model "
                         "= N/2 x 2): base params laid out by the TP/FSDP "
                         "rules, cohort sharded over the client axis — "
                         "executes the pod path at real scale without "
                         "pod hardware")
    ap.add_argument("--layer7b", action="store_true",
                    help="single-layer microbench at Llama-2-7B dims "
                         "(dim 4096, ffn 11008, 32q/32kv heads): per-layer "
                         "fwd+bwd step time and MFU, extrapolated x32 — "
                         "the 7B per-layer evidence one 16GiB chip allows")
    args_cli = ap.parse_args()
    if args_cli.mesh:
        if args_cli.mesh < 2 or args_cli.mesh % 2:
            ap.error(f"--mesh {args_cli.mesh}: must be an even count >= 2 "
                     "(mesh layout is client x model with model=2)")
        # must precede the jax import below.  The collective timeouts
        # matter at >=1B params: N virtual devices SERIALIZE on this
        # 1-core box, so a cross-module all-gather legitimately waits
        # minutes for all participants — XLA's default 40s terminate
        # timeout kills a correct program (observed at 1.075B; 40M fits
        # inside the window)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args_cli.mesh}"
            + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=7200"
            + " --xla_cpu_collective_timeout_seconds=7200").strip()
    if args_cli.layer7b:
        return layer7b_bench(args_cli)
    if args_cli.fast:
        args_cli.dim, args_cli.layers, args_cli.ffn, args_cli.vocab = \
            512, 8, 1408, 16000
        args_cli.seq, args_cli.rounds = 128, 1

    import numpy as np
    import jax

    import fedml_tpu
    from fedml_tpu.arguments import load_arguments
    from fedml_tpu import data as data_mod
    from fedml_tpu.llm.fedllm import FedLLMAPI
    from fedml_tpu.core.memory_estimate import (FedLLMLayout,
                                                estimate_fedllm_memory)

    args = load_arguments()
    args.update(
        dataset="shakespeare", train_size=args_cli.clients_per_round * 64,
        test_size=32, seq_len=args_cli.seq, model="llama",
        llm_dim=args_cli.dim, llm_n_layers=args_cli.layers,
        llm_n_heads=args_cli.heads, llm_n_kv_heads=args_cli.kv_heads,
        llm_ffn_dim=args_cli.ffn, llm_max_seq_len=args_cli.seq,
        client_num_in_total=max(4, args_cli.clients_per_round),
        client_num_per_round=args_cli.clients_per_round,
        comm_round=args_cli.rounds, batch_size=1,
        llm_max_local_steps=args_cli.local_steps,
        lora_rank=args_cli.lora_rank, learning_rate=1e-4, random_seed=0,
        streaming_xent_chunk=args_cli.xent_chunk,
        llm_remat=args_cli.remat,
    )
    args = fedml_tpu.init(args, should_init_logs=False)
    # the LM loader caps vocab at the spec; force the big-vocab synthetic
    args.update(dataset="stackoverflow_nwp")
    dataset, vocab = data_mod.load(args)
    # overwrite vocab to the requested size (tokens stay in range: the
    # synthetic generator draws < spec vocab; clip for safety)
    dataset.train_x = np.minimum(dataset.train_x, args_cli.vocab - 1)
    dataset.train_y = np.minimum(dataset.train_y, args_cli.vocab - 1)
    dataset.test_x = np.minimum(dataset.test_x, args_cli.vocab - 1)
    dataset.test_y = np.minimum(dataset.test_y, args_cli.vocab - 1)
    dataset.num_classes = args_cli.vocab

    mesh = None
    if args_cli.mesh:
        from fedml_tpu.core.mesh import make_mesh
        n_model = 2
        mesh = make_mesh(client=args_cli.mesh // n_model, model=n_model)
        print(f"# mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
              f"over {args_cli.mesh} virtual devices",
              file=sys.stderr, flush=True)

    t0 = time.time()
    api = FedLLMAPI(args, dataset, mesh=mesh)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(api.base_params))
    n_lora = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(api.global_lora))
    init_s = time.time() - t0
    print(f"# init: {n_params / 1e9:.3f}B base params, {n_lora / 1e6:.2f}M "
          f"adapter params, {init_s:.1f}s", file=sys.stderr, flush=True)

    # -- run rounds (first includes compile) -------------------------------
    t0 = time.time()
    m0 = api.train_one_round(0)
    jax.tree_util.tree_map(
        lambda a: np.asarray(a) if hasattr(a, "shape") else a, m0)
    compile_round_s = time.time() - t0
    timed = []
    for r in range(1, args_cli.rounds):
        t0 = time.time()
        m = api.train_one_round(r)
        loss = float(np.asarray(m["train_loss"]))
        timed.append(time.time() - t0)
    round_s = min(timed) if timed else compile_round_s
    tokens_per_round = (args_cli.clients_per_round * args_cli.local_steps
                        * 1 * args_cli.seq)
    # LoRA step FLOPs: frozen base = fwd + activation-grad matmuls only
    # (4NT); adapters pay the full 6T/param (see bench.py rationale)
    flops_per_round = (4.0 * n_params + 6.0 * n_lora) * tokens_per_round

    # -- live memory vs estimator ------------------------------------------
    # logical bytes count each sharded array once; PER-CHIP PHYSICAL bytes
    # (sum of addressable shard buffers per device — replicated terms cost
    # every replica) are what a real pod chip must hold, so the estimator
    # is judged against the max-loaded device, not the logical total
    from collections import Counter
    live = 0
    per_dev = Counter()
    for a in jax.live_arrays():
        live += a.nbytes
        try:
            for s in a.addressable_shards:
                per_dev[s.device.id] += int(
                    np.prod(s.data.shape)) * s.data.dtype.itemsize
        except Exception:                       # committed host/token arrays
            per_dev[0] += a.nbytes
    live_per_chip = max(per_dev.values()) if per_dev else live
    if args_cli.dump_live:
        groups = Counter()
        for a in jax.live_arrays():
            groups[(str(a.dtype), tuple(a.shape))] += a.nbytes
        for (dt, shp), nb in sorted(groups.items(), key=lambda kv: -kv[1]):
            print(f"# live {nb / 2**20:9.2f} MiB  {dt:10s} {shp}",
                  file=sys.stderr, flush=True)
        print("# per-device MiB: " + str(
            {d: round(v / 2**20, 1) for d, v in sorted(per_dev.items())}),
            file=sys.stderr, flush=True)
    layout = FedLLMLayout(
        n_params=n_params, n_lora_params=n_lora,
        n_clients=args_cli.clients_per_round,
        n_chips=max(args_cli.mesh, 1),
        model_shards=2 if args_cli.mesh else 1,
        batch_per_client=1, seq_len=args_cli.seq, dim=args_cli.dim,
        n_layers=args_cli.layers, remat=args_cli.remat,
        ffn_dim=args_cli.ffn,
        kv_dim=args_cli.kv_heads * (args_cli.dim // args_cli.heads))
    est = estimate_fedllm_memory(layout)

    from bench import _measured_matmul_peak, _peak_flops
    dev = jax.devices()[0]
    peak = _peak_flops(dev) or _measured_matmul_peak()

    result = {
        "metric": "fedllm_round_wall_clock",
        "value": round(round_s, 3),
        "unit": "s/round",
        "vs_baseline": None,
        "n_params": n_params,
        "n_params_b": round(n_params / 1e9, 3),
        "n_lora_params": n_lora,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "tokens_per_sec": round(tokens_per_round / round_s, 1),
        "mfu": round(flops_per_round / round_s / peak, 4),
        "compile_round_s": round(compile_round_s, 1),
        "init_s": round(init_s, 1),
        "train_loss": loss if timed else float(np.asarray(m0["train_loss"])),
        "live_bytes_gib": round(live / 2 ** 30, 3),
        "live_per_chip_gib": round(live_per_chip / 2 ** 30, 3),
        # per-chip estimate vs the max-loaded device's PHYSICAL bytes —
        # apples-to-apples: both count replicated terms per replica, so
        # the tightness here is the margin a real pod scheduler would see
        "estimator_gib": round(est["total_gib"], 3),
        "estimator_is_upper_bound": bool(est["total"] >= live_per_chip),
        "estimator_tightness": round(
            est["total"] / max(live_per_chip, 1), 2),
        "mesh": (dict(zip(mesh.axis_names,
                          [int(s) for s in mesh.devices.shape]))
                 if mesh is not None else None),
        "config": {"dim": args_cli.dim, "layers": args_cli.layers,
                   "heads": args_cli.heads, "kv_heads": args_cli.kv_heads,
                   "ffn": args_cli.ffn, "vocab": args_cli.vocab,
                   "seq": args_cli.seq, "lora_rank": args_cli.lora_rank,
                   "clients_per_round": args_cli.clients_per_round,
                   "local_steps": args_cli.local_steps, "dtype": "bfloat16",
                   "streaming_xent_chunk": args_cli.xent_chunk},
    }
    print(json.dumps(result))
    # per-mode artifacts: a --fast smoke or a mesh run must never
    # overwrite the flagship default-config artifact (round 3 shipped
    # exactly that mix-up — BASELINE.md's 1.08B row pointed at a --fast
    # run for a whole round)
    name = "LLM_SCALE_RUN"
    if args_cli.fast:
        name = "LLM_SCALE_FAST"
    if args_cli.mesh:
        name += "_MESH"
    out = os.path.join(REPO, name + ".json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
