"""Billion-parameter FedLLM execution probe (VERDICT r2 item 3: the
flagship had never executed above ~3.4M params).

Runs REAL federated LoRA rounds through the shipped ``FedLLMAPI`` on a
>=1B-parameter Llama config (bf16 base, fp32 adapters), measuring:

- wall-clock per federated round + tokens/sec + analytic MFU
  (6 * n_params * tokens / step, over the device peak — nominal for TPU,
  measured-matmul for CPU);
- live array bytes (``jax.live_arrays``) vs the closed-form prediction in
  ``core/memory_estimate.py`` — the estimator must be an UPPER bound that
  is not wildly loose (checked: actual <= estimate <= 4x actual).

Default config ~1.08B params (dim 2048, 20 layers, GQA 16q/8kv, ffn 5632,
vocab 32000).  On one CPU core a round is minutes — run detached; on a TPU
chip it is seconds.  ``--dim``/``--layers``/... override; ``--fast`` is a
CI-scale smoke (still >1B lookup-bound? no: fast drops to ~120M params).

Usage: python tools/llm_scale_run.py [--rounds 2] [--seq 256] [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("FEDML_TPU_PLATFORM") is None \
        and os.environ.get("LLM_SCALE_TPU") is None:
    # default CPU: the TPU tunnel wedges for hours; set LLM_SCALE_TPU=1 to
    # let the normal backend probe run (tools/tpu_watchdog.py does)
    os.environ["FEDML_TPU_PLATFORM"] = "cpu"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=20)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--ffn", type=int, default=5632)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--clients-per-round", type=int, default=2)
    ap.add_argument("--local-steps", type=int, default=2)
    ap.add_argument("--lora-rank", type=int, default=16)
    ap.add_argument("--fast", action="store_true",
                    help="~120M-param smoke for CI")
    args_cli = ap.parse_args()
    if args_cli.fast:
        args_cli.dim, args_cli.layers, args_cli.ffn, args_cli.vocab = \
            512, 8, 1408, 16000
        args_cli.seq, args_cli.rounds = 128, 1

    import numpy as np
    import jax

    import fedml_tpu
    from fedml_tpu.arguments import load_arguments
    from fedml_tpu import data as data_mod
    from fedml_tpu.llm.fedllm import FedLLMAPI
    from fedml_tpu.core.memory_estimate import (FedLLMLayout,
                                                estimate_fedllm_memory)

    args = load_arguments()
    args.update(
        dataset="shakespeare", train_size=args_cli.clients_per_round * 64,
        test_size=32, seq_len=args_cli.seq, model="llama",
        llm_dim=args_cli.dim, llm_n_layers=args_cli.layers,
        llm_n_heads=args_cli.heads, llm_n_kv_heads=args_cli.kv_heads,
        llm_ffn_dim=args_cli.ffn, llm_max_seq_len=args_cli.seq,
        client_num_in_total=max(4, args_cli.clients_per_round),
        client_num_per_round=args_cli.clients_per_round,
        comm_round=args_cli.rounds, batch_size=1,
        llm_max_local_steps=args_cli.local_steps,
        lora_rank=args_cli.lora_rank, learning_rate=1e-4, random_seed=0,
    )
    args = fedml_tpu.init(args, should_init_logs=False)
    # the LM loader caps vocab at the spec; force the big-vocab synthetic
    args.update(dataset="stackoverflow_nwp")
    dataset, vocab = data_mod.load(args)
    # overwrite vocab to the requested size (tokens stay in range: the
    # synthetic generator draws < spec vocab; clip for safety)
    dataset.train_x = np.minimum(dataset.train_x, args_cli.vocab - 1)
    dataset.train_y = np.minimum(dataset.train_y, args_cli.vocab - 1)
    dataset.test_x = np.minimum(dataset.test_x, args_cli.vocab - 1)
    dataset.test_y = np.minimum(dataset.test_y, args_cli.vocab - 1)
    dataset.num_classes = args_cli.vocab

    t0 = time.time()
    api = FedLLMAPI(args, dataset)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(api.base_params))
    n_lora = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(api.global_lora))
    init_s = time.time() - t0
    print(f"# init: {n_params / 1e9:.3f}B base params, {n_lora / 1e6:.2f}M "
          f"adapter params, {init_s:.1f}s", file=sys.stderr, flush=True)

    # -- run rounds (first includes compile) -------------------------------
    t0 = time.time()
    m0 = api.train_one_round(0)
    jax.tree_util.tree_map(
        lambda a: np.asarray(a) if hasattr(a, "shape") else a, m0)
    compile_round_s = time.time() - t0
    timed = []
    for r in range(1, args_cli.rounds):
        t0 = time.time()
        m = api.train_one_round(r)
        loss = float(np.asarray(m["train_loss"]))
        timed.append(time.time() - t0)
    round_s = min(timed) if timed else compile_round_s
    tokens_per_round = (args_cli.clients_per_round * args_cli.local_steps
                        * 1 * args_cli.seq)
    # LoRA step FLOPs: frozen base = fwd + activation-grad matmuls only
    # (4NT); adapters pay the full 6T/param (see bench.py rationale)
    flops_per_round = (4.0 * n_params + 6.0 * n_lora) * tokens_per_round

    # -- live memory vs estimator ------------------------------------------
    live = sum(a.nbytes for a in jax.live_arrays())
    layout = FedLLMLayout(
        n_params=n_params, n_lora_params=n_lora,
        n_clients=args_cli.clients_per_round, n_chips=1, model_shards=1,
        batch_per_client=1, seq_len=args_cli.seq, dim=args_cli.dim,
        n_layers=args_cli.layers)
    est = estimate_fedllm_memory(layout)

    from bench import _measured_matmul_peak, _peak_flops
    dev = jax.devices()[0]
    peak = _peak_flops(dev) or _measured_matmul_peak()

    result = {
        "metric": "fedllm_round_wall_clock",
        "value": round(round_s, 3),
        "unit": "s/round",
        "vs_baseline": None,
        "n_params": n_params,
        "n_params_b": round(n_params / 1e9, 3),
        "n_lora_params": n_lora,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "tokens_per_sec": round(tokens_per_round / round_s, 1),
        "mfu": round(flops_per_round / round_s / peak, 4),
        "compile_round_s": round(compile_round_s, 1),
        "init_s": round(init_s, 1),
        "train_loss": loss if timed else float(np.asarray(m0["train_loss"])),
        "live_bytes_gib": round(live / 2 ** 30, 3),
        "estimator_gib": round(est["total_gib"], 3),
        "estimator_is_upper_bound": bool(est["total"] >= live),
        "estimator_tightness": round(est["total"] / max(live, 1), 2),
        "config": {"dim": args_cli.dim, "layers": args_cli.layers,
                   "heads": args_cli.heads, "kv_heads": args_cli.kv_heads,
                   "ffn": args_cli.ffn, "vocab": args_cli.vocab,
                   "seq": args_cli.seq, "lora_rank": args_cli.lora_rank,
                   "clients_per_round": args_cli.clients_per_round,
                   "local_steps": args_cli.local_steps, "dtype": "bfloat16"},
    }
    print(json.dumps(result))
    out = os.path.join(REPO, "LLM_SCALE_RUN.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
