"""Inner bisect of the TPU-bf16 blockwise-attention gradient NaN.

Variants toggle one suspect at a time; run on a live TPU.
"""
from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

NEG_INF = -1e30


def blockwise(q, k, v, causal, block_k, neg_inf, pet, upcast):
    """Minimal MHA copy of ops.attention.blockwise_attention with knobs:
    neg_inf value, preferred_element_type on the score einsum, full-f32
    upcast of inputs."""
    if upcast:
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    *lead, s_q, d = q.shape
    s_k = k.shape[-2]
    sm_scale = 1.0 / (d ** 0.5)
    block_k = min(block_k, s_k)
    n_blocks = s_k // block_k
    kb = jnp.moveaxis(k.reshape(*lead, n_blocks, block_k, d), -3, 0)
    vb = jnp.moveaxis(v.reshape(*lead, n_blocks, block_k, d), -3, 0)
    q_pos = jnp.arange(s_q)

    def scores_of(q, kblk):
        if pet:
            return jnp.einsum("...qd,...kd->...qk", q, kblk,
                              preferred_element_type=jnp.float32) * sm_scale
        return jnp.einsum("...qd,...kd->...qk",
                          q, kblk).astype(jnp.float32) * sm_scale

    def body(carry, inp):
        m, l, acc, blk = carry
        kblk, vblk = inp
        scores = scores_of(q, kblk)
        kv_pos = blk * block_k + jnp.arange(block_k)
        if causal:
            valid = kv_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(valid, scores, neg_inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "...qk,...kd->...qd", p.astype(vblk.dtype),
            vblk).astype(jnp.float32)
        return (m_new, l_new, acc_new, blk + 1), None

    m0 = jnp.full((*lead, s_q), neg_inf, jnp.float32)
    l0 = jnp.zeros((*lead, s_q), jnp.float32)
    acc0 = jnp.zeros((*lead, s_q, d), jnp.float32)
    (m, l, acc, _), _ = jax.lax.scan(body, (m0, l0, acc0, 0), (kb, vb))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def check(name, **kw):
    b, h, s, d = 2, 8, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)
    fn = functools.partial(blockwise, **kw)
    g = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
        argnums=(0, 1, 2)))(q, k, v)
    gn = float(np.asarray(jnp.sqrt(sum(
        jnp.sum(jnp.square(x.astype(jnp.float32)))
        for x in jax.tree.leaves(g)))))
    print(f"{name:44s} gnorm={gn:12.4f} "
          f"{'ok' if np.isfinite(gn) else '*** NaN ***'}")


def main():
    print("backend:", jax.default_backend())
    base = dict(causal=True, block_k=256, neg_inf=NEG_INF, pet=False,
                upcast=False)
    check("baseline (causal, 2 blocks, -1e30)", **base)
    check("non-causal", **{**base, "causal": False})
    check("single k block", **{**base, "block_k": 512})
    check("neg_inf=-1e9", **{**base, "neg_inf": -1e9})
    check("neg_inf=-30000 (bf16-safe)", **{**base, "neg_inf": -30000.0})
    check("preferred_element_type=f32", **{**base, "pet": True})
    check("full f32 upcast", **{**base, "upcast": True})
    check("pet + non-causal", **{**base, "pet": True, "causal": False})


if __name__ == "__main__":
    main()
