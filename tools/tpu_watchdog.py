"""TPU-tunnel watchdog: probe until the wedged tunnel revives, then run
the full benchmark battery once and exit.

The tunnel-attached TPU in this image wedges for hours at a time
(BASELINE.md round-2 notes): ``jax.devices()`` blocks indefinitely and
only an out-of-process probe can tell.  This tool polls cheaply and, the
moment a probe succeeds, captures every TPU-side artifact in one pass:

- ``TPU_BENCH_LIVE.json``   — bench.py default mode (FedAvg + LLM LoRA)
- ``TPU_ATTN_SWEEP.json``   — bench.py --attn (flash vs blockwise parity+timing)
- ``TPU_SERVE_BENCH.json``  — bench.py --serve (decode stack tokens/sec)
- ``TPU_NAN_BISECT.out``    — tools/tpu_nan_bisect.py (bf16 gradient issue)

Run detached:  nohup python tools/tpu_watchdog.py > tools/watchdog.log 2>&1 &
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = 120
POLL_INTERVAL_S = 300
JOB_TIMEOUT_S = 2400


def _probe_worker(q):
    import jax  # noqa: PLC0415

    q.put([str(d) for d in jax.devices()])


def tpu_alive() -> bool:
    q = mp.Queue()
    p = mp.Process(target=_probe_worker, args=(q,))
    p.start()
    p.join(PROBE_TIMEOUT_S)
    if p.is_alive():
        p.terminate()
        p.join(5)
        return False
    if q.empty():
        return False
    devs = q.get()
    alive = any("TPU" in d or "tpu" in d for d in devs)
    print(f"[watchdog] probe: {devs} alive={alive}", flush=True)
    return alive


def run_job(cmd, out_path, timeout_s=JOB_TIMEOUT_S) -> bool:
    print(f"[watchdog] running: {' '.join(cmd)}", flush=True)
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        print(f"[watchdog] TIMEOUT: {cmd}", flush=True)
        # overwrite the artifact so a stale previous result can't
        # masquerade as this run's output
        with open(os.path.join(REPO, out_path), "w") as f:
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            f.write(json.dumps({"metric": "watchdog_timeout", "value": None,
                                "unit": None, "vs_baseline": None,
                                "cmd": cmd, "timeout_s": timeout_s}))
            f.write("\n[partial stdout]\n" + partial[-4000:])
        return False
    with open(os.path.join(REPO, out_path), "w") as f:
        f.write(r.stdout)
        if r.returncode != 0:
            f.write(f"\n[stderr tail]\n{r.stderr[-4000:]}\n[rc={r.returncode}]")
    print(f"[watchdog] {out_path}: rc={r.returncode} "
          f"({len(r.stdout)} bytes)", flush=True)
    return r.returncode == 0


def main():
    t0 = time.time()
    while True:
        if tpu_alive():
            break
        print(f"[watchdog] tunnel wedged ({(time.time() - t0) / 60:.0f} min "
              f"elapsed); retrying in {POLL_INTERVAL_S}s", flush=True)
        time.sleep(POLL_INTERVAL_S)

    py = sys.executable
    # serialize: one TPU client at a time (concurrent clients wedge it).
    # Ordered by value-per-minute in case the tunnel re-wedges mid-battery:
    # headline bench first, then the >=1B FedLLM run (the round-3 VERDICT
    # ask), then serving/attention, then tuning sweeps, then the NaN-fix
    # regression probe (bug already fixed+committed — lowest priority).
    run_job([py, "bench.py"], "TPU_BENCH_LIVE.json")
    _run_scale_jobs(py)
    run_job([py, "bench.py", "--serve"], "TPU_SERVE_BENCH.json")
    run_job([py, "bench.py", "--attn"], "TPU_ATTN_SWEEP.json",
            timeout_s=3600)
    # remaining flash-tile sweep shapes (shape 0 measured live round-3;
    # paste results into ops/attention.py::_TUNED_BLOCKS)
    run_job([py, "tools/tpu_flash_tune.py", "1", "2", "3", "4", "5"],
            "TPU_FLASH_TUNE.json", timeout_s=3600)
    run_job([py, "tools/tpu_nan_bisect.py"], "TPU_NAN_BISECT.out",
            timeout_s=1200)
    print("[watchdog] battery complete", flush=True)


def _run_scale_jobs(py):
    env = dict(os.environ)
    env["LLM_SCALE_TPU"] = "1"  # let the scale probes use the live TPU
    for cmd, out in ((["tools/llm_scale_run.py", "--rounds", "3"],
                      "TPU_LLM_SCALE.json"),
                     (["tools/llm_scale_run.py", "--layer7b",
                       "--seq", "2048"], "TPU_LLM_7B_LAYER.json")):
        try:
            r = subprocess.run([py] + cmd, cwd=REPO, capture_output=True,
                               text=True, timeout=3600, env=env)
            with open(os.path.join(REPO, out), "w") as f:
                f.write(r.stdout)
                if r.returncode != 0:
                    f.write(f"\n[stderr tail]\n{r.stderr[-4000:]}")
            print(f"[watchdog] {out} rc={r.returncode}", flush=True)
        except subprocess.TimeoutExpired:
            print(f"[watchdog] {cmd} TIMEOUT", flush=True)
            # overwrite so a stale previous result can't masquerade as
            # this run's output (same rule as run_job above)
            with open(os.path.join(REPO, out), "w") as f:
                f.write(json.dumps({"metric": "watchdog_timeout",
                                    "value": None, "unit": None,
                                    "vs_baseline": None, "cmd": cmd}))


if __name__ == "__main__":
    main()
