"""TPU-tunnel watchdog: probe until the wedged tunnel revives, then run
the HEADLINE captures once and exit.

The tunnel-attached TPU in this image wedges for hours at a time
(BASELINE.md round-2 notes): ``jax.devices()`` blocks indefinitely and
only an out-of-process probe can tell.  This tool polls cheaply and, the
moment a probe succeeds, captures the two highest-value artifacts:

- ``TPU_BENCH_LIVE.json``   — bench.py default mode (FedAvg + LLM LoRA)
- ``TPU_LLM_SCALE.json``    — the 1.075B flagship scale run

Everything else (serve, attn sweep, flash tune, the MFU ablation grid,
the 7B layer) is owned by ``tools/r5_tpu_controller.py``, which writes
attempts to side files and replaces an artifact ONLY with a validated
on-TPU capture — this tool's overwrite-on-timeout stubs must never race
it for those files (they destroyed a live capture's successor slot on
2026-08-01).

Run detached:  nohup python tools/tpu_watchdog.py > tools/watchdog.log 2>&1 &
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = 120
POLL_INTERVAL_S = 300
JOB_TIMEOUT_S = 2400


def _probe_worker(q):
    import jax  # noqa: PLC0415

    q.put([str(d) for d in jax.devices()])


def tpu_alive() -> bool:
    q = mp.Queue()
    p = mp.Process(target=_probe_worker, args=(q,))
    p.start()
    p.join(PROBE_TIMEOUT_S)
    if p.is_alive():
        p.terminate()
        p.join(5)
        return False
    if q.empty():
        return False
    devs = q.get()
    alive = any("TPU" in d or "tpu" in d for d in devs)
    print(f"[watchdog] probe: {devs} alive={alive}", flush=True)
    return alive


def run_job(cmd, out_path, timeout_s=JOB_TIMEOUT_S, extra_env=None) -> bool:
    print(f"[watchdog] running: {' '.join(cmd)}", flush=True)
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=timeout_s,
                           env=dict(os.environ, **(extra_env or {})))
    except subprocess.TimeoutExpired as e:
        print(f"[watchdog] TIMEOUT: {cmd}", flush=True)
        # overwrite the artifact so a stale previous result can't
        # masquerade as this run's output
        with open(os.path.join(REPO, out_path), "w") as f:
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            f.write(json.dumps({"metric": "watchdog_timeout", "value": None,
                                "unit": None, "vs_baseline": None,
                                "cmd": cmd, "timeout_s": timeout_s}))
            f.write("\n[partial stdout]\n" + partial[-4000:])
        return False
    with open(os.path.join(REPO, out_path), "w") as f:
        f.write(r.stdout)
        if r.returncode != 0:
            f.write(f"\n[stderr tail]\n{r.stderr[-4000:]}\n[rc={r.returncode}]")
    print(f"[watchdog] {out_path}: rc={r.returncode} "
          f"({len(r.stdout)} bytes)", flush=True)
    return r.returncode == 0


def main():
    t0 = time.time()
    while True:
        if tpu_alive():
            break
        print(f"[watchdog] tunnel wedged ({(time.time() - t0) / 60:.0f} min "
              f"elapsed); retrying in {POLL_INTERVAL_S}s", flush=True)
        time.sleep(POLL_INTERVAL_S)

    py = sys.executable
    # serialize: one TPU client at a time (concurrent clients wedge it).
    # Headline bench first, then the >=1B FedLLM run — highest value per
    # minute in case the tunnel re-wedges mid-battery.  The rest of the
    # battery (serve, attn, flash tune, MFU ablation, 7B layer) is OWNED
    # by tools/r5_tpu_controller.py: its overwrite rule (side-file
    # attempts, artifact replaced only by a validated on-TPU capture)
    # must not race this tool's overwrite-on-timeout stubs, which can
    # destroy validated evidence (observed hazard 2026-08-01).
    run_job([py, "bench.py"], "TPU_BENCH_LIVE.json")
    _run_scale_jobs(py)
    print("[watchdog] headline captures complete; run "
          "tools/r5_tpu_controller.py for the remaining artifacts",
          flush=True)


def _run_scale_jobs(py):
    env = dict(os.environ)
    env["LLM_SCALE_TPU"] = "1"  # let the scale probes use the live TPU
    # (the 7B-layer probe moved to r5_tpu_controller's queue — see main)
    for cmd, out in ((["tools/llm_scale_run.py", "--rounds", "3"],
                      "TPU_LLM_SCALE.json"),):
        try:
            r = subprocess.run([py] + cmd, cwd=REPO, capture_output=True,
                               text=True, timeout=3600, env=env)
            with open(os.path.join(REPO, out), "w") as f:
                f.write(r.stdout)
                if r.returncode != 0:
                    f.write(f"\n[stderr tail]\n{r.stderr[-4000:]}")
            print(f"[watchdog] {out} rc={r.returncode}", flush=True)
        except subprocess.TimeoutExpired:
            print(f"[watchdog] {cmd} TIMEOUT", flush=True)
            # overwrite so a stale previous result can't masquerade as
            # this run's output (same rule as run_job above)
            with open(os.path.join(REPO, out), "w") as f:
                f.write(json.dumps({"metric": "watchdog_timeout",
                                    "value": None, "unit": None,
                                    "vs_baseline": None, "cmd": cmd}))


if __name__ == "__main__":
    main()
