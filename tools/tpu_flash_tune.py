"""Sweep Pallas flash-attention block sizes on the live TPU.

BENCH_r03 showed flash 0.71x vs the XLA blockwise scan at the bench LLM
shape (d=64, S=1024) — the fixed 512/512 tiles are not universally right.
This sweeps (block_q, block_k) per shape, timing the Pallas forward and
backward against the blockwise baseline with the readback-forced method
(bench.py docstring), and prints one JSON line whose ``table`` field is
ready to paste into ``fedml_tpu/ops/attention.py::_TUNED_BLOCKS``.

Run only when no other tunnel client is active (concurrent clients wedge
the tunnel — BASELINE.md round-2 notes).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

# (batch, q_heads, kv_heads, seq, head_dim) — bench shape first, then the
# sweep shapes bench.py --attn exercises, then a 7B-ish GQA slice.
SHAPES = [
    (4, 16, 16, 1024, 64),
    (2, 16, 16, 2048, 64),
    (1, 16, 16, 4096, 64),
    (4, 8, 8, 1024, 128),
    (1, 8, 8, 4096, 128),
    (1, 32, 8, 2048, 128),
]
BLOCKS = (256, 512, 1024)
REPS = 8


def _readback(x):
    import jax
    import jax.numpy as jnp
    leaf = jax.tree.leaves(x)[0]
    return float(np.asarray(jnp.sum(leaf.astype(jnp.float32))))


def _time_chained(fn, x0, reps=REPS, min_total_s=1.0):
    """Time reps-long jitted chains of fn, dispatched back-to-back n times
    (async dispatches pipeline in device program order; the one final
    readback forces them all), growing n until wall-clock >= min_total_s
    so the tunnel RTT amortizes; returns s/call.  The table this feeds
    gates the autotune-or-fallback policy — a single short sample whose
    time is mostly one RTT draw can crown a losing tile."""
    import jax

    f = jax.jit(lambda x: _chain(fn, x, reps))
    _readback(f(x0))  # compile
    n, total = 1, 0.0
    for _ in range(4):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = f(x0)
        _readback(out)
        total = time.perf_counter() - t0
        if total >= min_total_s:
            break
        per = max(total / n, 1e-6)
        n = min(int(min_total_s * 1.3 / per) + 1, 512)
    return total / (n * reps)


def _chain(fn, x, reps):
    import jax

    def body(c, _):
        return fn(c), ()
    out, _ = jax.lax.scan(body, x, None, length=reps)
    return out


def main():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops import attention as A

    # optional argv: indices into SHAPES (resumable sweep), e.g. "1 2 3"
    idxs = [int(a) for a in sys.argv[1:]] or list(range(len(SHAPES)))

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    results = []
    table = {}
    for (b, h, h_kv, s, d) in [SHAPES[i] for i in idxs]:
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
        # grouped KV consumed natively by both paths (no repeat needed)
        kg = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.bfloat16)
        vg = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.bfloat16)

        base_s = _time_chained(
            lambda x: A.blockwise_attention(x, kg, vg, True), q)
        rows = []
        for bq in BLOCKS:
            if bq > s:
                continue
            for bk in BLOCKS:
                if bk > s:
                    continue
                try:
                    fwd_s = _time_chained(
                        lambda x, bq=bq, bk=bk: A.flash_attention_fwd_pallas(
                            x, kg, vg, True, None, block_q=bq, block_k=bk), q)
                except Exception as e:  # noqa: BLE001 — record and move on
                    rows.append({"bq": bq, "bk": bk, "error": repr(e)[:120]})
                    continue
                rows.append({"bq": bq, "bk": bk, "fwd_s": round(fwd_s, 6),
                             "vs_blockwise": round(base_s / fwd_s, 3)})
        ok = [r for r in rows if "fwd_s" in r]
        best = min(ok, key=lambda r: r["fwd_s"]) if ok else None
        # backward timing at the best fwd tile (do chained through dq)
        bwd_s = None
        if best is not None:
            out, lse = A.flash_attention_fwd_pallas(
                q, kg, vg, True, None, block_q=best["bq"],
                block_k=best["bk"], return_lse=True)

            def bwd(do, bq=best["bq"], bk=best["bk"]):
                dq, _, _ = A.flash_attention_bwd_pallas(
                    q, kg, vg, out, lse, do, True, None,
                    block_q=bq, block_k=bk)
                return dq
            try:
                bwd_s = _time_chained(bwd, q)
            except Exception as e:  # noqa: BLE001
                bwd_s = repr(e)[:120]
        shape_key = f"b{b}_h{h}_kv{h_kv}_s{s}_d{d}"
        results.append({"shape": shape_key, "blockwise_s": round(base_s, 6),
                        "rows": rows, "best": best, "bwd_s_at_best": bwd_s})
        # autotune-or-fallback: only shapes where flash WINS get a table
        # entry; losers stay on the blockwise path (attention._use_pallas)
        if best is not None and best["vs_blockwise"] >= 1.0:
            table[(s, d)] = (best["bq"], best["bk"])
        print(f"[tune] {shape_key}: blockwise {base_s*1e3:.2f}ms "
              f"best {best}", flush=True)

    # `paste` is literal _TUNED_BLOCKS entry lines (tuple keys/values),
    # i.e. actually ready to paste into fedml_tpu/ops/attention.py
    paste = "\n".join(f"    ({s}, {d}): ({bq}, {bk}),"
                      for (s, d), (bq, bk) in sorted(table.items()))
    print(json.dumps({
        "metric": "flash_block_tune",
        "value": len(table),
        "unit": "shapes_tuned",
        "vs_baseline": None,
        "device_kind": dev.device_kind,
        "paste": paste,
        "results": results,
    }))


if __name__ == "__main__":
    main()
