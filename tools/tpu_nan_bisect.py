"""Bisect the TPU-only bf16 gradient NaN (round-2 finding: full-model
grads are NaN on the tunnel TPU in bf16 for BOTH attention impls, while CPU
bf16 and TPU f32 are clean — see BASELINE.md round-2 notes).

Run on a healthy TPU:  python tools/tpu_nan_bisect.py

Each ablation builds a 1-layer model variant and reports whether grads wrt
params contain NaN.  The first ablation that flips clean → NaN names the op.
"""

from __future__ import annotations

import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")

from fedml_tpu.llm.model import (Attention, LlamaConfig, MLP,  # noqa: E402
                                 RMSNorm, _rope, causal_nll)

CFG = LlamaConfig(vocab_size=8192, dim=512, n_layers=1, n_heads=8,
                  n_kv_heads=4, ffn_dim=1408, max_seq_len=512,
                  dtype=jnp.bfloat16, lora_rank=0, attn_impl="blockwise")
B, S = 2, 512


class BlockVariant(nn.Module):
    cfg: LlamaConfig
    use_attn: bool = True
    use_mlp: bool = True
    use_norm: bool = True

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        norm = (lambda name: RMSNorm(cfg.norm_eps, name=name)) if \
            self.use_norm else (lambda name: (lambda v: v))
        if self.use_attn:
            x = x + Attention(cfg, name="attention")(norm("n1")(x), positions)
        if self.use_mlp:
            x = x + MLP(cfg, name="mlp")(norm("n2")(x))
        return x


class Variant(nn.Module):
    cfg: LlamaConfig
    use_attn: bool = True
    use_mlp: bool = True
    use_norm: bool = True
    use_remat: bool = False
    use_embed: bool = True
    fp32_head: bool = True

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        if self.use_embed:
            x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                         name="tok_embed")(tokens)
        else:
            x = jax.nn.one_hot(tokens % cfg.dim, cfg.dim, dtype=cfg.dtype)
        positions = jnp.arange(tokens.shape[-1])
        block_cls = nn.remat(BlockVariant) if self.use_remat else BlockVariant
        x = block_cls(cfg, self.use_attn, self.use_mlp, self.use_norm,
                      name="block")(x, positions)
        if self.use_norm:
            x = RMSNorm(cfg.norm_eps, name="nf")(x)
        head_dtype = jnp.float32 if self.fp32_head else cfg.dtype
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=head_dtype,
                        name="lm_head")(x)


def grads_nan(**kw) -> bool:
    model = Variant(CFG, **kw)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (B, S), 0, CFG.vocab_size)
    params = model.init(rng, tokens)["params"]

    def loss_fn(p):
        logits = model.apply({"params": p}, tokens)
        return causal_nll(logits[:, :-1], tokens[:, 1:])

    loss, g = jax.jit(jax.value_and_grad(loss_fn))(params)
    gn = float(optax.global_norm(g))
    return (not np.isfinite(gn)), float(loss), gn


def main():
    print("backend:", jax.default_backend())
    cases = [
        ("full (attn+mlp+norm+remat)", dict(use_remat=True)),
        ("no remat", dict(use_remat=False)),
        ("attn only", dict(use_mlp=False)),
        ("mlp only", dict(use_attn=False)),
        ("attn, no norm", dict(use_mlp=False, use_norm=False)),
        ("mlp, no norm", dict(use_attn=False, use_norm=False)),
        ("no embed (one-hot input)", dict(use_embed=False)),
        ("bf16 head", dict(fp32_head=False)),
        ("norm+head only", dict(use_attn=False, use_mlp=False)),
    ]
    for name, kw in cases:
        try:
            bad, loss, gn = grads_nan(**kw)
            print(f"{name:34s} loss={loss:9.4f} gnorm={gn:12.4f} "
                  f"{'*** NaN ***' if bad else 'ok'}")
        except Exception as e:
            print(f"{name:34s} ERROR {e}")


if __name__ == "__main__":
    main()
