"""Process-parallel pytest sharding (pytest-xdist is not in the image).

Partitions the test FILES across N worker processes (greedy longest-
processing-time bin packing over the duration hints below) and runs one
pytest per shard concurrently.  File granularity keeps every existing
module-scoped fixture/process assumption intact — tests within a file never
split across workers.

Duration hints come from a full-suite run (2026-07-31, 296 tests, 47 min
contended / ~25 min solo); unknown files get a middle weight.  Exact values
only affect balance, not correctness.

Usage: python tools/pytest_shard.py [-n 4] [-m "not slow"] [extra pytest args]
Exit code: max of the shard exit codes (0 only if every shard passed).
"""

from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rough seconds per file, solo-run scale; balance hints only
WEIGHTS = {
    "test_llm.py": 420, "test_mesh.py": 260, "test_serving_plane.py": 240,
    "test_algorithms.py": 220, "test_e2e_sp.py": 160, "test_moe.py": 150,
    "test_cross_silo.py": 150, "test_deploy_plane.py": 140,
    "test_speculative.py": 130, "test_flash_bwd.py": 120,
    "test_datasets_ext.py": 120, "test_scheduler.py": 110,
    "test_hierarchical_dcn.py": 110, "test_quantization.py": 100,
    "test_trust_stack.py": 100, "test_process_federation.py": 90,
    "test_secagg_cross_silo.py": 90, "test_native_edge.py": 90,
    "test_pipeline.py": 80, "test_compression.py": 80, "test_xent.py": 70,
    "test_mini_mqtt.py": 70, "test_hf_import.py": 60, "test_comm_ext.py": 60,
}
DEFAULT_WEIGHT = 50


def partition(files, n):
    """Greedy LPT bin packing, fully deterministic: ties in weight break on
    the basename, ties in load break on the lowest shard index, so the same
    file set always yields the same shards regardless of input order
    (glob order is filesystem-dependent) or `-p no:randomly`."""
    shards = [[] for _ in range(n)]
    loads = [0.0] * n
    for f in sorted(files, key=lambda f: (-WEIGHTS.get(os.path.basename(f),
                                                       DEFAULT_WEIGHT),
                                          os.path.basename(f))):
        i = loads.index(min(loads))
        shards[i].append(f)
        loads[i] += WEIGHTS.get(os.path.basename(f), DEFAULT_WEIGHT)
    return [s for s in shards if s]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=min(4, os.cpu_count() or 1),
                    help="worker processes (default: min(4, cores) — "
                         "oversubscribing cores just adds contention and "
                         "flakes timing-sensitive daemon tests)")
    ap.add_argument("-m", default=None, help="pytest -m marker expression")
    ap.add_argument("rest", nargs=argparse.REMAINDER,
                    help="extra pytest args (after --)")
    args = ap.parse_args()

    files = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    shards = partition(files, args.n)
    base = [sys.executable, "-m", "pytest", "-q"]
    if args.m:
        base += ["-m", args.m]
    base += [a for a in args.rest if a != "--"]

    t0 = time.time()
    procs = [subprocess.Popen(base + shard, cwd=REPO) for shard in shards]
    rcs = [p.wait() for p in procs]
    print(f"[shard] {len(shards)} shards finished in "
          f"{time.time() - t0:.0f}s, rcs={rcs}", flush=True)
    # pytest exit 5 = "no tests collected" (a shard whose files were all
    # deselected by -m) — fine per shard, but if EVERY shard collected
    # nothing (e.g. a typo'd -m expression) the run executed zero tests
    # and must not report success
    if all(rc == 5 for rc in rcs):
        print("[shard] ERROR: no tests collected in ANY shard "
              "(check the -m/-k expression)", flush=True)
        sys.exit(5)
    sys.exit(max((0 if rc == 5 else rc) for rc in rcs))


if __name__ == "__main__":
    main()
