"""Generate FORMAT-FAITHFUL dataset files for the real parsers.

The zero-egress image cannot download FEMNIST/CIFAR bytes, but the loaders'
format contracts (LEAF json with natural per-user splits — reference
``python/fedml/data/FederatedEMNIST``/``MNIST/data_loader.py`` read_data;
CIFAR binary batches — ``data/cifar10/data_loader.py``) can still be
exercised end-to-end with generated files.  Every directory written here
gets a ``PROVENANCE`` marker file so ``fedml_tpu.data.load`` stamps the
resulting dataset ``synthetic:*`` instead of ``real:*`` — a driver-provided
real archive (no marker) keeps its ``real:*`` tag.  Accuracy measured on
these files demonstrates the full parser→partition→train pipeline and the
learning dynamics, NOT real-dataset accuracy parity.

Content model: class templates + per-user style (brightness/shift) so the
label structure is learnable and clients are heterogeneous like real
FEMNIST writers.
"""

from __future__ import annotations

import json
import os

import numpy as np


def _class_images(rng, labels, shape, user_gain=1.0, user_bias=0.0,
                  noise=0.25, templates=None):
    """Low-rank class templates + noise; optionally per-user affine style."""
    h, w, c = shape
    n_classes = templates.shape[0]
    x = templates[labels % n_classes]
    x = x * user_gain + user_bias
    x = x + rng.normal(0.0, noise, size=x.shape)
    return np.clip(x, 0.0, 1.0).astype(np.float32)


def _make_templates(rng, n_classes, shape):
    h, w, c = shape
    t = rng.random((n_classes, h, w, c)) * 0.3
    # each class gets a distinct bright stripe pattern (learnable by a CNN)
    for k in range(n_classes):
        r0 = (k * 7) % h
        c0 = (k * 11) % w
        t[k, r0:r0 + 3, :, :] += 0.5
        t[k, :, c0:c0 + 3, :] += 0.4
    return np.clip(t, 0, 1)


def make_femnist_leaf(root: str, n_users: int = 100,
                      min_samples: int = 60, max_samples: int = 240,
                      n_classes: int = 62, shape=(28, 28, 1),
                      shards: int = 4, test_frac: float = 0.15,
                      seed: int = 7) -> str:
    """Write ``<root>/femnist/{train,test}/*.json`` in LEAF layout with a
    natural per-user partition and per-user style heterogeneity."""
    rng = np.random.default_rng(seed)
    base = os.path.join(root, "femnist")
    templates = _make_templates(rng, n_classes, shape)
    users = [f"f{u:04d}" for u in range(n_users)]
    train_blobs = [{"users": [], "num_samples": [], "user_data": {}}
                   for _ in range(shards)]
    test_blobs = [{"users": [], "num_samples": [], "user_data": {}}
                  for _ in range(shards)]
    for ui, u in enumerate(users):
        n = int(rng.integers(min_samples, max_samples + 1))
        # real femnist users only write a subset of characters
        classes_here = rng.choice(n_classes,
                                  size=int(rng.integers(8, 24)),
                                  replace=False)
        labels = rng.choice(classes_here, size=n)
        gain = float(rng.uniform(0.7, 1.3))
        bias = float(rng.uniform(-0.1, 0.1))
        x = _class_images(rng, labels, shape, gain, bias,
                          templates=templates)
        n_test = max(1, int(n * test_frac))
        flat = x.reshape(n, -1)
        sh = ui % shards
        for blob, sl in ((train_blobs[sh], slice(0, n - n_test)),
                         (test_blobs[sh], slice(n - n_test, n))):
            blob["users"].append(u)
            blob["num_samples"].append(sl.stop - (sl.start or 0))
            blob["user_data"][u] = {
                "x": [row.tolist() for row in flat[sl]],
                "y": [int(v) for v in labels[sl]],
            }
    for split, blobs in (("train", train_blobs), ("test", test_blobs)):
        d = os.path.join(base, split)
        os.makedirs(d, exist_ok=True)
        for i, blob in enumerate(blobs):
            with open(os.path.join(d, f"all_data_{i}.json"), "w") as f:
                json.dump(blob, f)
    with open(os.path.join(base, "PROVENANCE"), "w") as f:
        f.write("synthetic:leaf-format(femnist-shaped)")
    return base


def make_cifar_bin(root: str, name: str = "cifar10",
                   train_n: int = 10000, test_n: int = 2000,
                   seed: int = 7) -> str:
    """Write CIFAR binary batches (``cifar-10-batches-bin`` /
    ``cifar-100-binary`` layout: [label byte(s)][3072 pixel bytes] rows)."""
    rng = np.random.default_rng(seed)
    is100 = "100" in name
    classes = 100 if is100 else 10
    d = os.path.join(root, "cifar-100-binary" if is100
                     else "cifar-10-batches-bin")
    os.makedirs(d, exist_ok=True)
    templates = _make_templates(rng, classes, (32, 32, 3))

    def write(path, n):
        labels = rng.integers(0, classes, size=n)
        x = _class_images(rng, labels, (32, 32, 3), templates=templates)
        pix = (x * 255).astype(np.uint8).transpose(0, 3, 1, 2).reshape(n, -1)
        if is100:
            rows = np.concatenate(
                [(labels // 5).astype(np.uint8)[:, None],  # coarse label
                 labels.astype(np.uint8)[:, None], pix], axis=1)
        else:
            rows = np.concatenate([labels.astype(np.uint8)[:, None], pix],
                                  axis=1)
        rows.tofile(path)

    if is100:
        write(os.path.join(d, "train.bin"), train_n)
        write(os.path.join(d, "test.bin"), test_n)
    else:
        per = train_n // 5
        for i in range(1, 6):
            write(os.path.join(d, f"data_batch_{i}.bin"), per)
        write(os.path.join(d, "test_batch.bin"), test_n)
    with open(os.path.join(root, "PROVENANCE"), "w") as f:
        f.write(f"synthetic:{name}-bin-format")
    return d


if __name__ == "__main__":
    import sys
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/fedml_tpu_datasets"
    print(make_femnist_leaf(root))
    print(make_cifar_bin(os.path.join(root, "cifar10"), "cifar10"))
    print(make_cifar_bin(os.path.join(root, "cifar100"), "cifar100"))
