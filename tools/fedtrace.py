#!/usr/bin/env python
"""fedtrace — analyze fedml_tpu Chrome trace-event captures.

Pure stdlib (runs without jax installed, like ``tools/fedlint.py``):

- ``fedtrace.py summarize TRACE.json [--json]`` — span totals, counters,
  and the per-phase (staging / gather / client_steps / merge /
  server_update) round-time breakdown.
- ``fedtrace.py diff A.json B.json [--json]`` — per-phase comparison of
  two traces (e.g. fused vs. unfused, or two commits).
- ``fedtrace.py merge --out M.json A.json B.json ...`` — align N
  per-process captures of one federation run on a handshake-estimated
  clock offset into ONE Perfetto-loadable timeline (fedscope).
- ``fedtrace.py critical-path MERGED.json [--round R]`` — walk each
  round's span DAG (cross-process edges via the propagated span ids)
  and report the gating chain + per-silo straggler ranking.
- ``fedtrace.py regress CURRENT.json [--bands F] [--baseline-dir D]`` —
  per-metric tolerance gate of a bench row against the committed
  ``BENCH_r*.json`` trajectory; exit 3 on regression.
- ``fedtrace.py health TRACE.json [--json]`` — offline federation-health
  report from a captured trace (fedmon, docs/OBSERVABILITY.md): the
  per-round ``health.*`` counter trajectory, every flagged client with
  its score/reason, and the drift envelope.

Attribution model (docs/OBSERVABILITY.md): ``staging`` is measured
directly from host spans; the four device phases are apportioned from
each round's measured wall-clock (the ``obs.round`` counter's
``round_time_s``) proportionally to the per-phase FLOP weights the
compiled round records on device (``ObsCarry.phase_flops``) — unless the
trace carries MEASURED per-phase device durations (the ``device.<p>_s``
counters the ``trace_device`` probe emits), which replace the FLOP proxy.

Exit codes: 0 ok, 1 malformed trace / bad input, 2 usage error,
3 regression detected (``regress`` only).
"""

from __future__ import annotations

import argparse
import fnmatch
import glob as glob_mod
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

DEVICE_PHASES = ("gather", "client_steps", "merge", "server_update")
PHASES = ("staging",) + DEVICE_PHASES

#: counter names of the measured device-phase probe (obs/devicetime.py)
MEASURED_PHASE_COUNTERS = {p: f"device.{p}_s" for p in DEVICE_PHASES}


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        trace = json.load(fh)
    if isinstance(trace, list):  # bare-array Chrome format
        trace = {"traceEvents": trace}
    if "traceEvents" not in trace:
        raise ValueError(f"{path}: no traceEvents key")
    return trace


def validate_events(events: List[dict]) -> List[str]:
    """Schema check: required keys, monotonic ts, paired B/E per thread.
    Returns a list of problems (empty == valid)."""
    problems: List[str] = []
    last_ts = None
    stacks: Dict[Any, List[str]] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if "name" not in ev or ph is None:
            problems.append(f"event {i}: missing name/ph")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i} ({ev['name']}): missing ts")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i} ({ev['name']}): ts not monotonic "
                            f"({ts} < {last_ts})")
        last_ts = ts
        tid = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(tid, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.get(tid, [])
            if ev["name"] in stack:
                # pop through (tolerates interleaved-but-paired spans)
                while stack and stack[-1] != ev["name"]:
                    stack.pop()
                if stack:
                    stack.pop()
            else:
                problems.append(f"event {i}: E '{ev['name']}' without B "
                                f"on tid {tid}")
        elif ph not in ("C", "i", "X"):
            problems.append(f"event {i}: unknown ph {ph!r}")
    for tid, stack in stacks.items():
        for name in stack:
            problems.append(f"unclosed B '{name}' on tid {tid}")
    return problems


def span_totals(events: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per-name span aggregation from paired B/E events."""
    open_: Dict[Any, List[tuple]] = {}
    agg: Dict[str, Dict[str, float]] = {}
    for ev in events:
        ph = ev.get("ph")
        tid = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            open_.setdefault(tid, []).append((ev["name"], ev["ts"]))
        elif ph == "E":
            stack = open_.get(tid, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == ev["name"]:
                    name, t0 = stack.pop(i)
                    row = agg.setdefault(name, {"count": 0, "total_s": 0.0})
                    row["count"] += 1
                    row["total_s"] += (ev["ts"] - t0) / 1e6
                    break
    return agg


def counter_last(events: List[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for ev in events:
        if ev.get("ph") == "C" and ev.get("name") != "obs.round":
            v = (ev.get("args") or {}).get("value")
            if isinstance(v, (int, float)):
                out[ev["name"]] = float(v)
    return out


def round_records(events: List[dict]) -> List[dict]:
    return [dict(ev.get("args") or {}) for ev in events
            if ev.get("ph") == "C" and ev.get("name") == "obs.round"]


def measured_phase_seconds(events: List[dict]) -> Optional[Dict[str, float]]:
    """Measured per-phase device durations from the ``device.<p>_s``
    counters (the ``trace_device`` probe, obs/devicetime.py) — present
    only when the run opted into the out-of-band measurement.  Requires
    ALL four phases so the attribution never mixes measured and modeled
    weights."""
    counters = counter_last(events)
    out = {}
    for p, name in MEASURED_PHASE_COUNTERS.items():
        v = counters.get(name)
        if not isinstance(v, float) or v <= 0:
            return None
        out[p] = v
    return out


def phase_breakdown(events: List[dict],
                    spans: Optional[Dict[str, Dict[str, float]]] = None
                    ) -> Dict[str, Any]:
    """Per-phase seconds: staging measured from spans; device phases
    attributed from per-round wall-clock × on-device FLOP weights — or,
    when the trace carries the measured device-phase counters, × the
    MEASURED per-phase durations (proxy kept as fallback)."""
    spans = spans if spans is not None else span_totals(events)
    rounds = round_records(events)
    measured = measured_phase_seconds(events)
    phases = {p: 0.0 for p in PHASES}
    phases["staging"] = spans.get("staging", {}).get("total_s", 0.0)
    total_round_s = 0.0
    for rec in rounds:
        rt = float(rec.get("round_time_s", 0.0))
        total_round_s += rt
        if measured is not None:
            weights = [measured[p] for p in DEVICE_PHASES]
        else:
            weights = [max(float(rec.get(f"flops_{p}", 0.0)), 0.0)
                       for p in DEVICE_PHASES]
        wsum = sum(weights)
        if wsum <= 0:
            continue
        for p, w in zip(DEVICE_PHASES, weights):
            phases[p] += rt * (w / wsum)
    out = {
        "phases": {p: round(v, 6) for p, v in phases.items()},
        "rounds": len(rounds),
        "round_time_total_s": round(total_round_s, 6),
        "compile_s": round(spans.get("xla_compile", {}).get("total_s", 0.0),
                           6),
        "compile_count": int(spans.get("xla_compile", {}).get("count", 0)),
    }
    if measured is not None:
        out["device_phase_source"] = "measured"
        out["device_phases_measured_s"] = {p: round(v, 6)
                                           for p, v in measured.items()}
        # measured-vs-modeled share deltas: how far the FLOP proxy was off
        # (bench.py --trace archives these into the BENCH json)
        modeled = {p: 0.0 for p in DEVICE_PHASES}
        for rec in rounds:
            w = [max(float(rec.get(f"flops_{p}", 0.0)), 0.0)
                 for p in DEVICE_PHASES]
            ws = sum(w)
            if ws <= 0:
                continue
            for p, v in zip(DEVICE_PHASES, w):
                modeled[p] += v / ws
        n = max(len(rounds), 1)
        msum = sum(measured.values())
        out["device_phase_delta"] = {
            p: round(measured[p] / msum - modeled[p] / n, 6)
            for p in DEVICE_PHASES}
    return out


def summarize(trace: Dict[str, Any]) -> Dict[str, Any]:
    events = trace["traceEvents"]
    spans = span_totals(events)
    out = phase_breakdown(events, spans)
    out["spans"] = {n: {"count": int(r["count"]),
                        "total_s": round(r["total_s"], 6)}
                    for n, r in sorted(spans.items())}
    out["counters"] = counter_last(events)
    recs = round_records(events)
    if recs:
        out["update_norm_last"] = round(
            float(recs[-1].get("update_norm", 0.0)), 6)
        out["examples_total"] = round(
            sum(float(r.get("examples", 0.0)) for r in recs), 1)
        # low-precision collective layer (docs/COLLECTIVE_PRECISION.md):
        # modeled interconnect payload of the merge+broadcast collectives
        # and the quantization-residual norm the round carried on device
        cb = [float(r["collective_bytes"]) for r in recs
              if "collective_bytes" in r]
        if cb:
            out["collective_bytes_per_round"] = round(sum(cb) / len(cb), 1)
            out["collective_bytes_total"] = round(sum(cb), 1)
        # per-mesh-axis split (docs/MESH_2D.md, docs/PIPELINE.md):
        # merge/broadcast payload on ``client``, the pipeline permute +
        # flat-view traffic on ``stage`` (3-D layouts only), model-parallel
        # traffic on ``model``
        for axis in ("client", "stage", "model"):
            vals = [float(r[f"collective_bytes_{axis}"]) for r in recs
                    if f"collective_bytes_{axis}" in r]
            if vals:
                out[f"collective_bytes_{axis}_per_round"] = round(
                    sum(vals) / len(vals), 1)
        qe = [float(r["quant_error_norm"]) for r in recs
              if "quant_error_norm" in r]
        if qe:
            out["quant_error_norm_last"] = round(qe[-1], 6)
        # vmapped experiment population (docs/PRIMITIVES.md): per-member
        # loss envelope of the (P,)-stacked ObsCarry, plus the pinned
        # bytes-identical-across-members invariant (a nonzero spread means
        # members traced different programs)
        mem = [r for r in recs if "members" in r]
        if mem:
            out["population_members"] = int(float(mem[-1]["members"]))
            out["member_loss_best_last"] = round(
                float(mem[-1]["member_loss_best"]), 6)
            out["member_loss_worst_last"] = round(
                float(mem[-1]["member_loss_worst"]), 6)
            out["member_bytes_spread_max"] = round(
                max(float(r.get("member_bytes_spread", 0.0)) for r in mem),
                6)
    # paged client-state store (fedstore, docs/CLIENT_STORE.md): the
    # host-plane paging counters the store/pager emit — cumulative bytes
    # paged in, the final prefetch hit rate, and the write-back lag
    # (write-backs still pending when the last gather ran)
    counters = out["counters"]
    if "store.page_in_bytes" in counters:
        out["page_in_bytes"] = counters["store.page_in_bytes"]
    if "store.page_hit_rate" in counters:
        out["page_hit_rate"] = round(counters["store.page_hit_rate"], 6)
    if "store.writeback_lag_rounds" in counters:
        out["writeback_lag_rounds"] = counters["store.writeback_lag_rounds"]
    # buffered-async plane (fedbuff, docs/ASYNC.md): last-apply buffer
    # occupancy, the per-apply staleness envelope, and the cumulative
    # dropped-update count the engine emits at every buffer apply
    if "async.buffer_occupancy" in counters:
        out["buffer_occupancy_last"] = counters["async.buffer_occupancy"]
    if "async.staleness_p50" in counters:
        out["staleness_p50"] = round(counters["async.staleness_p50"], 6)
    if "async.staleness_p99" in counters:
        out["staleness_p99"] = round(counters["async.staleness_p99"], 6)
    if "async.updates_dropped" in counters:
        out["async_updates_dropped"] = counters["async.updates_dropped"]
    if "async.sim_time_s" in counters:
        out["async_sim_time_s"] = round(counters["async.sim_time_s"], 6)
    # fedguard fault-tolerance plane (docs/FAULT_TOLERANCE.md): retry
    # totals of the reliable-delivery layer, the per-round quorum
    # trajectory (every comm.quorum_size sample, in order — the shape of
    # a chaos run: full, then degraded, then healed), and the lease-dead
    # rank gauge
    if "comm.retries" in counters:
        out["comm_retries_total"] = counters["comm.retries"]
    if "comm.retry_rate" in counters:
        out["comm_retry_rate_last"] = round(counters["comm.retry_rate"], 6)
    if "comm.retry_exhausted" in counters:
        out["comm_retry_exhausted"] = counters["comm.retry_exhausted"]
    if "comm.ack_rtt" in counters:
        out["comm_ack_rtt_last_s"] = round(counters["comm.ack_rtt"], 6)
    quorum_traj = [int(e["args"]["value"]) for e in events
                   if e.get("ph") == "C"
                   and e.get("name") == "comm.quorum_size"]
    if quorum_traj:
        out["quorum_trajectory"] = quorum_traj
        out["quorum_size_last"] = quorum_traj[-1]
        out["quorum_size_min"] = min(quorum_traj)
    if "comm.dead_ranks" in counters:
        out["dead_ranks_last"] = counters["comm.dead_ranks"]
    if "comm.dup_dropped" in counters:
        out["comm_dup_dropped"] = counters["comm.dup_dropped"]
    # fedwire quantized wire plane (docs/WIRE.md): cumulative encoded
    # payload bytes, the codec's byte-model prediction, the last EF
    # residual norm, chunk-frame totals — and the headline
    # ``wire_bytes_ratio``: measured silo<->server wire bytes over the
    # modeled census.  ~1.0x (framing overhead only) proves the census
    # math IS what the wire carries; a tolerance band pins it in tests.
    if "wire.bytes" in counters:
        out["wire_bytes_total"] = counters["wire.bytes"]
    if "wire.modeled_bytes" in counters:
        out["wire_modeled_bytes_total"] = counters["wire.modeled_bytes"]
        measured = counters.get("comm.bytes.silo_server")
        if measured:
            out["wire_bytes_ratio"] = round(
                float(measured) / float(counters["wire.modeled_bytes"]), 6)
    if "wire.ef_norm" in counters:
        out["wire_ef_norm_last"] = round(counters["wire.ef_norm"], 6)
    if "comm.chunks_sent" in counters:
        out["comm_chunks_sent"] = counters["comm.chunks_sent"]
    # multi-tenant serving plane (docs/SERVING.md): admission spans and
    # the batching engine's host counters — admission-queue depth,
    # windowed tokens/s, and per-adapter request counts ("base" is
    # adapterless traffic on the zero bank row)
    if "serve.admit" in out["spans"]:
        out["serve_admits"] = out["spans"]["serve.admit"]["count"]
    if "serve.queue_depth" in counters:
        out["serve_queue_depth_last"] = counters["serve.queue_depth"]
    if "serve.tokens_per_s" in counters:
        out["serve_tokens_per_s_last"] = round(
            counters["serve.tokens_per_s"], 6)
    if "serve.tokens_total" in counters:
        out["serve_tokens_total"] = counters["serve.tokens_total"]
    # paged-KV memory plane (docs/SERVING.md): pool headroom at trace
    # end, the cumulative prefix page-share rate, chunked-prefill volume,
    # and the adapter HBM-cache hit/miss/eviction counters of store-mode
    # engines — the knobs' feedback loop (resize kv_pool_pages /
    # adapter_cache_slots on these)
    if "serve.kv_pages_free" in counters:
        out["serve_kv_pages_free_last"] = counters["serve.kv_pages_free"]
    if "serve.kv_page_hit_rate" in counters:
        out["serve_kv_page_hit_rate"] = round(
            counters["serve.kv_page_hit_rate"], 6)
    if "serve.prefill_chunks" in counters:
        out["serve_prefill_chunks"] = counters["serve.prefill_chunks"]
    if "serve.adapter_cache_hits" in counters:
        out["serve_adapter_cache"] = {
            "hits": counters["serve.adapter_cache_hits"],
            "misses": counters.get("serve.adapter_cache_misses", 0),
            "evictions": counters.get("serve.adapter_cache_evictions", 0),
        }
    if "serve.adapter_miss_rate" in counters:
        out["serve_adapter_miss_rate_last"] = round(
            counters["serve.adapter_miss_rate"], 6)
    # per-adapter request counts: the bounded-label counter (ONE metric,
    # ``adapter`` arg, capped at top-K + "other") is authoritative; the
    # deprecated per-adapter metric NAMES (serve.requests.<name>, behind
    # FEDML_SERVE_LEGACY_ADAPTER_COUNTERS for one release) merge in by
    # max so a flag-on trace doesn't double count
    adapter_reqs: Dict[str, int] = {}
    for e in events:
        if (e.get("ph") == "C"
                and e.get("name") == "serve.requests_by_adapter"):
            a = e.get("args") or {}
            if "adapter" in a:
                adapter_reqs[str(a["adapter"])] = int(a["value"])
    for k, v in counters.items():
        if k.startswith("serve.requests."):
            name = k[len("serve.requests."):]
            adapter_reqs[name] = max(adapter_reqs.get(name, 0), int(v))
    if adapter_reqs:
        out["serve_adapter_requests"] = adapter_reqs
        total_req = sum(adapter_reqs.values())
        if total_req:
            out["serve_adapter_shares"] = {
                k: round(v / total_req, 6)
                for k, v in sorted(adapter_reqs.items())}
    # fedslo request lifecycle (docs/OBSERVABILITY.md): each finished
    # request's serve.request span carries its full host-clock phase
    # breakdown in the B-event args, so the percentiles here are exact
    # over the trace's requests (hand-checkable against the mini-trace
    # golden), not bucket estimates
    req_args = [e.get("args") or {} for e in events
                if e.get("ph") == "B" and e.get("name") == "serve.request"]
    if req_args:
        out["serve_requests"] = len(req_args)

        def _vals(key):
            return sorted(float(a[key]) for a in req_args if key in a)

        def _pct(vals, q):
            # linear interpolation between closest ranks (numpy default)
            if not vals:
                return None
            pos = (len(vals) - 1) * q
            lo = int(pos)
            hi = min(lo + 1, len(vals) - 1)
            return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)

        ttft, e2e, qw = _vals("ttft_s"), _vals("e2e_s"), _vals("queue_s")
        if ttft:
            out["serve_ttft_p50"] = round(_pct(ttft, 0.50), 6)
            out["serve_ttft_p99"] = round(_pct(ttft, 0.99), 6)
        if e2e:
            out["serve_e2e_p99"] = round(_pct(e2e, 0.99), 6)
        if qw:
            out["serve_queue_wait_p99"] = round(_pct(qw, 0.99), 6)
        e2e_total = sum(e2e)
        if e2e_total > 0:
            out["serve_phase_breakdown"] = {
                ph: round(sum(float(a.get(f"{ph}_s", 0.0))
                              for a in req_args) / e2e_total, 6)
                for ph in ("queue", "prefill", "decode")}
        drafts = sum(int(a.get("drafts_proposed", 0)) for a in req_args)
        if drafts:
            out["serve_drafts_proposed"] = drafts
            out["serve_drafts_accepted"] = sum(
                int(a.get("drafts_accepted", 0)) for a in req_args)
    return out


def diff(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    sa, sb = summarize(a), summarize(b)
    out: Dict[str, Any] = {"a_rounds": sa["rounds"], "b_rounds": sb["rounds"],
                           "phases": {}}
    for p in PHASES:
        va, vb = sa["phases"][p], sb["phases"][p]
        na = va / max(sa["rounds"], 1)
        nb = vb / max(sb["rounds"], 1)
        out["phases"][p] = {
            "a_s": round(va, 6), "b_s": round(vb, 6),
            "a_s_per_round": round(na, 6), "b_s_per_round": round(nb, 6),
            "b_vs_a": round(nb / na, 3) if na > 0 else None,
        }
    ra = sa["round_time_total_s"] / max(sa["rounds"], 1)
    rb = sb["round_time_total_s"] / max(sb["rounds"], 1)
    out["round_s_per_round"] = {"a": round(ra, 6), "b": round(rb, 6),
                                "b_vs_a": round(rb / ra, 3) if ra > 0
                                else None}
    return out


# ---------------------------------------------------------------------------
# fedscope: multi-process merge (clock alignment) + critical path + regress
# ---------------------------------------------------------------------------

def _proc_meta(trace: Dict[str, Any], idx: int) -> Dict[str, Any]:
    od = trace.get("otherData") or {}
    return {
        "host": od.get("host", f"host{idx}"),
        "pid": int(od.get("pid", idx)),
        "label": od.get("label") or f"proc{idx}",
        "origin_unix_us": float(od.get("origin_unix_us", 0.0)),
        "trace_id": od.get("trace_id"),
    }


def _comm_pairs(events_a: List[dict], events_b: List[dict]
                ) -> List[Tuple[float, float, str]]:
    """Matched (send_ts, recv_ts, direction) pairs between two processes'
    RAW (per-process clock) events, linked exactly by the propagated span
    ids: a ``comm.recv`` B event's ``parent_span`` names the sender's
    ``comm.send`` span id.  direction is "a2b" or "b2a"."""
    def sends(evs):
        return {e["args"]["span_id"]: e["ts"] for e in evs
                if e.get("ph") == "B" and e.get("name") == "comm.send"
                and isinstance(e.get("args"), dict)
                and "span_id" in e["args"]}

    def recvs(evs):
        return [(e["args"].get("parent_span"), e["ts"]) for e in evs
                if e.get("ph") == "B" and e.get("name") == "comm.recv"
                and isinstance(e.get("args"), dict)]

    pairs = []
    sa, sb = sends(events_a), sends(events_b)
    for parent, ts in recvs(events_b):
        if parent in sa:
            pairs.append((sa[parent], ts, "a2b"))
    for parent, ts in recvs(events_a):
        if parent in sb:
            pairs.append((sb[parent], ts, "b2a"))
    return pairs


def _handshake_offset(meta_ref, events_ref, meta_p, events_p
                      ) -> Tuple[float, str]:
    """Residual clock offset ``d`` (µs) to ADD to process p's unix-mapped
    timestamps so they line up with the reference process.

    NTP-style bound from message causality (send happens-before recv):
    for p→ref messages ``d ≤ recv_ref − send_p``; for ref→p messages
    ``d ≥ send_ref − recv_p``; both in unix µs after applying each
    process's own wall-clock anchor.  The midpoint of the feasible
    interval is the estimate; with traffic in only one direction the
    single bound is used; with none, the raw unix anchors stand."""
    pairs = _comm_pairs(events_p, events_ref)   # a=p, b=ref
    o_p, o_ref = meta_p["origin_unix_us"], meta_ref["origin_unix_us"]
    hi, lo = [], []
    for send_ts, recv_ts, direction in pairs:
        if direction == "a2b":      # p sent, ref received
            hi.append((recv_ts + o_ref) - (send_ts + o_p))
        else:                       # ref sent, p received
            lo.append((send_ts + o_ref) - (recv_ts + o_p))
    if hi and lo:
        return (max(lo) + min(hi)) / 2.0, "handshake"
    if hi:
        return min(hi), "one_way_upper"
    if lo:
        return max(lo), "one_way_lower"
    return 0.0, "unix_clock"


def merge(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge N per-process captures into one timeline.

    Process 0 of the input list is the clock reference (pass the server's
    trace first).  Every process's events are mapped to unix time via its
    exported ``origin_unix_us`` anchor, then refined by the handshake
    estimate above; pids are remapped to the input order so Perfetto
    shows one stable lane per process."""
    procs = []
    for i, tr in enumerate(traces):
        meta = _proc_meta(tr, i)
        evs = [e for e in tr["traceEvents"] if e.get("ph") != "M"]
        procs.append((meta, evs))
    ref_meta, ref_evs = procs[0]
    offsets, methods = [0.0], ["reference"]
    for meta, evs in procs[1:]:
        off, how = _handshake_offset(ref_meta, ref_evs, meta, evs)
        offsets.append(off)
        methods.append(how)

    # merged clock zero = earliest corrected event
    t0 = None
    for (meta, evs), off in zip(procs, offsets):
        for e in evs:
            t = e["ts"] + meta["origin_unix_us"] + off
            t0 = t if t0 is None or t < t0 else t0
    t0 = t0 or 0.0

    merged_events: List[dict] = []
    proc_rows = []
    for i, ((meta, evs), off) in enumerate(zip(procs, offsets)):
        merged_events.append({
            "name": "process_name", "ph": "M", "ts": 0.0, "pid": i,
            "tid": 0, "args": {"name": meta["label"]}})
        for e in evs:
            ne = dict(e)
            ne["ts"] = e["ts"] + meta["origin_unix_us"] + off - t0
            ne["pid"] = i
            merged_events.append(ne)
        proc_rows.append({"label": meta["label"], "host": meta["host"],
                          "pid": meta["pid"],
                          "offset_us": round(offsets[i], 3),
                          "offset_method": methods[i],
                          "trace_id": meta["trace_id"]})
    merged_events.sort(key=lambda e: (e.get("ph") != "M",
                                      e.get("ts", 0.0)))
    return {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "fedtrace merge",
                      "fedscope_merge": {"processes": proc_rows,
                                         "t0_unix_us": round(t0, 3)}},
    }


def _paired_spans(events: List[dict]) -> List[dict]:
    """Complete spans (B/E paired per pid+tid) with the B event's args."""
    open_: Dict[Any, List[dict]] = {}
    spans: List[dict] = []
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            open_.setdefault(key, []).append(ev)
        elif ph == "E":
            stack = open_.get(key, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i]["name"] == ev["name"]:
                    b = stack.pop(i)
                    spans.append({
                        "pid": ev.get("pid"), "tid": ev.get("tid"),
                        "name": ev["name"], "t0": b["ts"], "t1": ev["ts"],
                        "args": dict(b.get("args") or {})})
                    break
    return spans


def _proc_labels(trace: Dict[str, Any]) -> Dict[Any, str]:
    labels: Dict[Any, str] = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            labels[e.get("pid")] = (e.get("args") or {}).get(
                "name", str(e.get("pid")))
    return labels


def critical_path(trace: Dict[str, Any],
                  round_idx: Optional[int] = None) -> Dict[str, Any]:
    """Walk each round's span DAG on a merged timeline and name the chain
    that gated the round — phase × process — plus a per-process straggler
    ranking.

    Edges: (1) cross-process ``comm.recv → comm.send`` links from the
    propagated span ids; (2) same-process precedence inside the round
    (the latest span ending inside, or immediately before, the current
    one).  The walk starts at the round's last-finishing span (the server
    combine/round close) and repeatedly follows the predecessor with the
    latest end time — by construction the time-critical chain."""
    events = trace["traceEvents"]
    spans = _paired_spans(events)
    labels = _proc_labels(trace)
    by_id = {s["args"]["span_id"]: s for s in spans
             if "span_id" in s["args"]}

    all_rounds = sorted({int(s["args"]["round"]) for s in spans
                         if isinstance(s["args"].get("round"), (int, float))})
    if round_idx is not None:
        all_rounds = [r for r in all_rounds if r == int(round_idx)]

    def label(s):
        return labels.get(s["pid"], str(s["pid"]))

    out_rounds = []
    for r in all_rounds:
        rs = [s for s in spans if s["args"].get("round") == r]
        if not rs:
            continue
        # terminal = the round's completion span: prefer the driver's
        # "round" span (the combine tier's close); the post-round state
        # sync can land on a silo AFTER it, but that tail is bookkeeping,
        # not the gating chain
        round_spans = [s for s in rs if s["name"] == "round"]
        terminal = max(round_spans or rs, key=lambda s: s["t1"])
        chain, seen = [], set()
        cur = terminal
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            chain.append(cur)
            nxt = None
            parent = cur["args"].get("parent_span")
            if parent in by_id and id(by_id[parent]) not in seen:
                nxt = by_id[parent]
            else:
                # latest same-process round-r span ending inside cur …
                cands = [s for s in rs
                         if s["pid"] == cur["pid"] and id(s) not in seen
                         and cur["t0"] <= s["t1"] <= cur["t1"]]
                if not cands:
                    # … or immediately before it
                    cands = [s for s in rs
                             if s["pid"] == cur["pid"]
                             and id(s) not in seen and s["t1"] <= cur["t0"]]
                if cands:
                    nxt = max(cands, key=lambda s: s["t1"])
            cur = nxt
        chain_rows = [{
            "process": label(s), "name": s["name"],
            "start_s": round(s["t0"] / 1e6, 6),
            "end_s": round(s["t1"] / 1e6, 6),
            "dur_s": round((s["t1"] - s["t0"]) / 1e6, 6),
        } for s in chain]
        gating = next((row["process"] for row in chain_rows
                       if row["process"] != chain_rows[0]["process"]), None)
        # straggler ranking: when does each process finish its OWN
        # round-r work on the merged clock — comm.recv spans are excluded
        # (receiving the post-round sync is waiting, not working), and so
        # is the combine tier itself (it closes every round by
        # construction; the ranking is about who it WAITED for)
        finish: Dict[str, float] = {}
        for s in rs:
            if s["name"] == "comm.recv" or label(s) == label(terminal):
                continue
            lb = label(s)
            finish[lb] = max(finish.get(lb, s["t1"]), s["t1"])
        if not finish:      # single-process trace: rank everyone
            for s in rs:
                lb = label(s)
                finish[lb] = max(finish.get(lb, s["t1"]), s["t1"])
        fastest = min(finish.values())
        stragglers = sorted(
            ({"process": lb, "finish_s": round(t / 1e6, 6),
              "lag_s": round((t - fastest) / 1e6, 6)}
             for lb, t in finish.items()),
            key=lambda row: -row["finish_s"])
        out_rounds.append({"round": r, "chain": chain_rows,
                           "gating_process": gating,
                           "stragglers": stragglers})
    gate_counts: Dict[str, int] = {}
    for row in out_rounds:
        if row["gating_process"]:
            gate_counts[row["gating_process"]] = \
                gate_counts.get(row["gating_process"], 0) + 1
    overall = max(gate_counts, key=gate_counts.get) if gate_counts else None
    return {"rounds": out_rounds, "gating_process_overall": overall}


# -- fedmon offline health report --------------------------------------------

#: per-round fedmon counters replayed into trajectories by ``health``
HEALTH_SERIES = ("health.anomaly_rate", "health.flagged_total",
                 "health.drift_score", "health.round_time_s",
                 "health.staleness_p99")


def health_report(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Offline federation-health report from a captured trace.

    Replays the ``health.*`` counter stream the monitor emitted at every
    verdict (one sample per observed round) plus the ``health.flag``
    events naming each newly flagged client — no jax, no re-detection:
    the report renders what the live monitor concluded, so a silo's
    post-mortem matches what ``/healthz`` served at the time."""
    events = trace["traceEvents"]
    series: Dict[str, List[float]] = {name: [] for name in HEALTH_SERIES}
    flags: List[dict] = []
    for ev in events:
        if ev.get("ph") != "C":
            continue
        name = ev.get("name")
        args = ev.get("args") or {}
        if name in series:
            v = args.get("value")
            if isinstance(v, (int, float)):
                series[name].append(float(v))
        elif name == "health.flag":
            flags.append({k: args[k] for k in
                          ("client", "round", "score", "reason",
                           "staleness") if k in args})
    spans = span_totals(events)
    verdicts = spans.get("health.verdict", {"count": 0, "total_s": 0.0})
    if not (int(verdicts["count"]) or flags
            or any(series[s] for s in series)):
        raise ValueError("trace carries no fedmon events (run with "
                         "health: true + trace: true)")
    out: Dict[str, Any] = {
        "rounds_observed": int(verdicts["count"]),
        "verdict_overhead_s": round(verdicts["total_s"], 6),
        "flags": flags,
        "flagged_clients": sorted({int(f["client"]) for f in flags
                                   if "client" in f}),
    }
    for name, vals in series.items():
        key = name.split(".", 1)[1]
        if vals:
            out[f"{key}_last"] = round(vals[-1], 6)
            out[f"{key}_max"] = round(max(vals), 6)
    return out


def _render_health(h: Dict[str, Any]) -> str:
    lines = [f"rounds observed: {h['rounds_observed']}   "
             f"anomaly rate (last/max): "
             f"{h.get('anomaly_rate_last', 0.0):g}/"
             f"{h.get('anomaly_rate_max', 0.0):g}   "
             f"drift (last/max): {h.get('drift_score_last', 0.0):g}/"
             f"{h.get('drift_score_max', 0.0):g}"]
    if "round_time_s_last" in h:
        lines.append(f"round time (last/max): "
                     f"{h['round_time_s_last']:g}s/"
                     f"{h['round_time_s_max']:g}s")
    if "staleness_p99_last" in h:
        lines.append(f"staleness p99 (last/max): "
                     f"{h['staleness_p99_last']:g}/"
                     f"{h['staleness_p99_max']:g}")
    lines.append(f"flagged clients: {len(h['flagged_clients'])}")
    for f in h["flags"]:
        lines.append(f"  client {f.get('client', '?'):>8}  "
                     f"round {f.get('round', '?'):>5}  "
                     f"score {f.get('score', 0.0):>8.2f}  "
                     f"{f.get('reason', '-')}")
    return "\n".join(lines)


# -- perf-regression gate ----------------------------------------------------

DEFAULT_BANDS_FILE = "BENCH_TOLERANCES.json"


def _dig(obj: Any, path: str) -> Optional[float]:
    cur = obj
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def regress(current: Dict[str, Any], bands: List[Dict[str, Any]],
            trajectory: List[Tuple[str, Dict[str, Any]]]
            ) -> Dict[str, Any]:
    """Compare ``current`` (one bench row) against the committed BENCH
    trajectory under per-metric tolerance bands.

    Each band: ``{"metric": dotted.path, "files": glob,
    "direction": "lower"|"higher", "rel_tol": float,
    "mode": "best"|"last"}``.  A band applies only when the current row
    CARRIES the metric (rows of different archetypes skip each other's
    bands).  Baseline = best (default) or most recent committed value
    among trajectory files matching the glob."""
    results, regressions = [], []
    for band in bands:
        metric = band["metric"]
        cur = _dig(current, metric)
        if cur is None:
            results.append({"metric": metric, "status": "skipped",
                            "reason": "metric absent from current row"})
            continue
        direction = band.get("direction", "lower")
        rel_tol = float(band.get("rel_tol", 0.2))
        mode = band.get("mode", "best")
        pat = band.get("files", "BENCH_r*.json")
        vals = [(name, _dig(row, metric)) for name, row in trajectory
                if fnmatch.fnmatch(os.path.basename(name), pat)]
        vals = [(n, v) for n, v in vals if v is not None]
        if not vals:
            results.append({"metric": metric, "status": "skipped",
                            "reason": f"no committed row matches "
                                      f"{pat!r} with this metric"})
            continue
        if mode == "last":
            base_name, base = vals[-1]
        elif direction == "higher":
            base_name, base = max(vals, key=lambda nv: nv[1])
        else:
            base_name, base = min(vals, key=lambda nv: nv[1])
        if direction == "higher":
            bound = base * (1.0 - rel_tol)
            ok = cur >= bound
        else:
            bound = base * (1.0 + rel_tol)
            ok = cur <= bound
        row = {"metric": metric, "status": "ok" if ok else "REGRESSION",
               "current": cur, "baseline": base,
               "baseline_file": os.path.basename(base_name),
               "bound": round(bound, 6), "direction": direction,
               "rel_tol": rel_tol}
        results.append(row)
        if not ok:
            regressions.append(row)
    return {"checked": sum(1 for r in results if r["status"] != "skipped"),
            "results": results, "regressions": regressions,
            "ok": not regressions}


def load_bands(path: str) -> List[Dict[str, Any]]:
    with open(path) as fh:
        data = json.load(fh)
    bands = data["bands"] if isinstance(data, dict) else data
    if not isinstance(bands, list):
        raise ValueError(f"{path}: expected a list (or {{'bands': [...]}})")
    return bands


def load_trajectory(baseline_dir: str
                    ) -> List[Tuple[str, Dict[str, Any]]]:
    rows = []
    for name in sorted(glob_mod.glob(
            os.path.join(baseline_dir, "BENCH_r*.json"))):
        try:
            with open(name) as fh:
                rows.append((name, json.load(fh)))
        except (OSError, json.JSONDecodeError):
            continue
    return rows


def _render_summary(s: Dict[str, Any]) -> str:
    lines = [f"rounds: {s['rounds']}   "
             f"round wall-clock: {s['round_time_total_s']:.4f}s   "
             f"compiles: {s['compile_count']} ({s['compile_s']:.2f}s)"]
    if "collective_bytes_per_round" in s:
        axis = ""
        if "collective_bytes_client_per_round" in s:
            stage = ""
            if s.get("collective_bytes_stage_per_round", 0.0):
                stage = (f" + stage "
                         f"{s['collective_bytes_stage_per_round']:.0f}")
            axis = (f" (client "
                    f"{s['collective_bytes_client_per_round']:.0f}"
                    f"{stage} + model "
                    f"{s.get('collective_bytes_model_per_round', 0.0):.0f})")
        lines.append(
            f"collective bytes/round: "
            f"{s['collective_bytes_per_round']:.0f}{axis}   "
            f"quant error norm (last): "
            f"{s.get('quant_error_norm_last', 0.0):g}")
    if "population_members" in s:
        lines.append(
            f"population: {s['population_members']} members   "
            f"member loss best/worst (last): "
            f"{s['member_loss_best_last']:g}/"
            f"{s['member_loss_worst_last']:g}   "
            f"bytes spread: {s['member_bytes_spread_max']:g}")
    if "page_in_bytes" in s or "page_hit_rate" in s:
        lines.append(
            f"store paging: {s.get('page_in_bytes', 0.0):.0f} B paged in   "
            f"hit rate {s.get('page_hit_rate', 0.0):g}   "
            f"writeback lag {s.get('writeback_lag_rounds', 0.0):g} rounds")
    if "buffer_occupancy_last" in s:
        lines.append(
            f"async buffer: occupancy (last) "
            f"{s['buffer_occupancy_last']:g}   staleness p50/p99 "
            f"{s.get('staleness_p50', 0.0):g}/"
            f"{s.get('staleness_p99', 0.0):g}   dropped "
            f"{s.get('async_updates_dropped', 0.0):g}   sim clock "
            f"{s.get('async_sim_time_s', 0.0):g}s")
    if "comm_retries_total" in s or "quorum_trajectory" in s:
        traj = s.get("quorum_trajectory", [])
        lines.append(
            f"fedguard: {s.get('comm_retries_total', 0.0):g} retries "
            f"(rate {s.get('comm_retry_rate_last', 0.0):g})   quorum "
            f"{'-'.join(str(q) for q in traj) or '?'}   dead ranks "
            f"(last) {s.get('dead_ranks_last', 0.0):g}   deduped "
            f"{s.get('comm_dup_dropped', 0.0):g}")
    if "serve_admits" in s or "serve_adapter_requests" in s:
        ad = s.get("serve_adapter_requests", {})
        lines.append(
            f"serving: {s.get('serve_admits', 0)} admits   "
            f"queue depth (last) {s.get('serve_queue_depth_last', 0.0):g}   "
            f"tokens/s (last) {s.get('serve_tokens_per_s_last', 0.0):g}   "
            f"{len(ad)} adapters / {sum(ad.values())} requests")
    if "serve_requests" in s:
        pb = s.get("serve_phase_breakdown", {})
        lines.append(
            f"serve slo: {s['serve_requests']} requests   ttft p50/p99 "
            f"{s.get('serve_ttft_p50', 0.0):g}/"
            f"{s.get('serve_ttft_p99', 0.0):g}s   e2e p99 "
            f"{s.get('serve_e2e_p99', 0.0):g}s   queue p99 "
            f"{s.get('serve_queue_wait_p99', 0.0):g}s   phases "
            + "/".join(f"{p} {pb.get(p, 0.0):.0%}"
                       for p in ("queue", "prefill", "decode")))
    if s.get("device_phase_source") == "measured":
        lines.append("device phases: MEASURED (trace_device probe; "
                     "FLOP proxy deltas "
                     + ", ".join(f"{p} {d:+.3f}"
                                 for p, d in s["device_phase_delta"]
                                 .items()) + ")")
    lines.append(f"{'phase':<16}{'seconds':>12}{'share':>9}")
    total = sum(s["phases"].values()) or 1.0
    for p in PHASES:
        v = s["phases"][p]
        lines.append(f"{p:<16}{v:>12.4f}{100.0 * v / total:>8.1f}%")
    if s.get("spans"):
        lines.append("spans:")
        for n, row in s["spans"].items():
            lines.append(f"  {n:<22}x{row['count']:<6}"
                         f"{row['total_s']:.4f}s")
    return "\n".join(lines)


def _render_diff(d: Dict[str, Any]) -> str:
    lines = [f"{'phase':<16}{'A s/round':>12}{'B s/round':>12}{'B/A':>8}"]
    for p in PHASES:
        row = d["phases"][p]
        ratio = row["b_vs_a"]
        lines.append(f"{p:<16}{row['a_s_per_round']:>12.5f}"
                     f"{row['b_s_per_round']:>12.5f}"
                     f"{ratio if ratio is not None else '-':>8}")
    r = d["round_s_per_round"]
    lines.append(f"{'round (total)':<16}{r['a']:>12.5f}{r['b']:>12.5f}"
                 f"{r['b_vs_a'] if r['b_vs_a'] is not None else '-':>8}")
    return "\n".join(lines)


def _render_critical_path(cp: Dict[str, Any]) -> str:
    lines = []
    for row in cp["rounds"]:
        lines.append(f"round {row['round']}: gated by "
                     f"{row['gating_process'] or '(single process)'}")
        for link in row["chain"]:
            lines.append(f"  <- {link['process']:<10}{link['name']:<14}"
                         f"{link['dur_s']:>10.4f}s  "
                         f"(ends {link['end_s']:.4f}s)")
        lines.append("  stragglers: " + "  ".join(
            f"{s['process']}+{s['lag_s']:.4f}s"
            for s in row["stragglers"]))
    lines.append(f"gating process overall: "
                 f"{cp['gating_process_overall'] or '-'}")
    return "\n".join(lines)


def _render_regress(r: Dict[str, Any]) -> str:
    lines = [f"{'metric':<42}{'status':<12}{'current':>12}{'baseline':>12}"
             f"{'bound':>12}"]
    for row in r["results"]:
        if row["status"] == "skipped":
            lines.append(f"{row['metric']:<42}{'skipped':<12}  "
                         f"({row['reason']})")
        else:
            lines.append(f"{row['metric']:<42}{row['status']:<12}"
                         f"{row['current']:>12.4f}{row['baseline']:>12.4f}"
                         f"{row['bound']:>12.4f}")
    lines.append(f"{r['checked']} checked, {len(r['regressions'])} "
                 f"regression(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fedtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd")
    p_sum = sub.add_parser("summarize", help="per-phase breakdown of one "
                                             "trace")
    p_sum.add_argument("trace")
    p_sum.add_argument("--json", action="store_true")
    p_diff = sub.add_parser("diff", help="compare two traces per phase")
    p_diff.add_argument("trace_a")
    p_diff.add_argument("trace_b")
    p_diff.add_argument("--json", action="store_true")
    p_merge = sub.add_parser(
        "merge", help="align N per-process captures into one timeline "
                      "(pass the server's trace first — it is the clock "
                      "reference)")
    p_merge.add_argument("traces", nargs="+")
    p_merge.add_argument("--out", required=True)
    p_merge.add_argument("--json", action="store_true")
    p_cp = sub.add_parser(
        "critical-path", help="per-round gating chain + straggler "
                              "ranking of a merged timeline")
    p_cp.add_argument("trace")
    p_cp.add_argument("--round", type=int, default=None)
    p_cp.add_argument("--json", action="store_true")
    p_health = sub.add_parser(
        "health", help="offline fedmon federation-health report from a "
                       "captured trace")
    p_health.add_argument("trace")
    p_health.add_argument("--json", action="store_true")
    p_reg = sub.add_parser(
        "regress", help="tolerance-band gate of a bench row vs the "
                        "committed BENCH_r*.json trajectory (exit 3 on "
                        "regression)")
    p_reg.add_argument("current")
    p_reg.add_argument("--bands", default=None)
    p_reg.add_argument("--baseline-dir", default=None)
    p_reg.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    if args.cmd is None:
        parser.print_usage(sys.stderr)
        return 2
    try:
        if args.cmd == "summarize":
            s = summarize(load_trace(args.trace))
            print(json.dumps(s) if args.json else _render_summary(s))
        elif args.cmd == "diff":
            d = diff(load_trace(args.trace_a), load_trace(args.trace_b))
            print(json.dumps(d) if args.json else _render_diff(d))
        elif args.cmd == "merge":
            merged = merge([load_trace(p) for p in args.traces])
            with open(args.out, "w") as fh:
                json.dump(merged, fh)
            info = merged["otherData"]["fedscope_merge"]
            if args.json:
                print(json.dumps(info))
            else:
                for row in info["processes"]:
                    print(f"{row['label']:<12}{row['host']}:{row['pid']}"
                          f"  offset {row['offset_us']:+.1f}us "
                          f"({row['offset_method']})")
                print(f"wrote {args.out}")
        elif args.cmd == "critical-path":
            cp = critical_path(load_trace(args.trace),
                               round_idx=args.round)
            print(json.dumps(cp) if args.json else
                  _render_critical_path(cp))
        elif args.cmd == "health":
            h = health_report(load_trace(args.trace))
            print(json.dumps(h) if args.json else _render_health(h))
        else:  # regress
            base_dir = args.baseline_dir or os.path.dirname(
                os.path.abspath(args.current)) or "."
            bands_path = args.bands or os.path.join(base_dir,
                                                    DEFAULT_BANDS_FILE)
            with open(args.current) as fh:
                current = json.load(fh)
            r = regress(current, load_bands(bands_path),
                        load_trajectory(base_dir))
            print(json.dumps(r) if args.json else _render_regress(r))
            if not r["ok"]:
                return 3
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"fedtrace: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
