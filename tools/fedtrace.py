#!/usr/bin/env python
"""fedtrace — analyze fedml_tpu Chrome trace-event captures.

Pure stdlib (runs without jax installed, like ``tools/fedlint.py``):

- ``fedtrace.py summarize TRACE.json [--json]`` — span totals, counters,
  and the per-phase (staging / gather / client_steps / merge /
  server_update) round-time breakdown.
- ``fedtrace.py diff A.json B.json [--json]`` — per-phase comparison of
  two traces (e.g. fused vs. unfused, or two commits).

Attribution model (docs/OBSERVABILITY.md): ``staging`` is measured
directly from host spans; the four device phases are apportioned from
each round's measured wall-clock (the ``obs.round`` counter's
``round_time_s``) proportionally to the per-phase FLOP weights the
compiled round records on device (``ObsCarry.phase_flops``) — the device
side of a fused ``jit(lax.scan(round))`` dispatch cannot be host-timed
per phase without breaking the zero-sync contract, so the breakdown is a
flop-weighted attribution, not a per-phase stopwatch.

Exit codes: 0 ok, 1 malformed trace, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

DEVICE_PHASES = ("gather", "client_steps", "merge", "server_update")
PHASES = ("staging",) + DEVICE_PHASES


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        trace = json.load(fh)
    if isinstance(trace, list):  # bare-array Chrome format
        trace = {"traceEvents": trace}
    if "traceEvents" not in trace:
        raise ValueError(f"{path}: no traceEvents key")
    return trace


def validate_events(events: List[dict]) -> List[str]:
    """Schema check: required keys, monotonic ts, paired B/E per thread.
    Returns a list of problems (empty == valid)."""
    problems: List[str] = []
    last_ts = None
    stacks: Dict[Any, List[str]] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if "name" not in ev or ph is None:
            problems.append(f"event {i}: missing name/ph")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i} ({ev['name']}): missing ts")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i} ({ev['name']}): ts not monotonic "
                            f"({ts} < {last_ts})")
        last_ts = ts
        tid = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(tid, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.get(tid, [])
            if ev["name"] in stack:
                # pop through (tolerates interleaved-but-paired spans)
                while stack and stack[-1] != ev["name"]:
                    stack.pop()
                if stack:
                    stack.pop()
            else:
                problems.append(f"event {i}: E '{ev['name']}' without B "
                                f"on tid {tid}")
        elif ph not in ("C", "i", "X"):
            problems.append(f"event {i}: unknown ph {ph!r}")
    for tid, stack in stacks.items():
        for name in stack:
            problems.append(f"unclosed B '{name}' on tid {tid}")
    return problems


def span_totals(events: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per-name span aggregation from paired B/E events."""
    open_: Dict[Any, List[tuple]] = {}
    agg: Dict[str, Dict[str, float]] = {}
    for ev in events:
        ph = ev.get("ph")
        tid = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            open_.setdefault(tid, []).append((ev["name"], ev["ts"]))
        elif ph == "E":
            stack = open_.get(tid, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == ev["name"]:
                    name, t0 = stack.pop(i)
                    row = agg.setdefault(name, {"count": 0, "total_s": 0.0})
                    row["count"] += 1
                    row["total_s"] += (ev["ts"] - t0) / 1e6
                    break
    return agg


def counter_last(events: List[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for ev in events:
        if ev.get("ph") == "C" and ev.get("name") != "obs.round":
            v = (ev.get("args") or {}).get("value")
            if isinstance(v, (int, float)):
                out[ev["name"]] = float(v)
    return out


def round_records(events: List[dict]) -> List[dict]:
    return [dict(ev.get("args") or {}) for ev in events
            if ev.get("ph") == "C" and ev.get("name") == "obs.round"]


def phase_breakdown(events: List[dict],
                    spans: Optional[Dict[str, Dict[str, float]]] = None
                    ) -> Dict[str, Any]:
    """Per-phase seconds: staging measured from spans; device phases
    attributed from per-round wall-clock × on-device FLOP weights."""
    spans = spans if spans is not None else span_totals(events)
    rounds = round_records(events)
    phases = {p: 0.0 for p in PHASES}
    phases["staging"] = spans.get("staging", {}).get("total_s", 0.0)
    total_round_s = 0.0
    for rec in rounds:
        rt = float(rec.get("round_time_s", 0.0))
        total_round_s += rt
        weights = [max(float(rec.get(f"flops_{p}", 0.0)), 0.0)
                   for p in DEVICE_PHASES]
        wsum = sum(weights)
        if wsum <= 0:
            continue
        for p, w in zip(DEVICE_PHASES, weights):
            phases[p] += rt * (w / wsum)
    return {
        "phases": {p: round(v, 6) for p, v in phases.items()},
        "rounds": len(rounds),
        "round_time_total_s": round(total_round_s, 6),
        "compile_s": round(spans.get("xla_compile", {}).get("total_s", 0.0),
                           6),
        "compile_count": int(spans.get("xla_compile", {}).get("count", 0)),
    }


def summarize(trace: Dict[str, Any]) -> Dict[str, Any]:
    events = trace["traceEvents"]
    spans = span_totals(events)
    out = phase_breakdown(events, spans)
    out["spans"] = {n: {"count": int(r["count"]),
                        "total_s": round(r["total_s"], 6)}
                    for n, r in sorted(spans.items())}
    out["counters"] = counter_last(events)
    recs = round_records(events)
    if recs:
        out["update_norm_last"] = round(
            float(recs[-1].get("update_norm", 0.0)), 6)
        out["examples_total"] = round(
            sum(float(r.get("examples", 0.0)) for r in recs), 1)
        # low-precision collective layer (docs/COLLECTIVE_PRECISION.md):
        # modeled interconnect payload of the merge+broadcast collectives
        # and the quantization-residual norm the round carried on device
        cb = [float(r["collective_bytes"]) for r in recs
              if "collective_bytes" in r]
        if cb:
            out["collective_bytes_per_round"] = round(sum(cb) / len(cb), 1)
            out["collective_bytes_total"] = round(sum(cb), 1)
        # per-mesh-axis split (docs/MESH_2D.md): merge/broadcast payload on
        # the ``client`` axis vs model-parallel traffic on ``model`` (only
        # 2-D ``mesh_shape`` layouts report a nonzero model share)
        for axis in ("client", "model"):
            vals = [float(r[f"collective_bytes_{axis}"]) for r in recs
                    if f"collective_bytes_{axis}" in r]
            if vals:
                out[f"collective_bytes_{axis}_per_round"] = round(
                    sum(vals) / len(vals), 1)
        qe = [float(r["quant_error_norm"]) for r in recs
              if "quant_error_norm" in r]
        if qe:
            out["quant_error_norm_last"] = round(qe[-1], 6)
        # vmapped experiment population (docs/PRIMITIVES.md): per-member
        # loss envelope of the (P,)-stacked ObsCarry, plus the pinned
        # bytes-identical-across-members invariant (a nonzero spread means
        # members traced different programs)
        mem = [r for r in recs if "members" in r]
        if mem:
            out["population_members"] = int(float(mem[-1]["members"]))
            out["member_loss_best_last"] = round(
                float(mem[-1]["member_loss_best"]), 6)
            out["member_loss_worst_last"] = round(
                float(mem[-1]["member_loss_worst"]), 6)
            out["member_bytes_spread_max"] = round(
                max(float(r.get("member_bytes_spread", 0.0)) for r in mem),
                6)
    # paged client-state store (fedstore, docs/CLIENT_STORE.md): the
    # host-plane paging counters the store/pager emit — cumulative bytes
    # paged in, the final prefetch hit rate, and the write-back lag
    # (write-backs still pending when the last gather ran)
    counters = out["counters"]
    if "store.page_in_bytes" in counters:
        out["page_in_bytes"] = counters["store.page_in_bytes"]
    if "store.page_hit_rate" in counters:
        out["page_hit_rate"] = round(counters["store.page_hit_rate"], 6)
    if "store.writeback_lag_rounds" in counters:
        out["writeback_lag_rounds"] = counters["store.writeback_lag_rounds"]
    # multi-tenant serving plane (docs/SERVING.md): admission spans and
    # the batching engine's host counters — admission-queue depth,
    # windowed tokens/s, and per-adapter request counts ("base" is
    # adapterless traffic on the zero bank row)
    if "serve.admit" in out["spans"]:
        out["serve_admits"] = out["spans"]["serve.admit"]["count"]
    if "serve.queue_depth" in counters:
        out["serve_queue_depth_last"] = counters["serve.queue_depth"]
    if "serve.tokens_per_s" in counters:
        out["serve_tokens_per_s_last"] = round(
            counters["serve.tokens_per_s"], 6)
    if "serve.tokens_total" in counters:
        out["serve_tokens_total"] = counters["serve.tokens_total"]
    adapter_reqs = {k[len("serve.requests."):]: int(v)
                    for k, v in counters.items()
                    if k.startswith("serve.requests.")}
    if adapter_reqs:
        out["serve_adapter_requests"] = adapter_reqs
    return out


def diff(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    sa, sb = summarize(a), summarize(b)
    out: Dict[str, Any] = {"a_rounds": sa["rounds"], "b_rounds": sb["rounds"],
                           "phases": {}}
    for p in PHASES:
        va, vb = sa["phases"][p], sb["phases"][p]
        na = va / max(sa["rounds"], 1)
        nb = vb / max(sb["rounds"], 1)
        out["phases"][p] = {
            "a_s": round(va, 6), "b_s": round(vb, 6),
            "a_s_per_round": round(na, 6), "b_s_per_round": round(nb, 6),
            "b_vs_a": round(nb / na, 3) if na > 0 else None,
        }
    ra = sa["round_time_total_s"] / max(sa["rounds"], 1)
    rb = sb["round_time_total_s"] / max(sb["rounds"], 1)
    out["round_s_per_round"] = {"a": round(ra, 6), "b": round(rb, 6),
                                "b_vs_a": round(rb / ra, 3) if ra > 0
                                else None}
    return out


def _render_summary(s: Dict[str, Any]) -> str:
    lines = [f"rounds: {s['rounds']}   "
             f"round wall-clock: {s['round_time_total_s']:.4f}s   "
             f"compiles: {s['compile_count']} ({s['compile_s']:.2f}s)"]
    if "collective_bytes_per_round" in s:
        axis = ""
        if "collective_bytes_client_per_round" in s:
            axis = (f" (client "
                    f"{s['collective_bytes_client_per_round']:.0f}"
                    f" + model "
                    f"{s.get('collective_bytes_model_per_round', 0.0):.0f})")
        lines.append(
            f"collective bytes/round: "
            f"{s['collective_bytes_per_round']:.0f}{axis}   "
            f"quant error norm (last): "
            f"{s.get('quant_error_norm_last', 0.0):g}")
    if "population_members" in s:
        lines.append(
            f"population: {s['population_members']} members   "
            f"member loss best/worst (last): "
            f"{s['member_loss_best_last']:g}/"
            f"{s['member_loss_worst_last']:g}   "
            f"bytes spread: {s['member_bytes_spread_max']:g}")
    if "page_in_bytes" in s or "page_hit_rate" in s:
        lines.append(
            f"store paging: {s.get('page_in_bytes', 0.0):.0f} B paged in   "
            f"hit rate {s.get('page_hit_rate', 0.0):g}   "
            f"writeback lag {s.get('writeback_lag_rounds', 0.0):g} rounds")
    if "serve_admits" in s or "serve_adapter_requests" in s:
        ad = s.get("serve_adapter_requests", {})
        lines.append(
            f"serving: {s.get('serve_admits', 0)} admits   "
            f"queue depth (last) {s.get('serve_queue_depth_last', 0.0):g}   "
            f"tokens/s (last) {s.get('serve_tokens_per_s_last', 0.0):g}   "
            f"{len(ad)} adapters / {sum(ad.values())} requests")
    lines.append(f"{'phase':<16}{'seconds':>12}{'share':>9}")
    total = sum(s["phases"].values()) or 1.0
    for p in PHASES:
        v = s["phases"][p]
        lines.append(f"{p:<16}{v:>12.4f}{100.0 * v / total:>8.1f}%")
    if s.get("spans"):
        lines.append("spans:")
        for n, row in s["spans"].items():
            lines.append(f"  {n:<22}x{row['count']:<6}"
                         f"{row['total_s']:.4f}s")
    return "\n".join(lines)


def _render_diff(d: Dict[str, Any]) -> str:
    lines = [f"{'phase':<16}{'A s/round':>12}{'B s/round':>12}{'B/A':>8}"]
    for p in PHASES:
        row = d["phases"][p]
        ratio = row["b_vs_a"]
        lines.append(f"{p:<16}{row['a_s_per_round']:>12.5f}"
                     f"{row['b_s_per_round']:>12.5f}"
                     f"{ratio if ratio is not None else '-':>8}")
    r = d["round_s_per_round"]
    lines.append(f"{'round (total)':<16}{r['a']:>12.5f}{r['b']:>12.5f}"
                 f"{r['b_vs_a'] if r['b_vs_a'] is not None else '-':>8}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fedtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd")
    p_sum = sub.add_parser("summarize", help="per-phase breakdown of one "
                                             "trace")
    p_sum.add_argument("trace")
    p_sum.add_argument("--json", action="store_true")
    p_diff = sub.add_parser("diff", help="compare two traces per phase")
    p_diff.add_argument("trace_a")
    p_diff.add_argument("trace_b")
    p_diff.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    if args.cmd is None:
        parser.print_usage(sys.stderr)
        return 2
    try:
        if args.cmd == "summarize":
            s = summarize(load_trace(args.trace))
            print(json.dumps(s) if args.json else _render_summary(s))
        else:
            d = diff(load_trace(args.trace_a), load_trace(args.trace_b))
            print(json.dumps(d) if args.json else _render_diff(d))
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"fedtrace: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
