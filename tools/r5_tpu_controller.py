"""Round-5 TPU capture controller.

The 2026-08-01 live window produced TPU_BENCH_LIVE + TPU_LLM_SCALE, then
the serve bench timed out at 2400s and its kill left the tunnel wedged:
every later battery job silently fell back to CPU (the --attn artifact
said on_tpu=false).  This controller owns the remaining queue and fixes
both failure modes:

- gates EVERY job on an out-of-process liveness probe (tpu_watchdog's),
  re-polling when the tunnel wedges mid-queue;
- validates after each run that the artifact's own platform field says
  TPU — a cpu-fallback capture is treated as a failed attempt, never
  committed as evidence.

Run detached: nohup python tools/r5_tpu_controller.py > tools/controller_r5.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_watchdog import tpu_alive  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POLL_S = 300
MAX_ATTEMPTS = 3
RETRY_BACKOFF_S = 60  # between failed attempts on a LIVE tunnel: a job that
# crashes deterministically in seconds must not burn all MAX_ATTEMPTS
# instantly while DEADLINE_S still has hours left
DEADLINE_S = 8.5 * 3600  # leave the tail of the session for curation


def _last_json(path):
    try:
        with open(path) as f:
            payload = None
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        payload = json.loads(line)
                    except ValueError:
                        continue
            return payload or {}
    except OSError:
        return {}


def _on_tpu(d):
    vals = (d.get("platform"), d.get("device_kind"), d.get("on_tpu"))
    return any(v is True or (isinstance(v, str) and
                             ("tpu" in v.lower() or "axon" in v.lower()))
               for v in vals)


# (artifact, cmd, timeout_s, extra_env)
JOBS = [
    ("TPU_SERVE_BENCH.json", ["bench.py", "--serve"], 3600,
     {"FEDML_SERVE_QUICK": "1"}),
    ("TPU_ATTN_SWEEP.json", ["bench.py", "--attn"], 3600, {}),
    ("TPU_FLASH_TUNE.json", ["tools/tpu_flash_tune.py", "1", "2", "3",
                             "4", "5"], 3600, {}),
    ("TPU_LLM_ABLATE.json", ["bench.py", "--llm-ablate"], 4800, {}),
    ("TPU_LLM_7B_LAYER.json", ["tools/llm_scale_run.py", "--layer7b",
                               "--seq", "2048"], 3600,
     {"LLM_SCALE_TPU": "1"}),
]


def run_once(art, cmd, timeout_s, extra_env, attempt) -> bool:
    """Run one capture job.  The artifact at ``art`` is replaced ONLY by a
    validated TPU capture — failed/cpu-fallback/timeout attempts go to a
    side file, so a prior good capture (or an honest retraction stub)
    survives every failure mode."""
    env = dict(os.environ, **extra_env)
    side = os.path.join(REPO, "tools", "attempts",
                        f"{art}.attempt{attempt}")
    os.makedirs(os.path.dirname(side), exist_ok=True)
    print(f"[ctl] running {cmd} -> {art}", flush=True)
    try:
        r = subprocess.run([sys.executable] + cmd, cwd=REPO,
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired as e:
        partial = e.stdout or ""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        with open(side, "w") as f:
            f.write(json.dumps({"metric": "controller_timeout",
                                "value": None, "unit": None,
                                "vs_baseline": None, "cmd": cmd,
                                "timeout_s": timeout_s}) + "\n")
            f.write(partial[-8000:])
        print(f"[ctl] TIMEOUT {cmd} (partial stdout in {side})", flush=True)
        return False
    with open(side, "w") as f:
        f.write(r.stdout)
        if r.returncode != 0:
            f.write(f"\n[stderr tail]\n{r.stderr[-4000:]}\n[rc={r.returncode}]")
    payload = _last_json(side)
    ok = r.returncode == 0 and _on_tpu(payload)
    if ok:
        os.replace(side, os.path.join(REPO, art))
    print(f"[ctl] {art}: rc={r.returncode} on_tpu={_on_tpu(payload)} "
          f"ok={ok}", flush=True)
    return ok


def main():
    t0 = time.time()
    attempts = {art: 0 for art, *_ in JOBS}  # budget counter (refundable)
    # side-file naming uses a SEPARATE monotonic try counter: a refunded
    # budget attempt must not reuse its index and overwrite the prior
    # side file — that file is the evidence the scheme exists to preserve
    tries = {art: 0 for art, *_ in JOBS}
    pending = list(JOBS)
    while pending and time.time() - t0 < DEADLINE_S:
        art, cmd, timeout_s, extra_env = pending[0]
        if not tpu_alive():
            print(f"[ctl] tunnel wedged ({(time.time()-t0)/60:.0f} min in); "
                  f"sleep {POLL_S}s", flush=True)
            time.sleep(POLL_S)
            continue
        attempts[art] += 1
        tries[art] += 1
        if run_once(art, cmd, timeout_s, extra_env, tries[art]):
            pending.pop(0)
        elif not tpu_alive():
            # the tunnel wedged mid-job: that's the environment failing,
            # not the job — refund the attempt so a capture isn't
            # abandoned while DEADLINE_S still has hours left
            attempts[art] -= 1
            print(f"[ctl] {art}: failure coincides with a wedged tunnel; "
                  f"attempt refunded", flush=True)
        elif attempts[art] >= MAX_ATTEMPTS:
            print(f"[ctl] {art}: giving up after {attempts[art]} attempts",
                  flush=True)
            pending.pop(0)
        else:
            # live tunnel + failed job: back off so a fast-failing job
            # spreads its remaining attempts over the window instead of
            # burning them in seconds
            print(f"[ctl] {art}: attempt {attempts[art]} failed on a live "
                  f"tunnel; backoff {RETRY_BACKOFF_S}s", flush=True)
            time.sleep(RETRY_BACKOFF_S)
        # loop re-probes liveness before the next attempt either way
    print(f"[ctl] done; unfinished: {[a for a, *_ in pending]}", flush=True)


if __name__ == "__main__":
    main()
