#!/usr/bin/env python
"""fedlint CLI — JAX-aware static analysis over the federated hot paths.

Usage:
    python tools/fedlint.py fedml_tpu/                 # human output
    python tools/fedlint.py --json fedml_tpu/ tests/   # machine output
    python tools/fedlint.py --rules jit-host-sync,rng-key-reuse fedml_tpu/
    python tools/fedlint.py --severity pytree-order=error fedml_tpu/
    python tools/fedlint.py --list-rules

Exit codes: 0 = no unsuppressed errors; 1 = at least one unsuppressed
error (or any unsuppressed finding with --strict); 2 = usage error.

The analyzer itself (``fedml_tpu/analysis/fedlint.py``) is pure stdlib —
this wrapper loads it by file path so linting works on machines without
jax installed (CI lint shards, pre-commit hooks).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fedlint():
    """Load the analyzer module directly, bypassing fedml_tpu/__init__
    (which imports jax and initializes a backend)."""
    path = os.path.join(REPO, "fedml_tpu", "analysis", "fedlint.py")
    spec = importlib.util.spec_from_file_location("_fedlint_impl", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedlint", description="JAX-aware static analysis "
        "(jit boundaries, RNG discipline, collectives, donation)")
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON (includes suppressed)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--severity", action="append", default=[],
                    metavar="RULE=LEVEL",
                    help="override a rule's severity (error|warning); "
                         "repeatable")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    fl = _load_fedlint()

    if args.list_rules:
        for r in fl.RULES.values():
            print(f"{r.name:24s} [{r.severity}] {r.doc}")
        return 0

    if not args.paths:
        ap.print_usage(sys.stderr)
        print("fedlint: error: no paths given", file=sys.stderr)
        return 2

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(fl.RULES)
        if unknown:
            print(f"fedlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    overrides = {}
    for spec in args.severity:
        if "=" not in spec:
            print(f"fedlint: bad --severity {spec!r} (want RULE=LEVEL)",
                  file=sys.stderr)
            return 2
        rule, level = spec.split("=", 1)
        if rule not in fl.RULES or level not in (fl.ERROR, fl.WARNING):
            print(f"fedlint: bad --severity {spec!r}", file=sys.stderr)
            return 2
        overrides[rule] = level

    findings = fl.analyze_paths(args.paths, rules=rules,
                                severity_overrides=overrides)
    if args.as_json:
        print(fl.findings_to_json(findings))
    else:
        print(fl.render_findings(findings,
                                 show_suppressed=args.show_suppressed))
    return fl.exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
