"""RTT-injection serving harness (round-4 VERDICT item 4).

The round-3 TPU serve capture was dispatch-bound: every per-token program
launch paid the ~70 ms tunnel RTT, so the committed numbers measured the
tunnel, not the stack.  The levers built in round 3 (decode ``horizon``,
fused speculative draft rounds, continuous batching) all attack exactly
that: FEWER DISPATCHES PER TOKEN.  When the tunnel is wedged this harness
demonstrates them under *simulated* latency on CPU: every jitted dispatch
is wrapped with ``time.sleep(rtt)``, then tokens/sec is measured for

- ``seq_kv``      — single-request KV-cached decode: 1 dispatch / token
- ``batched_h1``  — 4-slot continuous batching, horizon 1:
                    1 dispatch / (up to 4) tokens
- ``batched_h8``  — horizon 8: 1 dispatch / (up to 32) tokens
- ``spec_fused``  — speculative batching, k=4: 1 fused dispatch advances
                    each slot up to k+1 tokens (draft+verify in ONE
                    program — the round-3 "k+1 -> 2 dispatches" fusion,
                    here 1 because the engine fuses both blocks)

Under dispatch-dominated latency the expected ordering is
``seq_kv < batched_h1 < batched_h8`` with ratios tracking the
tokens-per-dispatch arithmetic; the JSON records measured tok/s, measured
dispatch counts, and the per-lever amortization ratios.

Usage: python tools/serve_rtt_harness.py [--rtt-ms 70] [--tokens 48]
Writes SERVE_RTT_SIM.json at the repo root.

Reference bar: the serving/model_scheduler inference path
(/root/reference/python/fedml/serving/ + model_scheduler) has no analog
lever — it serves per-request eager torch.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("FEDML_TPU_PLATFORM") is None:
    os.environ["FEDML_TPU_PLATFORM"] = "cpu"   # tunnel discipline


def _sleepy(fn, rtt_s: float, counter: dict):
    """Wrap a jitted callable: one injected RTT per dispatch."""
    @functools.wraps(fn)
    def wrapped(*a, **kw):
        counter["dispatches"] += 1
        time.sleep(rtt_s)
        return fn(*a, **kw)
    return wrapped


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rtt-ms", type=float, default=70.0,
                    help="injected per-dispatch latency (the tunnel's ~70)")
    ap.add_argument("--tokens", type=int, default=48,
                    help="new tokens per request")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "SERVE_RTT_SIM.json"))
    args = ap.parse_args()
    rtt_s = args.rtt_ms / 1e3

    import jax
    import jax.numpy as jnp

    import fedml_tpu  # noqa: F401 (backend pin)
    from fedml_tpu.llm.model import LlamaConfig, LlamaLM
    from fedml_tpu.serving import batching as B
    from fedml_tpu.serving.templates import openai_compat as oc

    slots, buf_len, k = 4, 128, 4
    cfg = LlamaConfig(vocab_size=258, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=4, ffn_dim=128, max_seq_len=buf_len + k + 1,
                      dtype=jnp.float32, lora_rank=0)
    model = LlamaLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # tiny draft = same arch (the fusion lever, not draft quality, is
    # what the harness demonstrates)
    draft_cfg = LlamaConfig(vocab_size=258, dim=32, n_layers=1, n_heads=2,
                            n_kv_heads=2, ffn_dim=64,
                            max_seq_len=buf_len + k + 1,
                            dtype=jnp.float32, lora_rank=0)
    draft = LlamaLM(draft_cfg)
    draft_params = draft.init(jax.random.PRNGKey(1),
                              jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = [5, 17, 42, 9, 33, 7]
    n_req = slots  # one request per slot; engines admit all up front

    result = {"rtt_ms": args.rtt_ms, "tokens_per_request": args.tokens,
              "requests": n_req, "slots": slots, "levers": {}}

    # -- seq_kv: single-request cached decode, 1 dispatch/token -----------
    prefill, step, tail_blk = oc._build_cached_decode(model, 0, 1.0)
    # warm compiles OUTSIDE the injected-latency window
    ref = oc.generate(lambda p, t: model.apply({"params": p}, t), params,
                      prompt, max_new_tokens=args.tokens, buf_len=buf_len,
                      model=model)
    ctr = {"dispatches": 0}
    orig_build = oc._build_cached_decode
    oc._build_cached_decode = lambda m, tk, tp: (
        _sleepy(prefill, rtt_s, ctr), _sleepy(step, rtt_s, ctr),
        _sleepy(tail_blk, rtt_s, ctr))
    try:
        t0 = time.perf_counter()
        outs = [oc.generate(None, params, prompt,
                            max_new_tokens=args.tokens, buf_len=buf_len,
                            model=model) for _ in range(n_req)]
        dt = time.perf_counter() - t0
    finally:
        oc._build_cached_decode = orig_build
    n_tok = sum(len(o) for o in outs)
    assert all(o == ref for o in outs)
    result["levers"]["seq_kv"] = {
        "tok_s": round(n_tok / dt, 1), "dispatches": ctr["dispatches"],
        "tokens_per_dispatch": round(n_tok / ctr["dispatches"], 2)}

    # -- batched engines at horizon 1 and 8 --------------------------------
    for name, horizon in (("batched_h1", 1), ("batched_h8", 8)):
        eng = B.ContinuousBatchingEngine(model, params, slots=slots,
                                         buf_len=buf_len, horizon=horizon)
        try:
            qs = [eng.submit(prompt, max_new_tokens=args.tokens)
                  for _ in range(n_req)]  # warm-up tick compiles happen on
            outs = [[t for t in iter(q.get, None)] for q in qs]
            assert all(o == ref for o in outs), name
            ctr = {"dispatches": 0}
            eng._step = _sleepy(eng._step, rtt_s, ctr)
            qs = [eng.submit(prompt, max_new_tokens=args.tokens)
                  for _ in range(n_req)]
            t0 = time.perf_counter()
            outs = [[t for t in iter(q.get, None)] for q in qs]
            dt = time.perf_counter() - t0
        finally:
            eng.stop()
        n_tok = sum(len(o) for o in outs)
        assert all(o == ref for o in outs), name
        result["levers"][name] = {
            "tok_s": round(n_tok / dt, 1), "dispatches": ctr["dispatches"],
            "tokens_per_dispatch": round(n_tok / max(ctr["dispatches"], 1),
                                         2)}

    # -- fused speculative batching ----------------------------------------
    # two bounds: a random-init tiny draft (acceptance ~0 — the lever's
    # floor) and the target as its own draft (acceptance 1 — the ceiling,
    # k+1 tokens per fused dispatch; a TRAINED draft lands in between)
    for spec_name, d_model, d_params in (
            ("spec_fused_tinydraft", draft, draft_params),
            ("spec_fused_selfdraft", model, params)):
        _run_spec(B, spec_name, model, params, d_model, d_params, slots,
                  buf_len, k, prompt, args, rtt_s, ref, result)

    seq = result["levers"]["seq_kv"]["tok_s"]
    result.update({
        "metric": "serve_rtt_amortization",
        "value": round(result["levers"]["batched_h8"]["tok_s"] / seq, 2),
        "unit": f"x_vs_seq_kv_at_{args.rtt_ms:.0f}ms_rtt",
        "vs_baseline": None,
        "amortization": {n: round(v["tok_s"] / seq, 2)
                         for n, v in result["levers"].items()},
    })
    print(json.dumps(result))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)


def _run_spec(B, name, model, params, d_model, d_params, slots, buf_len, k,
              prompt, args, rtt_s, ref, result):
    n_req = slots
    eng = B.SpeculativeBatchingEngine(model, params, d_model, d_params,
                                      slots=slots, buf_len=buf_len, k=k)
    try:
        qs = [eng.submit(prompt, max_new_tokens=args.tokens)
              for _ in range(n_req)]
        outs = [[t for t in iter(q.get, None)] for q in qs]
        assert all(o == ref for o in outs), f"{name} warmup parity"
        ctr = {"dispatches": 0}
        eng._spec_tick = _sleepy(eng._spec_tick, rtt_s, ctr)
        qs = [eng.submit(prompt, max_new_tokens=args.tokens)
              for _ in range(n_req)]
        t0 = time.perf_counter()
        outs = [[t for t in iter(q.get, None)] for q in qs]
        dt = time.perf_counter() - t0
        stats = dict(eng.stats)
    finally:
        eng.stop()
    n_tok = sum(len(o) for o in outs)
    assert all(o == ref for o in outs), f"{name} parity under injection"
    result["levers"][name] = {
        "tok_s": round(n_tok / dt, 1), "dispatches": ctr["dispatches"],
        "tokens_per_dispatch": round(n_tok / max(ctr["dispatches"], 1), 2),
        "acceptance": round(stats.get("accepted", 0)
                            / max(stats.get("proposed", 1), 1), 3)}


if __name__ == "__main__":
    main()
