#!/usr/bin/env python
"""serve_load — closed-loop load harness for the multi-tenant serving
engine (ISSUE 9 / ROADMAP open item 4: the serving plane had never been
load-tested).

Drives a :class:`~fedml_tpu.serving.batching.ContinuousBatchingEngine` at
a **target RPS** with the traffic shape production LoRA serving actually
sees:

- **Poisson arrivals** at ``--rps`` (exponential inter-arrival gaps) —
  open-loop admission, so a saturated engine shows up as admission-queue
  depth and latency growth rather than a silently throttled driver;
- **heavy-tailed prompt lengths** (log-normal, clipped to the engine's
  buffer) — the short-request-behind-long-request case continuous
  batching exists for;
- **Zipf adapter popularity** over the registered adapters plus base
  traffic — a few hot cohorts, a long cold tail, every request landing
  on the ONE shared batched program.

Each request's **latency** is measured from its scheduled arrival to its
completion (so scheduler lag and queueing both count, like a client would
experience), **TTFT** to its first emitted token.  The report carries
p50/p99 of both, aggregate tokens/s, achieved admission RPS vs target,
and the admission-queue depth envelope — the numbers ``bench.py
--serve-mt`` folds into the BENCH json.

``--multi N`` (fedslo, docs/OBSERVABILITY.md) drives N independent
engine replicas, scrapes each one's live ``/metrics``, and merges the
native ``serve_ttft_seconds`` histograms by bucket addition into FLEET
percentiles — then cross-checks the bucket-estimated fleet p50/p99
against the harness's exact sample percentiles (must agree within one
bucket width, the merge-correctness canary for multi-replica scrapes).

Usage (self-contained tiny-model demo):
    python tools/serve_load.py [--rps 20] [--requests 64] [--adapters 8]
Writes SERVE_LOAD.json at the repo root; ``run_load`` / ``run_fleet``
are importable for driving any engine(s) in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("FEDML_TPU_PLATFORM") is None:
    os.environ["FEDML_TPU_PLATFORM"] = "cpu"   # tunnel discipline

# the traffic shapes are shared with the async arrival simulator
# (fedml_tpu/core/traffic.py, docs/ASYNC.md); zipf_weights stays re-exported
# here so `from serve_load import zipf_weights` keeps working
from fedml_tpu.core.traffic import (  # noqa: E402
    lognormal_sizes, poisson_arrivals, zipf_weights)


def _percentile(vals: Sequence[float], q: float) -> float:
    return float(np.percentile(np.asarray(vals, np.float64), q)) \
        if len(vals) else 0.0


def run_load(engine, *, target_rps: float, n_requests: int,
             adapters: Sequence[Optional[str]] = (None,),
             zipf_a: float = 1.2, prompt_len_mean: float = 8.0,
             prompt_len_sigma: float = 0.8, max_new_tokens: int = 16,
             vocab: int = 256, seed: int = 0,
             timeout_s: float = 300.0,
             scrape_url: Optional[str] = None,
             scrape_rel_tol: float = 0.6,
             keep_samples: bool = False) -> Dict:
    """Drive ``engine`` at ``target_rps`` and report the latency/throughput
    envelope.  ``adapters`` lists the routing choices in popularity order
    (``None`` = base traffic); the Zipf mix makes the first entries hot.

    ``scrape_url`` (fedmon, docs/OBSERVABILITY.md): a live ``/metrics``
    endpoint scraped MID-RUN (at ~60% of submissions, off the submit
    thread).  The report then cross-checks the engine's own gauges
    against this harness's independent measurements — ``serve.tokens_
    total`` must sit inside the run's token envelope, ``serve.tokens_
    per_s`` within ``scrape_rel_tol`` of the measured aggregate, and the
    queue-depth gauge inside the observed envelope — the silent-counter-
    drift canary (``report["scrape"]["ok"]``).

    The caller should warm the engine's compiled programs first (one
    request per distinct program) — this harness measures serving, not
    XLA compilation.
    """
    rng = np.random.default_rng(seed)
    arrival = poisson_arrivals(rng, target_rps, n_requests)
    weights = zipf_weights(len(adapters), zipf_a)
    choice = rng.choice(len(adapters), size=n_requests, p=weights)
    lens = lognormal_sizes(rng, prompt_len_mean, prompt_len_sigma,
                           n_requests,
                           hi=max(1, engine.buf_len - max_new_tokens - 1))
    prompts = [rng.integers(2, vocab, int(n)).tolist() for n in lens]

    lat: List[float] = [0.0] * n_requests
    ttft: List[float] = [0.0] * n_requests
    # first token since the actual submit call (the engine's own ttft
    # clock convention) — what histogram cross-checks compare against
    ttft_sub: List[float] = [0.0] * n_requests
    toks: List[int] = [0] * n_requests
    failed: List[int] = []
    queue_depths: List[int] = []
    lock = threading.Lock()
    # harness-side token clock (independent of the engine's counters):
    # every received token bumps it, so the scrape can measure ITS OWN
    # windowed tokens/s to compare against the engine's windowed gauge
    tok_clock = [0]

    def collect(i: int, q, t_sched: float, t_sub: float):
        first = None
        count = 0
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                t = q.get(timeout=max(deadline - time.monotonic(), 0.001))
            except Exception:  # queue.Empty — engine wedged
                with lock:
                    failed.append(i)
                return
            now = time.monotonic()
            if first is None:
                first = now
            if t is None:
                break
            count += 1
            with lock:
                tok_clock[0] += 1
        with lock:
            lat[i] = now - t_sched
            ttft[i] = first - t_sched
            ttft_sub[i] = first - t_sub
            toks[i] = count

    scrape: Dict[str, float] = {}

    def do_scrape():
        import urllib.request
        from fedml_tpu.obs.metricsd import (parse_prometheus_text,
                                            prom_value)
        url = scrape_url.rstrip("/")
        if not url.endswith("/metrics"):
            url += "/metrics"
        try:
            # harness-side windowed rate over the ~same window the
            # engine's serve.tokens_per_s gauge integrates (0.5s+),
            # measured from the independent token clock
            with lock:
                n0 = tok_clock[0]
            w0 = time.monotonic()
            time.sleep(0.8)
            with lock:
                n1 = tok_clock[0]
            w1 = time.monotonic()
            scrape["_harness_tokens_per_s"] = (n1 - n0) / max(w1 - w0,
                                                              1e-9)
            text = urllib.request.urlopen(url, timeout=10).read().decode()
            samples = parse_prometheus_text(text)
            for gauge in ("serve.tokens_per_s", "serve.tokens_total",
                          "serve.queue_depth"):
                v = prom_value(samples, "fedtrace_counter", name=gauge)
                if v is not None:
                    scrape[gauge] = v
            scrape["_t"] = time.monotonic()
        except Exception as e:   # a failed scrape is a result, not a crash
            scrape["_error"] = repr(e)  # type: ignore[assignment]

    threads = []
    t0 = time.monotonic()
    adapter_counts: Dict[str, int] = {}
    behind_s = 0.0
    scrape_at = max(1, int(0.6 * n_requests))
    scrape_thread = None
    for i in range(n_requests):
        t_sched = t0 + arrival[i]
        now = time.monotonic()
        if now < t_sched:
            time.sleep(t_sched - now)
        else:
            behind_s = max(behind_s, now - t_sched)
        name = adapters[int(choice[i])]
        adapter_counts[name or "base"] = \
            adapter_counts.get(name or "base", 0) + 1
        t_sub = time.monotonic()
        q = engine.submit(prompts[i], max_new_tokens=max_new_tokens,
                          adapter=name) if name is not None else \
            engine.submit(prompts[i], max_new_tokens=max_new_tokens)
        queue_depths.append(engine._waiting.qsize())
        if scrape_url and i == scrape_at:
            scrape_thread = threading.Thread(target=do_scrape, daemon=True)
            scrape_thread.start()
        th = threading.Thread(target=collect, args=(i, q, t_sched, t_sub),
                              daemon=True)
        th.start()
        threads.append(th)
    t_last_submit = time.monotonic()
    for th in threads:
        th.join(timeout=timeout_s)
    t_end = time.monotonic()
    if scrape_thread is not None:
        scrape_thread.join(timeout=30.0)

    ok = [i for i in range(n_requests) if i not in set(failed)]
    lat_ok = [lat[i] for i in ok]
    ttft_ok = [ttft[i] for i in ok]
    total_toks = sum(toks[i] for i in ok)
    makespan = max(t_end - t0, 1e-9)
    scrape_report = None
    if scrape_url:
        scrape_report = {"url": scrape_url}
        if "_error" in scrape:
            scrape_report.update(ok=False, error=scrape["_error"])
        elif not scrape:
            scrape_report.update(ok=False, error="scrape never ran "
                                 "(fewer submissions than scrape point?)")
        else:
            measured_tps = total_toks / makespan
            harness_tps = scrape.get("_harness_tokens_per_s", 0.0)
            gauge_tps = scrape.get("serve.tokens_per_s")
            gauge_total = scrape.get("serve.tokens_total")
            gauge_depth = scrape.get("serve.queue_depth")
            # like-for-like rate comparison: the engine gauge is a short
            # windowed rate, so compare it against the harness's OWN
            # windowed rate at scrape time; the bound allows rel_tol of
            # the larger rate plus a small absolute floor (window phase
            # offset between the two clocks)
            tps_bound = (scrape_rel_tol * max(harness_tps, gauge_tps or 0.0)
                         + 0.1 * max(measured_tps, 1.0))
            checks = {
                # mid-run cumulative total must sit inside [0, final]
                "tokens_total_in_envelope": (
                    gauge_total is None
                    or 0.0 <= gauge_total <= total_toks),
                "tokens_per_s_agree": (
                    gauge_tps is None or harness_tps <= 0
                    or abs(gauge_tps - harness_tps) <= tps_bound),
                # the gauge can never exceed the worst depth we saw
                "queue_depth_in_envelope": (
                    gauge_depth is None
                    or gauge_depth <= max(queue_depths, default=0) + 1),
            }
            scrape_report.update(
                ok=all(checks.values()), checks=checks,
                tokens_per_s_gauge=gauge_tps,
                tokens_per_s_harness_window=round(harness_tps, 1),
                tokens_per_s_measured=round(measured_tps, 1),
                tokens_total_gauge=gauge_total,
                queue_depth_gauge=gauge_depth,
                rel_tol=scrape_rel_tol)
    return {
        "target_rps": float(target_rps),
        "requests": n_requests,
        "completed": len(ok),
        "failed": len(failed),
        "achieved_admission_rps": round(
            n_requests / max(t_last_submit - t0, 1e-9), 2),
        "driver_max_lag_s": round(behind_s, 4),
        "latency_p50_ms": round(_percentile(lat_ok, 50) * 1e3, 2),
        "latency_p99_ms": round(_percentile(lat_ok, 99) * 1e3, 2),
        "ttft_p50_ms": round(_percentile(ttft_ok, 50) * 1e3, 2),
        "ttft_p99_ms": round(_percentile(ttft_ok, 99) * 1e3, 2),
        "tokens_total": int(total_toks),
        "tokens_per_s": round(total_toks / makespan, 1),
        "queue_depth_max": int(max(queue_depths, default=0)),
        "queue_depth_mean": round(float(np.mean(queue_depths))
                                  if queue_depths else 0.0, 2),
        "adapter_request_counts": adapter_counts,
        "prompt_len_mean_actual": round(float(np.mean(lens)), 1),
        "prompt_len_max_actual": int(np.max(lens)),
        "makespan_s": round(makespan, 3),
        **({"scrape": scrape_report} if scrape_report is not None else {}),
        # raw per-request samples for fleet-level exact percentiles
        # (run_fleet pops this before reporting)
        **({"_samples": {"ttft": ttft_ok,
                         "ttft_submit": [ttft_sub[i] for i in ok],
                         "latency": lat_ok}}
           if keep_samples else {}),
    }


def merge_fleet_histograms(texts: Sequence[str],
                           metric: str = "serve_ttft_seconds",
                           label_key: str = "adapter",
                           baseline_texts: Optional[Sequence[str]] = None
                           ) -> Dict:
    """Merge N engines' ``/metrics`` texts into fleet histogram entries
    (fedslo, docs/OBSERVABILITY.md): parse each scrape, reassemble the
    native histogram per adapter label, and add buckets — valid because
    every engine shares the same fixed boundary grid.

    ``baseline_texts`` (one earlier scrape per engine, same order)
    subtracts each engine's pre-window counts first — the Prometheus
    ``rate()`` discipline, which is how warm-up/compile requests are
    kept out of a measurement window over cumulative histograms.

    Returns ``{"labels": {label: entry}, "fleet": entry|None}`` where
    each entry is ``snapshot()``-shaped (feed it straight to
    :func:`~fedml_tpu.obs.histogram.quantile_from_buckets`).
    """
    from fedml_tpu.obs.histogram import (buckets_from_samples,
                                         diff_bucket_entries,
                                         merge_bucket_entries)
    from fedml_tpu.obs.metricsd import parse_prometheus_text
    per_engine = [buckets_from_samples(parse_prometheus_text(t), metric,
                                       label_key=label_key)
                  for t in texts]
    if baseline_texts is not None:
        base = [buckets_from_samples(parse_prometheus_text(t), metric,
                                     label_key=label_key)
                for t in baseline_texts]
        per_engine = [{lbl: diff_bucket_entries(e, b.get(lbl))
                       for lbl, e in pe.items()}
                      for pe, b in zip(per_engine, base)]
    labels = sorted({lbl for pe in per_engine for lbl in pe})
    merged = {lbl: merge_bucket_entries([pe.get(lbl) for pe in per_engine])
              for lbl in labels}
    fleet = merge_bucket_entries([e for pe in per_engine
                                  for e in pe.values()])
    return {"labels": merged, "fleet": fleet}


def run_fleet(engines: Sequence, metrics_urls: Sequence[str], *,
              target_rps: float, n_requests: int,
              adapters: Sequence[Optional[str]] = (None,),
              max_new_tokens: int = 16, vocab: int = 256, seed: int = 0,
              timeout_s: float = 300.0) -> Dict:
    """Drive each engine replica with an equal share of the load, scrape
    every live ``/metrics`` endpoint, and merge the per-engine native
    TTFT histograms into fleet percentiles by bucket addition.

    The cross-check: the bucket-estimated fleet p50/p99 must land within
    one bucket width of the harness's exact sample percentiles over ALL
    replicas' requests (``merge_ok``) — if merging were wrong (boundary
    drift, double count, dropped replica) the estimate falls outside the
    width guarantee of a single correct histogram.
    """
    import urllib.request

    from fedml_tpu.obs.histogram import (bucket_width_at,
                                         quantile_from_buckets)

    def _scrape(u: str) -> str:
        url = u.rstrip("/")
        if not url.endswith("/metrics"):
            url += "/metrics"
        return urllib.request.urlopen(url, timeout=10).read().decode()

    n_eng = len(engines)
    share = max(1, n_requests // n_eng)
    # pre-window scrape: whatever the engines served before this run
    # (warm-up/compile requests) is subtracted rate()-style
    baseline_texts = [_scrape(u) for u in metrics_urls]
    reports: List[Dict] = []
    ttft_all: List[float] = []
    for k, eng in enumerate(engines):
        rep = run_load(eng, target_rps=target_rps / n_eng,
                       n_requests=share, adapters=adapters,
                       max_new_tokens=max_new_tokens, vocab=vocab,
                       seed=seed + 101 * k, timeout_s=timeout_s,
                       keep_samples=True)
        # submit-based samples: the engine's own ttft clock convention,
        # so the check exercises the histogram algebra, not the gap
        # between scheduled-arrival and submit clocks
        ttft_all.extend(rep.pop("_samples")["ttft_submit"])
        reports.append(rep)
    texts = [_scrape(u) for u in metrics_urls]
    merged = merge_fleet_histograms(texts, metric="serve_ttft_seconds",
                                    baseline_texts=baseline_texts)
    fleet = merged["fleet"]
    checks: Dict[str, bool] = {}
    fleet_pct: Dict[str, Optional[float]] = {}
    if fleet is not None and ttft_all:
        for qname, q in (("p50", 0.50), ("p99", 0.99)):
            est = quantile_from_buckets(fleet, q)
            exact = _percentile(ttft_all, q * 100.0)
            width = bucket_width_at(fleet, exact)
            fleet_pct[qname] = est
            checks[f"ttft_{qname}_within_bucket"] = (
                est is not None and abs(est - exact) <= width + 1e-9)
        checks["fleet_count_matches"] = \
            fleet["count"] == sum(r["completed"] for r in reports)
    return {
        "engines": n_eng,
        "fleet_requests": sum(r["completed"] for r in reports),
        "fleet_failed": sum(r["failed"] for r in reports),
        "fleet_tokens_per_s": round(sum(r["tokens_per_s"]
                                        for r in reports), 1),
        "fleet_ttft_p50_ms": round((fleet_pct.get("p50") or 0.0) * 1e3, 2),
        "fleet_ttft_p99_ms": round((fleet_pct.get("p99") or 0.0) * 1e3, 2),
        "fleet_hist_count": int(fleet["count"]) if fleet else 0,
        "adapter_labels": sorted(merged["labels"]),
        "merge_checks": checks,
        "merge_ok": bool(checks) and all(checks.values()),
        "per_engine": reports,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rps", type=float, default=20.0)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--adapters", type=int, default=8,
                    help="registered LoRA adapters (plus base traffic)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(REPO, "SERVE_LOAD.json"))
    ap.add_argument("--multi", type=int, default=1, metavar="N",
                    help="drive N engine replicas, scrape each /metrics, "
                         "merge the native TTFT histograms into fleet "
                         "percentiles and cross-check them against exact "
                         "sample percentiles (fedslo)")
    ap.add_argument("--scrape-metrics", default=None, metavar="URL",
                    help="scrape this live fedmon /metrics endpoint "
                         "mid-run and cross-check the serve.* gauges "
                         "against the harness's own measurements "
                         "('self' starts an in-process endpoint over "
                         "the engine's tracer)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import fedml_tpu  # noqa: F401 (backend pin)
    from fedml_tpu.llm.fedllm import lora_init
    from fedml_tpu.llm.model import LlamaConfig, LlamaLM
    from fedml_tpu.serving.batching import ContinuousBatchingEngine

    buf_len = 128
    cfg = LlamaConfig(vocab_size=258, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=4, ffn_dim=128, max_seq_len=buf_len,
                      dtype=jnp.float32, lora_rank=8)
    model = LlamaLM(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))

    if args.multi > 1:
        engines = []
        for _k in range(args.multi):
            eng = ContinuousBatchingEngine(
                model, variables["params"], slots=args.slots,
                buf_len=buf_len, adapter_slots=args.adapters + 2,
                metrics_port=0)
            for i in range(args.adapters):
                eng.registry.register(
                    f"cohort{i}",
                    lora_init(jax.random.PRNGKey(100 + i),
                              variables["lora"]))
            engines.append(eng)
        names = [f"cohort{i}" for i in range(args.adapters)]
        try:
            for eng in engines:   # warm both compiled programs off-clock
                eng.generate([5, 17, 42], max_new_tokens=2,
                             adapter=names[0] if names else None)
            report = run_fleet(
                engines, [e.metrics_server.url for e in engines],
                target_rps=args.rps, n_requests=args.requests,
                adapters=[None] + names,
                max_new_tokens=args.max_new_tokens,
                vocab=cfg.vocab_size, seed=args.seed)
        finally:
            for eng in engines:
                eng.stop()
        print(json.dumps(report))
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        return

    engine = ContinuousBatchingEngine(
        model, variables["params"], slots=args.slots, buf_len=buf_len,
        adapter_slots=args.adapters + 2)
    names = []
    for i in range(args.adapters):
        name = f"cohort{i}"
        engine.registry.register(
            name, lora_init(jax.random.PRNGKey(100 + i), variables["lora"]))
        names.append(name)
    scrape_url = args.scrape_metrics
    metrics_server = None
    if scrape_url == "self":
        # the serve.* gauges only exist with the tracer on; an ephemeral
        # endpoint over the global tracer is the self-contained demo
        from fedml_tpu import obs
        obs.configure(enabled=True, reset=True)
        from fedml_tpu.obs.metricsd import MetricsServer
        metrics_server = MetricsServer()
        metrics_server.start()
        scrape_url = metrics_server.url
    try:
        # warm both compiled programs (prefill + batched step) off-clock
        engine.generate([5, 17, 42], max_new_tokens=2, adapter=names[0])
        report = run_load(
            engine, target_rps=args.rps, n_requests=args.requests,
            adapters=[None] + names, max_new_tokens=args.max_new_tokens,
            vocab=cfg.vocab_size, seed=args.seed, scrape_url=scrape_url)
    finally:
        engine.stop()
        if metrics_server is not None:
            metrics_server.close()
    report["engine"] = {"slots": args.slots, "buf_len": buf_len,
                        "adapters_registered": len(names)}
    print(json.dumps(report))
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)


if __name__ == "__main__":
    main()
