#!/usr/bin/env python
"""fedrace CLI — lock-discipline & deadlock checker for the host
concurrency plane (docs/FEDRACE.md).

Usage:
    python tools/fedrace.py check                        # whole package
    python tools/fedrace.py check fedml_tpu/store
    python tools/fedrace.py check --json
    python tools/fedrace.py check --update-manifest      # refresh pins
    python tools/fedrace.py --list-rules

Exit codes mirror fedlint/fedproto/fedverify: 0 = no unsuppressed
errors, 1 = at least one (or any unsuppressed finding with --strict),
2 = usage error.

Pure stdlib like ``tools/fedlint.py``: the analyzer is loaded by file
path (fedlint first, then fedrace, which imports it), so race checking
needs no jax install — it runs on CI lint shards and pre-commit hooks.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fedrace():
    """Load fedlint + fedrace directly, bypassing fedml_tpu/__init__
    (which imports jax and initializes a backend)."""
    analysis = os.path.join(REPO, "fedml_tpu", "analysis")

    def load(name, fname):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(analysis, fname))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        return mod

    load("fedlint", "fedlint.py")   # fedrace's ImportError fallback name
    return load("fedrace", "fedrace.py")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedrace", description="lock-discipline & deadlock checker "
        "for the host concurrency plane (shared-write guards, "
        "acquisition-order cycles, blocking-under-lock, leaked threads)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    sub = ap.add_subparsers(dest="cmd")

    chk = sub.add_parser("check", help="extract + check the package's "
                         "concurrency surface")
    chk.add_argument("paths", nargs="*", default=None,
                     help="files/dirs to analyze (default: fedml_tpu/)")
    chk.add_argument("--json", action="store_true", dest="as_json")
    chk.add_argument("--strict", action="store_true",
                     help="exit 1 on warnings too")
    chk.add_argument("--show-suppressed", action="store_true")
    chk.add_argument("--manifest", default=None,
                     help="concurrency.json path (default: "
                          "tests/data/fedrace/concurrency.json)")
    chk.add_argument("--update-manifest", action="store_true",
                     help="rewrite the manifest's extracted surface "
                          "(suppressions are preserved); the git diff is "
                          "the review surface")

    args = ap.parse_args(argv)
    fr = _load_fedrace()

    if args.list_rules:
        for r in fr.RACE_RULES.values():
            print(f"{r.name:24s} [{r.severity}] {r.doc}")
        return 0
    if args.cmd is None:
        ap.print_usage(sys.stderr)
        print("fedrace: error: choose a subcommand (check)",
              file=sys.stderr)
        return 2

    paths = args.paths or [os.path.join(REPO, "fedml_tpu")]
    scopes, warnings, extractors = fr.extract_concurrency(paths)
    if args.update_manifest:
        fr.update_manifest(scopes, extractors, args.manifest)
    manifest = fr.load_manifest(args.manifest)
    findings = fr.check_concurrency(scopes, extractors, manifest, warnings)
    if args.as_json:
        print(json.dumps({
            "findings": json.loads(fr.findings_to_json(findings)),
            "scopes": {n: fr.scope_to_manifest(s)
                       for n, s in sorted(scopes.items())},
        }, indent=2, default=list))
    else:
        print(fr.render_findings(findings,
                                 show_suppressed=args.show_suppressed,
                                 tool="fedrace"))
    return fr.exit_code(findings, strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
