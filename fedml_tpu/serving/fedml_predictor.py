"""FedMLPredictor ABC — serving surface parity (reference
``python/fedml/serving/fedml_predictor.py:4``)."""

from __future__ import annotations

import abc


class FedMLPredictor(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def predict(self, *args, **kwargs):
        ...

    def ready(self) -> bool:
        return True
