"""Speculative (draft-assisted) greedy decoding.

A small draft model proposes ``k`` tokens with cheap cached steps; the
target model verifies all of them in ONE k-token cached forward and accepts
the longest matching prefix plus its own correction token.  Output is
**bit-identical to target-only greedy decode** (verified in tests) — the
draft only changes how many target forwards are spent, not what they
produce.  With an aligned draft, one target forward yields up to ``k``
tokens; on TPU a k-token decode block costs barely more than a 1-token step
(the MXU is idle at s=1), so acceptance rate translates almost directly
into decode speedup.

Cache-correctness argument (why rejected tokens need no rollback): the
decode-mode attention masks every key/value slot at a position greater than
the query's (``llm/model.py::_decode_attend``), so K/V written for rejected
draft tokens are never attended until the decode frontier reaches their
positions again — at which point the verify block of a later round
overwrites them.  Both the target and draft caches self-heal this way.

Reference note: the reference serving stack has no speculative path (its
HF template predates assisted generation); this is a beyond-parity serving
feature. Greedy (temperature 0) only, like early HF assisted generation.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.quantization import dequantize_params, weight_dtype


def _vars(params, lora):
    """Variable dict with an optional "lora" collection — ``None`` is a
    trace-time constant, so adapter-blind callers compile the exact
    pre-lora programs."""
    v = {"params": params}
    if lora is not None:
        v["lora"] = lora
    return v


def propose_block(model, params, cache, sync, slen, fd, m, lora=None):
    """Un-jitted fused draft round: catch-up sync + m-token greedy
    proposal — the single source of truth for the draft-side cache
    position logic, shared by :func:`speculative_generate` (jitted per
    depth) and the vmapped :class:`~.batching.SpeculativeBatchingEngine`.

    ``params`` must already be dequantized.  ``sync``: (Kpad,) canonical
    tokens at positions ``fd..``; only the first ``slen`` are real (the
    padding's speculative writes self-heal — module docstring).  Returns
    ``(d_tokens (m,), cache)``; d_tokens[j] sits at position
    ``fd + slen + j``.
    """
    logits, mut = model.apply(
        {**_vars(params, lora), "cache": cache}, sync[None, :], decode=True,
        start_pos=fd, mutable=["cache"])
    cache = mut["cache"]
    pos = fd + slen - 1                  # last canonical position
    first = jnp.argmax(jax.lax.dynamic_index_in_dim(
        logits[0], slen - 1, axis=0, keepdims=False)).astype(jnp.int32)

    def body(carry, j):
        tok, cache = carry               # tok sits at position pos+j
        lg, mut = model.apply(
            {**_vars(params, lora), "cache": cache}, tok[None, None],
            decode=True, start_pos=pos + j, mutable=["cache"])
        nxt = jnp.argmax(lg[0, 0]).astype(jnp.int32)
        return (nxt, mut["cache"]), nxt

    # m is the host-static draft block length (engine config, never a
    # tracer); the branch just picks the scan-free shape for m == 1
    # fedlint: disable-next-line=recompile-hazard
    if m > 1:
        (_, cache), rest = jax.lax.scan(body, (first, cache),
                                        jnp.arange(1, m))
        return jnp.concatenate([first[None], rest]), cache
    return first[None], cache


def verify_greedy_block(model, params, cache, block, pos, lora=None):
    """Un-jitted target verify: ``block`` (k,) tokens written at positions
    ``pos..pos+k-1``; returns the target's greedy prediction for each next
    position.  ``params`` must already be dequantized."""
    logits, mut = model.apply(
        {**_vars(params, lora), "cache": cache}, block[None, :], decode=True,
        start_pos=pos, mutable=["cache"])
    return jnp.argmax(logits[0], axis=-1).astype(jnp.int32), mut["cache"]


@functools.lru_cache(maxsize=16)
def _build_spec_fns(model):
    # not k-specialized: verify_block handles any block length via jit
    # retracing, so the cache keys on the model alone.  Every function
    # takes ``lora`` as its second argument — a LoRA tree for per-request
    # personalization (traced, so one compiled program serves every
    # adapter of a given shape) or None for adapter-blind decode — the
    # same convention as openai_compat._build_cached_decode.
    wdtype = weight_dtype(model)

    @jax.jit
    def prefill(params, lora, buf, n):
        logits, mut = model.apply(
            _vars(dequantize_params(params, wdtype), lora), buf, decode=True,
            start_pos=jnp.zeros((), jnp.int32), mutable=["cache"])
        live = jax.lax.dynamic_index_in_dim(logits[0], n - 1, axis=0,
                                            keepdims=False)
        return jnp.argmax(live).astype(jnp.int32), mut["cache"]

    @jax.jit
    def step(params, lora, cache, tok, pos):
        logits, mut = model.apply(
            {**_vars(dequantize_params(params, wdtype), lora),
             "cache": cache},
            tok[None, None], decode=True, start_pos=pos, mutable=["cache"])
        return jnp.argmax(logits[0, 0]).astype(jnp.int32), mut["cache"]

    @jax.jit
    def verify_block(params, lora, cache, block, pos):
        return verify_greedy_block(model, dequantize_params(params, wdtype),
                                   cache, block, pos, lora)

    @functools.partial(jax.jit, static_argnames=("m",))
    def propose(params, lora, cache, sync_buf, sync_len, start, m):
        """Fused draft round: catch-up sync + m-token proposal, ONE
        dispatch (body shared with the batched engine: propose_block)."""
        return propose_block(model, dequantize_params(params, wdtype),
                             cache, sync_buf, sync_len, start, m, lora)

    return prefill, step, verify_block, propose


def speculative_generate(model, params, draft_model, draft_params,
                         prompt_ids: List[int], max_new_tokens: int = 64,
                         buf_len: int = 256, k: int = 4,
                         eos_id: Optional[int] = None,
                         on_token=None, adaptive_k: bool = True,
                         lora=None, draft_lora=None
                         ) -> Tuple[List[int], Dict[str, float]]:
    """Greedy decode of ``max_new_tokens`` with draft-model speculation.

    Returns ``(tokens, stats)``; ``stats['target_forwards']`` counts the
    expensive model's invocations and ``stats['acceptance_rate']`` the
    fraction of draft proposals the target agreed with.

    ``adaptive_k`` (default on, the HF assisted-generation heuristic):
    the verify-block size starts at 2 (= 1 draft proposal + the current
    token), doubles toward ``k`` (= up to ``k - 1`` proposals) after a
    fully-accepted round, and halves after a rejection — a misaligned
    draft stops burning draft forwards while an aligned one still reaches
    the full depth.  Output is unaffected (verified: any depth schedule
    yields the target-greedy stream).

    ``lora`` applies a LoRA adapter tree to the TARGET's prefill and
    verify (same argument the cached-decode builders take), so the output
    is bit-identical to ``generate(..., lora=lora)`` at temperature 0 —
    speculative + LoRA serves the adapter, not the base.  ``draft_lora``
    optionally personalizes the draft too; leaving the draft adapter-blind
    only lowers the acceptance rate, never changes output.
    """
    raw = params.get("params", params) if isinstance(params, dict) else params
    draw = draft_params.get("params", draft_params) \
        if isinstance(draft_params, dict) else draft_params
    t_prefill, _, t_verify, _ = _build_spec_fns(model)
    d_prefill, _, _, d_propose = _build_spec_fns(draft_model)

    prompt_ids = list(prompt_ids)[-(buf_len - 1):]
    n = len(prompt_ids)
    buf = np.zeros((1, buf_len), np.int32)
    buf[0, :n] = prompt_ids
    buf_j = jnp.asarray(buf)

    # both models prefill the prompt; target's greedy next-token is the
    # first "cur" (identical to generate()'s prefill output at temp 0)
    cur, t_cache = t_prefill(raw, lora, buf_j, jnp.int32(n))
    _, d_cache = d_prefill(draw, draft_lora, buf_j, jnp.int32(n))
    pos = n
    out: List[int] = []
    f_d = n  # draft CONFIRMED frontier: positions < f_d hold canonical K/V
    stats = {"target_forwards": 1, "draft_forwards": 1,
             "proposed": 0, "accepted": 0}

    def emit(tok: int) -> bool:
        if eos_id is not None and tok == eos_id:
            return False
        if pos_holder[0] >= buf_len or len(out) >= max_new_tokens:
            return False
        out.append(tok)
        if on_token is not None:
            on_token(tok)
        return len(out) < max_new_tokens

    pos_holder = [pos]
    cur = int(cur)
    if not emit(cur):
        return out, _finalize(stats)

    # adaptive depth stays a power of two (capped by k), so the verify
    # block only ever takes ~log2(k) distinct shapes — each novel shape is
    # a fresh XLA compile mid-request, which the schedule must not amplify
    depth = min(2, k) if adaptive_k else k
    while True:
        pos = pos_holder[0]
        block_k = min(depth, k, buf_len - pos)
        if block_k < 1:
            break
        # fused draft round: catch-up sync (every canonical token the draft
        # hasn't confirmed, f_d..pos — speculative writes from earlier
        # rounds are overwritten) + (block_k-1)-token proposal scan, all in
        # ONE device dispatch (the old host loop paid one tunnel round-trip
        # per draft token)
        d_tokens = []
        # near the buffer end the fixed (k+1) padded sync would clamp its
        # cache write (dynamic_update_slice) and silently corrupt canonical
        # draft K/V below the frontier — fall back to verify-only rounds
        # for the last few positions instead
        if block_k >= 2 and f_d + k + 1 <= buf_len:
            sync = [(prompt_ids[p] if p < n else out[p - n])
                    for p in range(f_d, pos + 1)]
            assert len(sync) <= k + 1, (len(sync), k)  # f_d trails pos by <= k
            sync_buf = np.zeros(k + 1, np.int32)
            sync_buf[:len(sync)] = sync
            d_jax, d_cache = d_propose(draw, draft_lora, d_cache,
                                       jnp.asarray(sync_buf),
                                       jnp.int32(len(sync)), jnp.int32(f_d),
                                       block_k - 1)
            stats["draft_forwards"] += block_k - 1
            f_d = pos + 1
            d_tokens = [int(t) for t in np.asarray(d_jax)]
        stats["proposed"] += len(d_tokens)
        block_k = len(d_tokens) + 1  # actual block length (guard may skip)

        # one target forward verifies cur + all proposals
        block = jnp.asarray([cur] + d_tokens, jnp.int32)
        greedy, t_cache = t_verify(raw, lora, t_cache, block, jnp.int32(pos))
        stats["target_forwards"] += 1
        greedy_host = np.asarray(greedy)

        done = False
        rejected = False
        for i, d in enumerate(d_tokens):
            g = int(greedy_host[i])
            if d != g:
                # first disagreement: the target's own token replaces it
                rejected = True
                pos_holder[0] = pos + i + 1
                cur = g
                done = not emit(g)
                break
            stats["accepted"] += 1
            pos_holder[0] = pos + i + 1
            if not emit(d):
                done = True
                break
            cur = d
        else:
            # every proposal accepted: the block's last greedy token is the
            # target's continuation of the final draft token
            g = int(greedy_host[block_k - 1])
            pos_holder[0] = pos + block_k
            cur = g
            done = not emit(g)
        if done:
            break
        if adaptive_k:
            depth = max(2, depth // 2) if rejected else \
                (depth * 2 if depth < k else depth)
    return out, _finalize(stats)


def _finalize(stats: Dict[str, int]) -> Dict[str, float]:
    stats = dict(stats)
    stats["acceptance_rate"] = (stats["accepted"] / stats["proposed"]
                                if stats["proposed"] else 0.0)
    return stats


__all__ = ["speculative_generate"]
