"""Serving SDK (reference ``python/fedml/serving/``): predictor ABC, HTTP
inference runner, federated serving client/server, OpenAI-compatible
template."""

from .adapters import AdapterRegistry, BankFullError
from .fedml_client import FedMLModelServingClient
from .fedml_inference_runner import FedMLInferenceRunner
from .fedml_predictor import FedMLPredictor
from .fedml_server import FedMLModelServingServer

__all__ = ["AdapterRegistry", "BankFullError", "FedMLInferenceRunner",
           "FedMLModelServingClient", "FedMLModelServingServer",
           "FedMLPredictor"]
