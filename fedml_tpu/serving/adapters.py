"""Multi-tenant LoRA serving: the adapter bank + registry.

The end state of federated fine-tuning is serving each cohort's (or each
user's) LoRA delta back to the population that trained it.  Per-adapter
engines don't scale — every one would carry its own copy of the shared
base — so the bank keeps N adapters **stacked on a leading adapter axis,
device-resident next to ONE shared base**: the batched decode step gathers
``bank[slot_adapter_ids]`` inside the compiled program and the vmapped
:class:`~fedml_tpu.llm.model.LoRADense` layers run the low-rank matmuls as
slot-batched (grouped) einsums.  Bank *capacity* is static (one compiled
program); *membership* is data — registering, evicting, or re-pointing an
adapter never recompiles anything.

Concurrency contract (the registry is shared between request threads and
the engine's decode thread):

- Row writes go through one jitted donated ``.at[row].set`` under
  ``self.lock``; the engine snapshots ``self.bank`` (and dispatches) under
  the same lock, so a donated-away buffer can never race a dispatch.
- Rows referenced by in-flight requests are **pinned**.  Re-registering a
  pinned name is copy-on-write: the name moves to a fresh row, the old row
  becomes a *zombie* that frees when its pins drain — an in-flight stream
  finishes on exactly the weights it started with.  Evicting a pinned name
  likewise only unroutes it; the row's bytes survive until the last
  reader finishes.
- Row 0 is the reserved **zero adapter** (A = B = 0 — the exact base
  model): requests without an adapter ride the same gathered program, so
  base and personalized traffic share one batch.

Cache mode (``store=``, an :class:`~fedml_tpu.serving.adapter_store
.AdapterStore`): the bank is demoted from *the* registered population to
an N-row HBM cache over the host/disk store.  ``register`` writes
through to the store and only unroutes any stale resident copy — rows
page in lazily on first ``acquire``.  A miss kicks an async store read
(:class:`~fedml_tpu.store.pager.AsyncRowFetcher`) and raises
:class:`AdapterMissError`; the engine parks the request and retries
after the fetch lands.  Residents evict LRU-unpinned under pressure
(their bytes live on in the store), pinned rows never evict, and
``BankFullError`` disappears: registered-adapter count is bounded by the
store, not HBM.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp


class BankFullError(RuntimeError):
    """Every non-reserved bank row is registered or still pinned by an
    in-flight request — evict something (or wait for a drain) first.
    (Bank-only registries; cache-mode registries page in/evict instead.)"""


class AdapterMissError(RuntimeError):
    """Cache-mode ``acquire`` miss: the adapter lives in the store but is
    not bank-resident (or every row is pinned).  An async page-in is
    already running — park the request and retry when it lands."""

    def __init__(self, name: str):
        super().__init__(f"adapter {name!r} not bank-resident — "
                         "page-in in flight, requeue the request")
        self.name = name


class _Row:
    __slots__ = ("name", "pins", "zombie", "token")

    def __init__(self):
        self.name: Optional[str] = None
        self.pins = 0
        self.zombie = False
        # identity token, refreshed per registration: prefix-cache keying
        # compares it by ``is`` so KV computed under one adapter version
        # can never serve another (templates/openai_compat.PrefixCache)
        self.token: object = object()


class AdapterRegistry:
    """Name → bank-row routing over a device-resident stacked LoRA bank.

    ``capacity`` counts bank rows *including* the reserved zero row, so a
    capacity-``N`` registry serves up to ``N - 1`` named adapters plus
    base traffic.  All public methods are thread-safe.
    """

    def __init__(self, model, capacity: int = 8, dtype=jnp.float32,
                 store=None):
        if getattr(getattr(model, "cfg", None), "lora_rank", 0) <= 0:
            raise ValueError("AdapterRegistry requires a lora_rank>0 model "
                             "config (LoRADense layers)")
        capacity = int(capacity)
        if capacity < 2:
            raise ValueError(f"capacity={capacity}: need >= 2 (row 0 is the "
                             "reserved zero adapter)")
        self.capacity = capacity
        # cache mode: the bank caches rows of this AdapterStore
        self.store = store
        # eval_shape + zeros, NOT model.init: init would materialize a full
        # base-parameter tree just to read the lora collection's structure
        shapes = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0))["lora"]
        self.bank = jax.tree_util.tree_map(
            lambda s: jnp.zeros((capacity,) + s.shape, dtype), shapes)
        self._row_struct = shapes

        @partial(jax.jit, donate_argnums=(0,))
        def set_row(bank, tree, row):
            return jax.tree_util.tree_map(
                lambda b, t: b.at[row].set(t.astype(b.dtype)), bank, tree)

        @jax.jit
        def gather_row(bank, row):
            return jax.tree_util.tree_map(lambda b: b[row], bank)

        self._set_row = set_row
        self._gather_row = gather_row
        self.lock = threading.RLock()
        self._names: Dict[str, int] = {}
        self._rows = [_Row() for _ in range(capacity)]
        self._free: List[int] = list(range(1, capacity))
        self.stats = {"registered": 0, "evicted": 0, "copy_on_write": 0,
                      "rows_reclaimed": 0, "cache_hits": 0,
                      "cache_misses": 0, "cache_evictions": 0}
        # cache-mode state: per-name registration version (stale in-flight
        # fetches are dropped on arrival), LRU clock per row, fetched rows
        # waiting for a free/unpinned slot
        self._ver: Dict[str, int] = {}
        self._lru: Dict[int, int] = {}
        self._lru_clock = 0
        self._pending_install: Dict[str, tuple] = {}
        self._fetcher = None
        self.on_fetch_done = None   # engine wake-up hook (set post-ctor)
        if store is not None:
            from ..store.pager import AsyncRowFetcher
            self._fetcher = AsyncRowFetcher(on_done=self._fetch_done)

    def _fetch_done(self, name: str) -> None:
        cb = self.on_fetch_done
        if cb is not None:
            cb(name)

    def close(self) -> None:
        if self._fetcher is not None:
            self._fetcher.close()

    # -- routing -----------------------------------------------------------
    def names(self) -> List[str]:
        with self.lock:
            if self.store is not None:
                return sorted(set(self._names) | set(self.store.names()))
            return sorted(self._names)

    def __contains__(self, name: str) -> bool:
        with self.lock:
            if self.store is not None and name in self.store:
                return True
            return name in self._names

    def _touch(self, row: int) -> None:
        self._lru_clock += 1
        self._lru[row] = self._lru_clock

    def _install_row(self, name: str, tree) -> Optional[int]:
        """Write a fetched row into the bank (lock held): a free row if
        any, else LRU-evict an unpinned resident.  None when every row is
        pinned (caller re-parks)."""
        if self._free:
            row = self._free.pop()
        else:
            cands = [(self._lru.get(i, 0), i)
                     for i, r in enumerate(self._rows)
                     if i and r.name is not None and r.pins == 0
                     and not r.zombie]
            if not cands:
                return None
            _, row = min(cands)
            old = self._rows[row].name
            del self._names[old]
            self._rows[row].name = None
            self.stats["cache_evictions"] += 1
        self.bank = self._set_row(self.bank, tree, jnp.int32(row))
        r = self._rows[row]
        r.name = name
        r.zombie = False
        r.token = object()
        self._names[name] = row
        self._touch(row)
        return row

    def acquire(self, name: Optional[str]):
        """Resolve ``name`` to ``(row, token)`` and pin the row for the
        lifetime of one request (``None`` → the zero row, never pinned —
        it cannot be evicted or rewritten).  Raises ``KeyError`` for
        unknown names.

        Cache mode: a bank-resident name pins and LRU-touches its row; a
        store-only name kicks an async page-in and raises
        :class:`AdapterMissError` (requeue and retry)."""
        with self.lock:
            if name is None:
                return 0, self._rows[0].token
            row = self._names.get(name)
            if row is not None:
                self._rows[row].pins += 1
                if self.store is not None:
                    self._touch(row)
                    self.stats["cache_hits"] += 1
                return row, self._rows[row].token
            if self.store is None:
                raise KeyError(
                    f"unknown adapter {name!r}; have {sorted(self._names)}")
            # fetched already? install now (engine thread holds the lock,
            # so the donated bank write cannot race a dispatch snapshot)
            pending = self._pending_install.pop(name, None)
            if pending is None:
                ok, val = self._fetcher.take(name)
                if ok:
                    pending = val
            if pending is not None:
                ver, tree = pending
                if ver == self._ver.get(name):
                    row = self._install_row(name, tree)
                    if row is not None:
                        self._rows[row].pins += 1
                        self.stats["cache_hits"] += 1
                        return row, self._rows[row].token
                    # every row pinned right now — hold the bytes, retry
                    self._pending_install[name] = pending
                    raise AdapterMissError(name)
                # stale version fetched mid-re-register: refetch below
            if name not in self.store:
                raise KeyError(
                    f"unknown adapter {name!r}; have {self.names()}")
            ver = self._ver.get(name)
            store = self.store
            if self._fetcher.request(
                    name, lambda: (ver, store.get(name))):
                self.stats["cache_misses"] += 1
            raise AdapterMissError(name)

    def release(self, row: int) -> None:
        """Drop one pin; a zombie row whose pins drain returns to the free
        list."""
        if row == 0:
            return
        with self.lock:
            r = self._rows[row]
            r.pins = max(r.pins - 1, 0)
            if r.zombie and r.pins == 0:
                r.zombie = False
                self._free.append(row)
                self.stats["rows_reclaimed"] += 1

    def lora_for_row(self, row: int):
        """Gathered single-adapter tree for one row (prefill-time use)."""
        with self.lock:
            return self._gather_row(self.bank, jnp.int32(row))

    # -- membership --------------------------------------------------------
    def _check_tree(self, lora_tree) -> None:
        got_def = jax.tree_util.tree_structure(lora_tree)
        want_def = jax.tree_util.tree_structure(self._row_struct)
        if got_def != want_def:
            raise ValueError(
                "lora tree does not match the bank's row structure "
                f"(model lora config mismatch): got {got_def}, "
                f"want {want_def}")
        for got, want in zip(jax.tree_util.tree_leaves(lora_tree),
                             jax.tree_util.tree_leaves(self._row_struct)):
            if tuple(got.shape) != tuple(want.shape):
                raise ValueError(
                    "lora leaf shape mismatch vs the bank row: got "
                    f"{tuple(got.shape)}, want {tuple(want.shape)} "
                    "(model lora_rank/config mismatch)")

    def register(self, name: str, lora_tree) -> int:
        """Write ``lora_tree`` into a bank row and route ``name`` to it.

        A re-register of an *unpinned* name rewrites its row in place; a
        *pinned* name moves to a fresh row (copy-on-write) so in-flight
        requests keep decoding against the weights they started with.
        Raises :class:`BankFullError` when no row is free.

        Cache mode writes through to the STORE, not the bank: any stale
        resident copy is unrouted (zombie while pinned — in-flight
        streams finish on the weights they started with) and the new
        version pages into a row lazily on first ``acquire``.  Returns
        -1 (no resident row yet); never raises ``BankFullError``."""
        name = str(name)
        self._check_tree(lora_tree)
        if self.store is not None:
            with self.lock:
                self._ver[name] = self._ver.get(name, 0) + 1
                self.store.put(name, lora_tree)
                self._pending_install.pop(name, None)
                row = self._names.pop(name, None)
                if row is not None:
                    r = self._rows[row]
                    r.name = None
                    if r.pins > 0:
                        r.zombie = True
                        self.stats["copy_on_write"] += 1
                    else:
                        self._free.append(row)
                self.stats["registered"] += 1
                return -1
        with self.lock:
            row = self._names.get(name)
            if row is not None and self._rows[row].pins > 0:
                # copy-on-write: the old row keeps serving its readers
                self._rows[row].zombie = True
                self._rows[row].name = None
                self.stats["copy_on_write"] += 1
                row = None
            if row is None:
                if not self._free:
                    raise BankFullError(
                        f"adapter bank full ({self.capacity - 1} rows; "
                        f"registered={sorted(self._names)}, zombies="
                        f"{sum(r.zombie for r in self._rows)}) — evict an "
                        "adapter or wait for in-flight requests to drain")
                row = self._free.pop()
            self.bank = self._set_row(self.bank, lora_tree, jnp.int32(row))
            r = self._rows[row]
            r.name = name
            r.zombie = False
            r.token = object()
            self._names[name] = row
            self.stats["registered"] += 1
            return row

    def evict(self, name: str) -> None:
        """Unroute ``name``.  New requests for it fail immediately; a row
        still pinned by in-flight requests survives as a zombie until they
        drain, then frees.  Cache mode also drops the store copy (and
        invalidates any in-flight page-in of it)."""
        name = str(name)
        with self.lock:
            row = self._names.pop(name, None)
            if self.store is not None:
                known = row is not None or name in self.store
                if not known:
                    raise KeyError(f"unknown adapter {name!r}")
                self.store.remove(name)
                self._ver[name] = self._ver.get(name, 0) + 1
                self._pending_install.pop(name, None)
                self.stats["evicted"] += 1
                if row is None:
                    return
            elif row is None:
                raise KeyError(f"unknown adapter {name!r}")
            else:
                self.stats["evicted"] += 1
            r = self._rows[row]
            r.name = None
            if r.pins > 0:
                r.zombie = True
            else:
                self._free.append(row)

    # -- federated handoff -------------------------------------------------
    def register_from_checkpoint(self, name: str, directory: str,
                                 round_idx: Optional[int] = None,
                                 member: Optional[int] = None) -> int:
        """Register a LoRA delta straight out of a federated orbax
        checkpoint — a fine-tune run's output becomes servable without a
        restart.  The saved state may be the bare lora tree, any dict
        carrying a ``"lora"`` key, or a population-stacked run (pass
        ``member`` to extract one experiment via
        :func:`fedml_tpu.core.federated.population_member`)."""
        from ..core.checkpoint import RoundCheckpointer
        ckpt = RoundCheckpointer(directory)
        try:
            state = ckpt.restore_state(round_idx)
        finally:
            ckpt.close()
        if state is None:
            raise FileNotFoundError(
                f"no checkpoint round in {directory!r}")
        tree = state["lora"] if isinstance(state, dict) and "lora" in state \
            else state
        if member is not None:
            from ..core.federated import population_member
            tree = population_member(tree, int(member))
        return self.register(name, tree)


__all__ = ["AdapterRegistry", "AdapterMissError", "BankFullError"]
