"""Multi-tenant LoRA serving: the adapter bank + registry.

The end state of federated fine-tuning is serving each cohort's (or each
user's) LoRA delta back to the population that trained it.  Per-adapter
engines don't scale — every one would carry its own copy of the shared
base — so the bank keeps N adapters **stacked on a leading adapter axis,
device-resident next to ONE shared base**: the batched decode step gathers
``bank[slot_adapter_ids]`` inside the compiled program and the vmapped
:class:`~fedml_tpu.llm.model.LoRADense` layers run the low-rank matmuls as
slot-batched (grouped) einsums.  Bank *capacity* is static (one compiled
program); *membership* is data — registering, evicting, or re-pointing an
adapter never recompiles anything.

Concurrency contract (the registry is shared between request threads and
the engine's decode thread):

- Row writes go through one jitted donated ``.at[row].set`` under
  ``self.lock``; the engine snapshots ``self.bank`` (and dispatches) under
  the same lock, so a donated-away buffer can never race a dispatch.
- Rows referenced by in-flight requests are **pinned**.  Re-registering a
  pinned name is copy-on-write: the name moves to a fresh row, the old row
  becomes a *zombie* that frees when its pins drain — an in-flight stream
  finishes on exactly the weights it started with.  Evicting a pinned name
  likewise only unroutes it; the row's bytes survive until the last
  reader finishes.
- Row 0 is the reserved **zero adapter** (A = B = 0 — the exact base
  model): requests without an adapter ride the same gathered program, so
  base and personalized traffic share one batch.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp


class BankFullError(RuntimeError):
    """Every non-reserved bank row is registered or still pinned by an
    in-flight request — evict something (or wait for a drain) first."""


class _Row:
    __slots__ = ("name", "pins", "zombie", "token")

    def __init__(self):
        self.name: Optional[str] = None
        self.pins = 0
        self.zombie = False
        # identity token, refreshed per registration: prefix-cache keying
        # compares it by ``is`` so KV computed under one adapter version
        # can never serve another (templates/openai_compat.PrefixCache)
        self.token: object = object()


class AdapterRegistry:
    """Name → bank-row routing over a device-resident stacked LoRA bank.

    ``capacity`` counts bank rows *including* the reserved zero row, so a
    capacity-``N`` registry serves up to ``N - 1`` named adapters plus
    base traffic.  All public methods are thread-safe.
    """

    def __init__(self, model, capacity: int = 8, dtype=jnp.float32):
        if getattr(getattr(model, "cfg", None), "lora_rank", 0) <= 0:
            raise ValueError("AdapterRegistry requires a lora_rank>0 model "
                             "config (LoRADense layers)")
        capacity = int(capacity)
        if capacity < 2:
            raise ValueError(f"capacity={capacity}: need >= 2 (row 0 is the "
                             "reserved zero adapter)")
        self.capacity = capacity
        # eval_shape + zeros, NOT model.init: init would materialize a full
        # base-parameter tree just to read the lora collection's structure
        shapes = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0))["lora"]
        self.bank = jax.tree_util.tree_map(
            lambda s: jnp.zeros((capacity,) + s.shape, dtype), shapes)
        self._row_struct = shapes

        @partial(jax.jit, donate_argnums=(0,))
        def set_row(bank, tree, row):
            return jax.tree_util.tree_map(
                lambda b, t: b.at[row].set(t.astype(b.dtype)), bank, tree)

        @jax.jit
        def gather_row(bank, row):
            return jax.tree_util.tree_map(lambda b: b[row], bank)

        self._set_row = set_row
        self._gather_row = gather_row
        self.lock = threading.RLock()
        self._names: Dict[str, int] = {}
        self._rows = [_Row() for _ in range(capacity)]
        self._free: List[int] = list(range(1, capacity))
        self.stats = {"registered": 0, "evicted": 0, "copy_on_write": 0,
                      "rows_reclaimed": 0}

    # -- routing -----------------------------------------------------------
    def names(self) -> List[str]:
        with self.lock:
            return sorted(self._names)

    def __contains__(self, name: str) -> bool:
        with self.lock:
            return name in self._names

    def acquire(self, name: Optional[str]):
        """Resolve ``name`` to ``(row, token)`` and pin the row for the
        lifetime of one request (``None`` → the zero row, never pinned —
        it cannot be evicted or rewritten).  Raises ``KeyError`` for
        unknown names."""
        with self.lock:
            if name is None:
                return 0, self._rows[0].token
            if name not in self._names:
                raise KeyError(
                    f"unknown adapter {name!r}; have {sorted(self._names)}")
            row = self._names[name]
            self._rows[row].pins += 1
            return row, self._rows[row].token

    def release(self, row: int) -> None:
        """Drop one pin; a zombie row whose pins drain returns to the free
        list."""
        if row == 0:
            return
        with self.lock:
            r = self._rows[row]
            r.pins = max(r.pins - 1, 0)
            if r.zombie and r.pins == 0:
                r.zombie = False
                self._free.append(row)
                self.stats["rows_reclaimed"] += 1

    def lora_for_row(self, row: int):
        """Gathered single-adapter tree for one row (prefill-time use)."""
        with self.lock:
            return self._gather_row(self.bank, jnp.int32(row))

    # -- membership --------------------------------------------------------
    def _check_tree(self, lora_tree) -> None:
        got_def = jax.tree_util.tree_structure(lora_tree)
        want_def = jax.tree_util.tree_structure(self._row_struct)
        if got_def != want_def:
            raise ValueError(
                "lora tree does not match the bank's row structure "
                f"(model lora config mismatch): got {got_def}, "
                f"want {want_def}")
        for got, want in zip(jax.tree_util.tree_leaves(lora_tree),
                             jax.tree_util.tree_leaves(self._row_struct)):
            if tuple(got.shape) != tuple(want.shape):
                raise ValueError(
                    "lora leaf shape mismatch vs the bank row: got "
                    f"{tuple(got.shape)}, want {tuple(want.shape)} "
                    "(model lora_rank/config mismatch)")

    def register(self, name: str, lora_tree) -> int:
        """Write ``lora_tree`` into a bank row and route ``name`` to it.

        A re-register of an *unpinned* name rewrites its row in place; a
        *pinned* name moves to a fresh row (copy-on-write) so in-flight
        requests keep decoding against the weights they started with.
        Raises :class:`BankFullError` when no row is free."""
        name = str(name)
        self._check_tree(lora_tree)
        with self.lock:
            row = self._names.get(name)
            if row is not None and self._rows[row].pins > 0:
                # copy-on-write: the old row keeps serving its readers
                self._rows[row].zombie = True
                self._rows[row].name = None
                self.stats["copy_on_write"] += 1
                row = None
            if row is None:
                if not self._free:
                    raise BankFullError(
                        f"adapter bank full ({self.capacity - 1} rows; "
                        f"registered={sorted(self._names)}, zombies="
                        f"{sum(r.zombie for r in self._rows)}) — evict an "
                        "adapter or wait for in-flight requests to drain")
                row = self._free.pop()
            self.bank = self._set_row(self.bank, lora_tree, jnp.int32(row))
            r = self._rows[row]
            r.name = name
            r.zombie = False
            r.token = object()
            self._names[name] = row
            self.stats["registered"] += 1
            return row

    def evict(self, name: str) -> None:
        """Unroute ``name``.  New requests for it fail immediately; a row
        still pinned by in-flight requests survives as a zombie until they
        drain, then frees."""
        with self.lock:
            row = self._names.pop(str(name), None)
            if row is None:
                raise KeyError(f"unknown adapter {name!r}")
            r = self._rows[row]
            r.name = None
            self.stats["evicted"] += 1
            if r.pins > 0:
                r.zombie = True
            else:
                self._free.append(row)

    # -- federated handoff -------------------------------------------------
    def register_from_checkpoint(self, name: str, directory: str,
                                 round_idx: Optional[int] = None,
                                 member: Optional[int] = None) -> int:
        """Register a LoRA delta straight out of a federated orbax
        checkpoint — a fine-tune run's output becomes servable without a
        restart.  The saved state may be the bare lora tree, any dict
        carrying a ``"lora"`` key, or a population-stacked run (pass
        ``member`` to extract one experiment via
        :func:`fedml_tpu.core.federated.population_member`)."""
        from ..core.checkpoint import RoundCheckpointer
        ckpt = RoundCheckpointer(directory)
        try:
            state = ckpt.restore_state(round_idx)
        finally:
            ckpt.close()
        if state is None:
            raise FileNotFoundError(
                f"no checkpoint round in {directory!r}")
        tree = state["lora"] if isinstance(state, dict) and "lora" in state \
            else state
        if member is not None:
            from ..core.federated import population_member
            tree = population_member(tree, int(member))
        return self.register(name, tree)


__all__ = ["AdapterRegistry", "BankFullError"]
