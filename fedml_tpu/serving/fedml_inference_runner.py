"""Inference runner — HTTP serving of a FedMLPredictor (reference
``python/fedml/serving/fedml_inference_runner.py:8``: FastAPI ``/predict`` +
``/ready``).

FastAPI isn't in this image, so the server is a stdlib
``ThreadingHTTPServer`` speaking the same JSON protocol on the same routes —
zero extra deps, good enough for single-model endpoints; the deploy plane
can front it with any gateway.  jit-compiled predictors amortize compile on
first request (or call ``warmup()``).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .fedml_predictor import FedMLPredictor

log = logging.getLogger(__name__)


class FedMLInferenceRunner:
    def __init__(self, client_predictor: FedMLPredictor,
                 host: str = "127.0.0.1", port: int = 2345):
        # loopback by default: the endpoint is unauthenticated; external
        # exposure requires an explicit host="0.0.0.0"
        self.client_predictor = client_predictor
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None

    def _make_handler(self):
        predictor = self.client_predictor

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/ready", "/health"):
                    ok = predictor.ready()
                    self._send(200 if ok else 503, {"ready": bool(ok)})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in ("/predict", "/api/v1/predict"):
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    result = predictor.predict(req)
                    self._send(200, {"result": result})
                except Exception as e:  # surface errors as JSON, keep serving
                    log.exception("predict failed")
                    self._send(500, {"error": str(e)})

            def log_message(self, fmt, *args):
                log.debug("http: " + fmt, *args)

        return Handler

    def start(self) -> int:
        """Non-blocking start; returns the bound port."""
        self._server = ThreadingHTTPServer((self.host, self.port),
                                           self._make_handler())
        self.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        log.info("inference runner serving on %s:%d", self.host, self.port)
        return self.port

    def run(self):
        """Blocking serve (reference FedMLInferenceRunner.run surface)."""
        self.start()
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            self.stop()

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
