"""Portable serving artifacts — the TPU-native analog of the reference's
model conversion step (``device_model_deployment.py:618``
``convert_model_to_onnx`` and the ``.mnn`` files ``model_hub.py:81-88``
writes for phones).

An artifact is a single zip holding the model's forward as serialized
StableHLO (``jax.export`` — version-stable, hardware-retargetable: the same
artifact loads on CPU or TPU) plus the msgpack'd params.  Serving a model
therefore needs NO Python model code at the endpoint, matching the
container-ships-a-converted-model deployment story.
"""

from __future__ import annotations

import json
import zipfile
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_HLO_NAME = "forward.stablehlo"
_PARAMS_NAME = "params.msgpack"
_META_NAME = "meta.json"


def save_model_artifact(path: str, model, params,
                        batch_size: int = 1) -> str:
    """Serialize ``model.apply(params, x)`` for a fixed batch shape.

    ``model``: a :class:`~fedml_tpu.models.base.FlaxModel` (or anything
    with ``.apply(params, x)`` and ``.input_shape``).
    """
    import flax.serialization

    x_spec = jax.ShapeDtypeStruct(
        (batch_size,) + tuple(model.input_shape),
        getattr(model, "input_dtype", jnp.float32))
    params_spec = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    exported = jax.export.export(jax.jit(model.apply))(params_spec, x_spec)
    blob = exported.serialize()
    host_params = jax.tree.map(np.asarray, params)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(_HLO_NAME, blob)
        z.writestr(_PARAMS_NAME,
                   flax.serialization.msgpack_serialize(host_params))
        z.writestr(_META_NAME, json.dumps({
            "input_shape": list(model.input_shape),
            "input_dtype": str(np.dtype(
                getattr(model, "input_dtype", jnp.float32))),
            "batch_size": batch_size,
            "format": "stablehlo+msgpack/v1",
        }))
    return path


def load_model_artifact(path: str) -> Tuple[Callable, dict]:
    """Load an artifact → (predict_fn(x) -> logits, meta).  No model code
    needed; the StableHLO is rehydrated by jax.export and jitted."""
    import flax.serialization

    with zipfile.ZipFile(path) as z:
        exported = jax.export.deserialize(z.read(_HLO_NAME))
        params = flax.serialization.msgpack_restore(z.read(_PARAMS_NAME))
        meta = json.loads(z.read(_META_NAME))

    def predict(x):
        x = jnp.asarray(x, dtype=meta["input_dtype"])
        return exported.call(params, x)

    return predict, meta


__all__ = ["save_model_artifact", "load_model_artifact"]
