"""Federated model-serving client (reference
``python/fedml/serving/fedml_client.py:5`` ``FedMLModelServingClient`` — the
silo-side participant of a serving federation; same FSM as the cross-silo
trainer client)."""

from __future__ import annotations

from ..cross_silo.client import Client


class FedMLModelServingClient:
    def __init__(self, args, end_point_name, model_name, model_version="",
                 inference_request=None, device=None, dataset=None,
                 model=None, train_data_num=0, client_trainer=None):
        self.end_point_name = end_point_name
        self.model_name = model_name
        self.model_version = model_version
        self.inference_request = inference_request
        args.update(end_point_name=end_point_name, model_name=model_name,
                    model_version=model_version)
        self._client = Client(args, device, dataset, model,
                              client_trainer=client_trainer)

    def run(self):
        self._client.run()
