"""AdapterStore — host/disk backing store for the serving adapter cache.

The HBM adapter bank (``serving/adapters.py``) used to BE the registered
population: ``adapter_slots=N`` meant at most N−1 named adapters, ever.
This store demotes the bank to an N-row cache: every registered adapter's
LoRA tree lives here as one row of a :class:`ClientStateStore` — the same
sparse hash-paged host table (with optional LRU ``.npz`` spill past
``max_resident_pages``) that scaled per-client training state past HBM in
the fedstore work — and the registry pages rows in on cache miss.
Registered-adapter count is now bounded by host RAM / disk, not HBM
(10k+ adapters through one engine at flat HBM, ``bench.py
--serve-paged``).

Thread-safety: the name→row-id map and the underlying store carry their
own locks; ``put``/``get`` may be called from HTTP registration threads
and the registry's async fetch worker concurrently.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..store.clientstore import ClientStateStore

Pytree = Any


class AdapterStore:
    """Named LoRA-tree rows over a :class:`ClientStateStore`.

    ``model`` supplies the row template (the lora collection's
    shapes/dtypes via ``eval_shape`` — nothing is materialized);
    ``registered`` bounds the id space (ids are assigned to names in
    registration order and never reused).  ``spill_dir`` +
    ``max_resident_pages`` bound host RSS by spilling cold pages to disk
    (``adapter_store_dir`` on the engine/server ctor).
    """

    def __init__(self, model, registered: int = 16384,
                 page_size: int = 64, max_resident_pages: int = 0,
                 spill_dir: Optional[str] = None):
        shapes = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0))
        if "lora" not in shapes:
            raise ValueError("model has no 'lora' collection "
                             "(lora_rank=0?) — nothing to store")
        template = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), shapes["lora"])
        self._store = ClientStateStore(
            template, registered=int(registered), page_size=page_size,
            max_resident_pages=max_resident_pages, spill_dir=spill_dir)
        self._ids: Dict[str, int] = {}
        self._next = 0
        self._lock = threading.RLock()

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._ids

    def names(self) -> List[str]:
        with self._lock:
            return list(self._ids)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)

    def put(self, name: str, tree: Pytree) -> None:
        """Write (or overwrite) ``name``'s row.  Host copies only — the
        caller's device arrays are materialized here, off the bank."""
        with self._lock:
            rid = self._ids.get(name)
            if rid is None:
                if self._next >= self._store.registered:
                    raise RuntimeError(
                        f"adapter store full ({self._store.registered} "
                        "ids) — raise `registered`")
                rid = self._next
                self._next += 1
                self._ids[name] = rid
        rows = jax.tree_util.tree_map(
            lambda l: np.asarray(l)[None], tree)
        self._store.scatter(np.array([rid], np.int64), rows)

    def get(self, name: str) -> Pytree:
        """Read ``name``'s row (KeyError for unknown names); may hit the
        disk spill path — callers on a latency-sensitive thread should go
        through the registry's async fetcher instead."""
        with self._lock:
            rid = self._ids[name]
        rows = self._store.gather(np.array([rid], np.int64))
        return jax.tree_util.tree_map(lambda l: l[0], rows)

    def remove(self, name: str) -> None:
        """Drop the name→row routing (the row itself stays; ids are not
        reused, matching the registry's evict-then-reregister flow)."""
        with self._lock:
            self._ids.pop(name, None)

    def stats(self) -> Dict[str, int]:
        s = dict(self._store.stats())
        with self._lock:
            s["registered_names"] = len(self._ids)
        s["row_nbytes"] = self._store.row_nbytes
        return s
