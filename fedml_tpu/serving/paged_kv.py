"""Host-side bookkeeping for the paged KV cache (docs/SERVING.md).

The device side is one page pool per layer (``llm/model.py``
``_paged_decode_attend``) addressed through per-slot block tables carried
as TRACED data.  Everything here is plain-python free-list + refcount
bookkeeping over *page ids* — no device arrays, no jax — run only on the
engine thread between dispatches, so admission, finish, prefix sharing
and eviction never touch the compiled programs.

Page 0 is the reserved trash page: block tables default to it, so writes
past a slot's reservation (chunk padding, horizon burn-out) land in
garbage that mask discipline keeps out of every softmax.  It is never in
the free list and never refcounted.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple


class PageExhaustedError(RuntimeError):
    """Not enough free pages for a reservation (the engine parks the
    request and retries after the next finish/evict frees pages)."""


class PagedBlockPool:
    """Free list + per-page refcounts over ``n_pages`` device pages.

    Pages are *reserved* (refcount 1) at admission for a slot's private
    blocks, *shared* (refcount +1) when a prefix-cache hit lends its
    pages to a new slot or the cache itself retains them, and *released*
    when a holder drops out — a page returns to the free list when its
    last holder releases it.
    """

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"n_pages={n_pages}: page 0 is the reserved "
                             "trash page — need at least 2")
        self.n_pages = int(n_pages)
        self._free: List[int] = list(range(1, self.n_pages))
        self._refs = [0] * self.n_pages
        self.stats: Dict[str, int] = {
            "reserved_pages": 0, "shared_pages": 0, "released_pages": 0,
            "exhausted": 0,
        }

    @property
    def pages_free(self) -> int:
        return len(self._free)

    def can_reserve(self, n: int) -> bool:
        return n <= len(self._free)

    def reserve(self, n: int) -> List[int]:
        """Take ``n`` fresh pages (refcount 1 each) off the free list."""
        if n > len(self._free):
            self.stats["exhausted"] += 1
            raise PageExhaustedError(
                f"need {n} pages, {len(self._free)} free "
                f"(pool of {self.n_pages})")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self.stats["reserved_pages"] += n
        return pages

    def share(self, pages: List[int]) -> None:
        """Add one holder to already-live pages (prefix sharing)."""
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(f"share of dead page {p}")
            self._refs[p] += 1
        self.stats["shared_pages"] += len(pages)

    def release(self, pages: List[int]) -> None:
        """Drop one holder from each page; last holder frees it."""
        for p in pages:
            if p == 0:
                continue
            r = self._refs[p] - 1
            if r < 0:
                raise ValueError(f"release of free page {p}")
            self._refs[p] = r
            if r == 0:
                self._free.append(p)
        self.stats["released_pages"] += len(pages)


class PagedPrefixCache:
    """Prefix reuse as copy-on-write page *sharing* (refcounts), not KV
    copies — the paged counterpart of ``openai_compat.PrefixCache``.

    An entry holds the page ids covering the FULL pages of a finished
    prefill (positions ``[0, len(pages)*page_tokens)``); the cache itself
    holds one reference on each (``pool.share`` at insert).  ``lookup``
    lends the longest usable full-page prefix to a new slot — the caller
    increfs before wiring the pages into its block table, and the replay
    invariant (writes only at positions ``>= full*page_tokens``) keeps
    the lent pages read-only under every sharer.

    Entries are keyed by the prompt token tuple and pinned to the params
    identity + per-registration adapter token that produced them (KV
    computed under one weight/adapter version never serves another).
    """

    def __init__(self, capacity: int, page_tokens: int,
                 pool: PagedBlockPool):
        self.capacity = int(capacity)
        self.page_tokens = int(page_tokens)
        self.pool = pool
        self._entries: "OrderedDict[tuple, Tuple[List[int], Any]]" = \
            OrderedDict()
        self._params_ref: Any = None
        self.lock = threading.RLock()
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "insertions": 0, "evictions": 0,
            "shared_pages": 0, "private_pages": 0,
        }

    def _flush_if_stale(self, params) -> None:
        if self._params_ref is not params:
            self.clear()
            self._params_ref = params

    def clear(self) -> None:
        with self.lock:
            for pages, _tok in self._entries.values():
                self.pool.release(pages)
            self._entries.clear()

    def lookup(self, prompt_ids: List[int], params,
               adapter_token) -> Tuple[int, List[int]]:
        """Longest shareable full-page prefix for ``prompt_ids`` →
        ``(n_full_pages, page_ids)`` (``(0, [])`` on miss).  The shared
        span always leaves at least the final prompt token to replay, so
        the caller's chunk replay produces the first sample itself."""
        n = len(prompt_ids)
        ptok = self.page_tokens
        with self.lock:
            self._flush_if_stale(params)
            best: Tuple[int, List[int]] = (0, [])
            best_key = None
            for key, (pages, tok) in self._entries.items():
                if tok is not adapter_token:
                    continue
                c = 0
                for a, b in zip(key, prompt_ids):
                    if a != b:
                        break
                    c += 1
                full = min(len(pages), c // ptok, (n - 1) // ptok)
                if full > best[0]:
                    best = (full, pages[:full])
                    best_key = key
            if best_key is not None:
                self._entries.move_to_end(best_key)
                self.stats["hits"] += 1
                self.stats["shared_pages"] += best[0]
            else:
                self.stats["misses"] += 1
            return best

    def insert(self, prompt_ids: List[int], pages: List[int], params,
               adapter_token) -> None:
        """Retain ``pages`` (the prompt's full pages, in block order) for
        future sharers; the cache increfs them itself."""
        if not pages:
            return
        key = tuple(prompt_ids)
        with self.lock:
            self._flush_if_stale(params)
            if key in self._entries:
                self._entries.move_to_end(key)
                return
            self.pool.share(pages)
            self._entries[key] = (list(pages), adapter_token)
            self.stats["insertions"] += 1
            while len(self._entries) > self.capacity:
                _k, (old, _t) = self._entries.popitem(last=False)
                self.pool.release(old)
                self.stats["evictions"] += 1

    def evict_for_pages(self, needed_free: int) -> int:
        """LRU-drop entries until the pool could satisfy a reservation of
        ``needed_free`` pages (an entry's pages only return to the free
        list if no slot still shares them).  Returns entries dropped."""
        dropped = 0
        with self.lock:
            while self._entries and self.pool.pages_free < needed_free:
                _k, (pages, _t) = self._entries.popitem(last=False)
                self.pool.release(pages)
                self.stats["evictions"] += 1
                dropped += 1
        return dropped

    def __len__(self) -> int:
        with self.lock:
            return len(self._entries)
