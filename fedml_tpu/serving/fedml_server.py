"""Federated model-serving server (reference
``python/fedml/serving/fedml_server.py:4`` ``FedMLModelServingServer`` —
binds endpoint metadata to the cross-silo server FSM so a trained federated
model can keep being refined while it serves).

Reuses the cross-silo ``Server`` (same aggregation FSM, same comm
backends); endpoint identity travels in the args so the deploy plane can
register the resulting model under ``{end_point_name}/{model_name}``.
"""

from __future__ import annotations

from ..cross_silo.server import Server


class FedMLModelServingServer:
    def __init__(self, args, end_point_name, model_name, model_version="",
                 inference_request=None, device=None, dataset=None,
                 model=None, server_aggregator=None):
        self.end_point_name = end_point_name
        self.model_name = model_name
        self.model_version = model_version
        self.inference_request = inference_request
        args.update(end_point_name=end_point_name, model_name=model_name,
                    model_version=model_version)
        self._server = Server(args, device, dataset, model,
                              server_aggregator=server_aggregator)

    @property
    def aggregator(self):
        return self._server.aggregator

    def run(self):
        return self._server.run()
