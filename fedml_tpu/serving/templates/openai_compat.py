"""OpenAI-compatible chat/completions endpoint over a flax causal LM
(reference ``python/fedml/serving/templates/hf_template/main_openai.py`` —
the HF chatbot template exposing ``/v1/chat/completions``).

TPU-native serving decisions:

- **KV-cached decode.** When the server is built from a model exposing the
  flax "cache" collection (``LlamaLM(decode=True)``), generation is a
  one-shot prefill over the padded prompt buffer followed by a jitted
  single-token step against a static-length KV cache — O(S) per token
  instead of the O(S²) full-buffer re-forward.  All shapes static, so both
  programs compile once per (buffer length, batch) and are cached across
  requests.
- **Fixed-shape fallback.** Any bare ``apply_fn(params, tokens) -> logits``
  still works: the token buffer is padded to a static length and each step
  re-runs the full forward (the round-1 behavior, kept as the generic
  path).
- **Deterministic sampling.** threefry key per request; temperature 0 ⇒
  argmax.
- **Zero extra deps.** stdlib HTTP server (FastAPI isn't in the image),
  byte-level tokenizer fallback so no tokenizer download is needed; any
  object with encode/decode can be plugged in instead.
"""

from __future__ import annotations

import collections
import functools
import json
import logging
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...obs.context import parse_traceparent
from ...obs.tracer import get_tracer

log = logging.getLogger(__name__)


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 = bytes, 256 = BOS, 257 = EOS."""

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= int(i) < 256)
        return data.decode("utf-8", errors="replace")


def _sample_live(live, key, temp, top_k: int, top_p: float = 1.0):
    """live: (V,) logits → sampled token id (greedy at temp 0).

    ``top_k``/``top_p`` are static (compile-time) filters like the
    reference HF template's generation kwargs: top-k keeps the k highest
    logits, nucleus top-p keeps the smallest prefix of the sorted
    distribution with cumulative probability ≥ p (always ≥ 1 token)."""
    if (top_k and top_k > 0) or top_p < 1.0:
        # one descending sort serves both filters; top-k is a prefix mask
        # on the sorted array, and top-p renormalizes over what top-k kept
        # (HF generation semantics: k first, then p)
        sorted_desc = jnp.sort(live)[::-1]
        if top_k and top_k > 0:
            idx = jnp.arange(sorted_desc.shape[0])
            sorted_desc = jnp.where(idx < top_k, sorted_desc, -jnp.inf)
        if top_p < 1.0:
            probs = jax.nn.softmax(sorted_desc)
            cum = jnp.cumsum(probs)
            # keep token i iff the mass BEFORE it is < p; the argmax is
            # always kept, so top_p <= 0 degrades to greedy, not to
            # an all-masked distribution
            keep = (cum - probs < top_p).at[0].set(True)
            sorted_desc = jnp.where(keep, sorted_desc, -jnp.inf)
        kth = jnp.min(jnp.where(jnp.isfinite(sorted_desc), sorted_desc,
                                jnp.inf))
        live = jnp.where(live < kth, -jnp.inf, live)
    greedy = jnp.argmax(live)
    sampled = jax.random.categorical(key, live / jnp.maximum(temp, 1e-6))
    return jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def _build_plain_step(apply_fn: Callable, top_k: int, top_p: float):
    """Jitted full-buffer step, cached across requests (a per-request
    ``@jax.jit`` would re-trace every call — the jit cache is keyed on the
    function object)."""

    @jax.jit
    def step(params, buf, pos, key, temp):
        logits = apply_fn(params, buf)  # (1, L, V)
        # logits at pos-1 predict token at pos
        live = jax.lax.dynamic_index_in_dim(logits[0], pos - 1, axis=0,
                                            keepdims=False)
        return _sample_live(live, key, temp, top_k, top_p)

    return step


@functools.lru_cache(maxsize=32)
def _build_cached_decode(model, top_k: int, top_p: float):
    """Jitted (prefill, step) pair for a flax model supporting
    ``decode=True`` with a "cache" collection (``llm.model.LlamaLM``).

    Both functions take ``lora`` as their second argument: a LoRA tree
    (the "lora" collection of LoRADense layers) for per-request
    personalization — a traced argument, so ONE compiled program serves
    every adapter of a given shape — or ``None`` (an empty pytree; the
    presence/absence is part of the jit cache key) for models without
    adapters.  int8-quantized param trees (``llm/quantization.py``) pass
    through transparently: the dequantize runs inside the traced
    program, so the weights stay int8 in HBM and the per-matmul dequant
    fuses."""
    from ...llm.quantization import dequantize_params, weight_dtype
    wdtype = weight_dtype(model)

    def _vars(params, lora):
        v = {"params": dequantize_params(params, wdtype)}
        if lora is not None:        # trace-time: None is an empty pytree
            v["lora"] = lora
        return v

    @jax.jit
    def prefill(params, lora, buf, n, key, temp):
        logits, mut = model.apply(
            _vars(params, lora), buf, decode=True,
            start_pos=jnp.zeros((), jnp.int32), mutable=["cache"])
        live = jax.lax.dynamic_index_in_dim(logits[0], n - 1, axis=0,
                                            keepdims=False)
        return _sample_live(live, key, temp, top_k, top_p), mut["cache"]

    @jax.jit
    def step(params, lora, cache, tok, pos, key, temp):
        logits, mut = model.apply(
            {**_vars(params, lora), "cache": cache}, tok[None, None],
            decode=True, start_pos=pos, mutable=["cache"])
        return _sample_live(logits[0, 0], key, temp, top_k,
                            top_p), mut["cache"]

    @jax.jit
    def tail_block(params, lora, cache, padded_buf, start, n, key, temp):
        """Replay prompt positions start..n-1 in ONE dispatch (prefix-cache
        partial hits: a per-token tail replay costs one host round-trip
        per token, which inverts the caching win on dispatch-bound
        targets — round-4 advisor).  ``padded_buf`` is the prompt buffer
        right-padded with TAIL_BLOCK zeros so the dynamic slice never
        clamps; the block writes K/V for a fixed TAIL_BLOCK window whose
        stale positions >= n progressively self-heal (each later decode
        step overwrites position p's K/V before any query attends it —
        the same mask-discipline argument the speculative verify blocks
        rely on).  Logits are read at the last REAL position (n-1)."""
        block = jax.lax.dynamic_slice(padded_buf, (0, start),
                                      (1, TAIL_BLOCK))
        logits, mut = model.apply(
            {**_vars(params, lora), "cache": cache}, block,
            decode=True, start_pos=start, mutable=["cache"])
        live = jax.lax.dynamic_index_in_dim(logits[0], n - 1 - start,
                                            axis=0, keepdims=False)
        return _sample_live(live, key, temp, top_k, top_p), mut["cache"]

    return prefill, step, tail_block


#: fixed width of the one-dispatch tail-replay block (compiled once; a
#: partial prefix hit with an uncached tail up to this long replays as a
#: single device program instead of per-token steps)
TAIL_BLOCK = 32


def _replay_tail(step_fn, tail_fn, cache, buf_j, ids, start, n, max_seq,
                 key, temp):
    """Replay prompt positions ``start..n-1`` onto a cached KV state —
    the ONE shared implementation of the prefix-hit replay discipline
    (generate() and the batching engine's admission both use it, so the
    correctness guards cannot diverge).  Multi-token tails that fit the
    fixed block AND the context window replay as one tail_block dispatch;
    everything else (exact hits, tails longer than TAIL_BLOCK under a
    custom admission bound, the window's very end) takes the bounded
    per-token path.  Returns ``(tok, cache, key)``."""
    tail = n - start
    if 1 < tail <= TAIL_BLOCK and start + TAIL_BLOCK <= max_seq:
        padded = jnp.concatenate(
            [buf_j, jnp.zeros((1, TAIL_BLOCK), jnp.int32)], axis=1)
        key, sub = jax.random.split(key)
        tok, cache = tail_fn(cache, padded, jnp.int32(start),
                             jnp.int32(n), sub, temp)
        return tok, cache, key
    tok = None
    for j in range(start, n):
        key, sub = jax.random.split(key)
        tok, cache = step_fn(cache, jnp.int32(ids[j]), jnp.int32(j), sub,
                             temp)
    return tok, cache, key


class RequestError(ValueError):
    """Client-side request mistake -> HTTP 4xx (a 500 would be counted
    against server error budgets and retried by OpenAI-style clients)."""

    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = int(status)


class PrefixCache:
    """LRU cache of prefill KV states keyed by prompt token prefix.

    Serving workloads re-send shared prefixes constantly (a system
    prompt, a federated-eval template) — this skips prefill work for the
    longest cached prefix: an exact hit replays one idempotent decode
    step (re-writing the last position with identical K/V) instead of
    the whole prefill; a prefix hit continues from the cached state
    through only the unseen tail tokens.  vLLM calls the idea automatic
    prefix caching; the reference's serving path
    (/root/reference/python/fedml/serving/) re-forwards every request
    from scratch.

    Greedy outputs are BIT-IDENTICAL with or without the cache (pinned
    by test).  Sampled requests draw a different-but-equally-distributed
    key sequence (the prefill split is skipped), so seeds don't
    reproduce across cache states — same caveat vLLM documents.

    Memory: ``capacity`` x one full KV buffer (layers x 2 x B x H_kv x
    buf_len x head_dim in the model's KV dtype); size capacity to HBM.
    Entries are immutable jax arrays, so sharing them across requests
    and threads is safe; the dict itself is guarded by a lock.
    """

    def __init__(self, capacity: int = 8, max_tail: int = TAIL_BLOCK):
        self.capacity = int(capacity)
        #: partial-hit admission bound, in TOKENS of uncached tail.  The
        #: serving cost model is DISPATCHES, not FLOPs (~70 ms/launch over
        #: a tunnel-attached TPU — SERVE_RTT_SIM): tails up to TAIL_BLOCK
        #: replay as ONE tail_block dispatch — dispatch-parity with the
        #: miss path's single prefill while skipping the cached prefix's
        #: FLOPs — so the default bound is TAIL_BLOCK.  Longer tails would
        #: fall back to one dispatch PER token, inverting the win exactly
        #: where latency matters most (round-4 advisor finding), so they
        #: miss instead.
        self.max_tail = int(max_tail)
        self._entries = collections.OrderedDict()   # tuple(ids) -> cache
        self._lock = threading.Lock()
        #: the params tree the cached KV was computed under — held by
        #: STRONG reference so identity comparison is exact (an id() of a
        #: freed tree could be reused); entries are invalidated wholesale
        #: when a different tree shows up (federated serving swaps
        #: weights every round — old-weight KV must never mix with
        #: new-weight decode).  NOTE the strong ref keeps the OLD tree
        #: alive until the first post-swap request arrives; weight-swap
        #: paths should call :meth:`clear` eagerly (the server's
        #: ``update_params`` does) so the old weights + stale KV free
        #: immediately instead of squatting on HBM through the idle gap
        self._params_ref = None
        self._lora_ref = None
        self.stats = {"hits": 0, "exact_hits": 0, "misses": 0,
                      "insertions": 0, "invalidations": 0,
                      "prefill_tokens_skipped": 0}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._params_ref = None
            self._lora_ref = None

    def _sync_params(self, params, lora=None) -> None:
        """Caller holds the lock.  Drop every entry when the weights OR
        the adapter the cache was built under are replaced — prefix KV is
        (params, lora)-specific, so uniform-adapter traffic caches
        normally while a change of either tree invalidates wholesale."""
        if self._params_ref is not params or self._lora_ref is not lora:
            if self._entries:
                self.stats["invalidations"] += 1
                self._entries.clear()
            self._params_ref = params
            self._lora_ref = lora

    def lookup(self, ids: List[int], params=None, lora=None):
        """Longest COMMON prefix between ``ids`` and any cached entry →
        (c, cache) or (0, None).  A cached buffer whose prompt diverges
        after position c is still valid for the first c tokens: decode
        steps attend only positions <= their own, and each step writes
        its position's K/V before attending, so the stale tail
        progressively self-heals (the same mask-discipline argument the
        speculative verify blocks rely on).  ``params`` (the weight tree
        the caller will decode with) invalidates the cache on change."""
        t = tuple(ids)
        with self._lock:
            if params is not None:
                self._sync_params(params, lora)
            best, best_key = 0, None
            for key in self._entries:
                c = 0
                for a, b in zip(key, t):
                    if a != b:
                        break
                    c += 1
                if c > best:
                    best, best_key = c, key
            # hit policy: the uncached tail replays as single-token steps
            # (one dispatch each) while a miss costs ONE prefill dispatch,
            # so admission is gated on an ABSOLUTE tail bound (max_tail
            # tokens) — dispatch count, not FLOPs, is the serving cost
            # model; exact hits (1 idempotent replay step) always win
            if best_key is not None and len(t) - best <= self.max_tail:
                self._entries.move_to_end(best_key)   # LRU recency
                cache = self._entries[best_key]
                self.stats["hits"] += 1
                if best == len(t):
                    self.stats["exact_hits"] += 1
                # positions genuinely not re-forwarded: an exact hit still
                # replays the last prompt position, a prefix hit replays
                # best..n-1 — so min(best, n-1), not the matched length
                self.stats["prefill_tokens_skipped"] += min(best, len(t) - 1)
                return best, cache
            self.stats["misses"] += 1
            return 0, None

    def insert(self, ids: List[int], cache, params=None,
               lora=None) -> None:
        t = tuple(ids)
        with self._lock:
            if params is not None:
                self._sync_params(params, lora)
            if t in self._entries:
                self._entries.move_to_end(t)
                return
            self._entries[t] = cache
            self.stats["insertions"] += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)


def generate(apply_fn: Callable, params, prompt_ids: List[int],
             max_new_tokens: int = 64, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 1.0, seed: int = 0,
             buf_len: int = 256,
             eos_id: Optional[int] = None,
             on_token: Optional[Callable[[int], None]] = None,
             model=None, prefix_cache: Optional[PrefixCache] = None,
             lora=None) -> List[int]:
    """Sample ``max_new_tokens`` continuations of ``prompt_ids``.

    ``apply_fn(params, tokens)`` must return logits of shape (B, T, V).
    With ``model`` given (a flax module supporting ``decode=True`` whose
    ``cfg.max_seq_len >= buf_len``), decode uses the KV cache: prefill
    once, then O(1)-context single-token steps.  All shapes are static, so
    each program compiles once per buffer size regardless of
    prompt/generation length.
    """
    prompt_ids = list(prompt_ids)[-(buf_len - 1):]
    buf = np.zeros((1, buf_len), np.int32)
    n = len(prompt_ids)
    buf[0, :n] = prompt_ids
    buf_j = jnp.asarray(buf)
    key = jax.random.PRNGKey(seed)
    temp = float(temperature)
    out: List[int] = []

    if model is not None:
        raw_params = params.get("params", params) if isinstance(params, dict) \
            else params
        prefill_p, step_p, tail_p = _build_cached_decode(model, int(top_k),
                                                         float(top_p))
        prefill = functools.partial(prefill_p, raw_params, lora)
        step = functools.partial(step_p, raw_params, lora)
        tail_blk = functools.partial(tail_p, raw_params, lora)
        # prefix KV is adapter-specific: the cache keys validity on
        # (params, lora) identity, so uniform-adapter traffic (e.g. the
        # server's shared zero adapter) caches normally while a CHANGE of
        # adapter invalidates wholesale — stale cross-adapter KV can
        # never serve
        hit_len, hit_cache = (prefix_cache.lookup(prompt_ids, raw_params,
                                                  lora)
                              if prefix_cache is not None and n > 0
                              else (0, None))
        if hit_cache is not None:
            # continue from the cached state through the unseen tail; an
            # exact hit replays only the LAST prompt token — position
            # n-1's K/V rewrite is idempotent (same deterministic apply),
            # and its logits equal the prefill's, so greedy output is
            # bit-identical to the uncached path.  Multi-token tails
            # replay as ONE tail_block dispatch (vs one dispatch per
            # token) whenever the fixed block fits inside the context
            # window; at the window's very end the bounded per-token
            # fallback runs instead.
            cache = hit_cache
            start = min(hit_len, n - 1)
            max_seq = getattr(getattr(model, "cfg", None), "max_seq_len",
                              buf_len)
            tok, cache, key = _replay_tail(step, tail_blk, cache, buf_j,
                                           prompt_ids, start, n, max_seq,
                                           key, temp)
        else:
            key, sub = jax.random.split(key)
            tok, cache = prefill(buf_j, n, sub, temp)
        if prefix_cache is not None and n > 0:
            prefix_cache.insert(prompt_ids, cache, raw_params, lora)
        pos = n
        while pos < buf_len and len(out) < max_new_tokens:
            t = int(tok)
            if eos_id is not None and t == eos_id:
                break
            out.append(t)
            if on_token is not None:
                on_token(t)
            key, sub = jax.random.split(key)
            tok, cache = step(cache, jnp.int32(t), jnp.int32(pos), sub,
                              temp)
            pos += 1
        return out

    step = _build_plain_step(apply_fn, int(top_k), float(top_p))
    pos = n
    for _ in range(max_new_tokens):
        if pos >= buf_len:
            break
        key, sub = jax.random.split(key)
        tok = int(step(params, buf_j, pos, sub, temp))
        if eos_id is not None and tok == eos_id:
            break
        out.append(tok)
        if on_token is not None:
            on_token(tok)
        buf_j = buf_j.at[0, pos].set(tok)
        pos += 1
    return out


def _render_chat(messages: List[dict]) -> str:
    """Minimal chat template (the reference delegates to the HF tokenizer's
    chat template; the byte tokenizer needs an explicit one)."""
    parts = [f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}"
             for m in messages]
    return "\n".join(parts) + "\n<|assistant|>\n"


class OpenAICompatServer:
    """Serves /v1/models, /v1/completions, /v1/chat/completions (+ SSE
    streaming) over a (model_apply, params) pair."""

    def __init__(self, apply_fn: Callable, params, tokenizer=None,
                 model_name: str = "fedml-tpu-llm", host: str = "127.0.0.1",
                 port: int = 0, buf_len: int = 256, model=None,
                 batch_slots: int = 0, draft_model=None, draft_params=None,
                 decode_horizon: int = 1, spec_k: int = 4,
                 prefix_cache_slots: int = 0,
                 prefix_max_tail: int = TAIL_BLOCK,
                 adapters=None, adapter_slots: int = 0,
                 metrics_port: Optional[int] = None,
                 slo_rules: Optional[List[dict]] = None,
                 kv_page_tokens: int = 0, kv_pool_pages: int = 0,
                 prefill_chunk_tokens: int = 0, prefill_lanes: int = 1,
                 adapter_cache_slots: int = 0,
                 adapter_store_dir: Optional[str] = None):
        """``host`` defaults to loopback — the endpoint is unauthenticated,
        so exposing it on all interfaces requires an explicit
        ``host="0.0.0.0"``.  ``model`` (optional): flax module supporting
        ``decode=True`` → KV-cached decode (see :func:`generate`).
        ``batch_slots`` > 0 (requires ``model``) routes requests through the
        :class:`~fedml_tpu.serving.batching.ContinuousBatchingEngine` so
        concurrent requests share one batched decode program; sampled
        requests that ALSO ask for ``top_k``/``top_p`` fall through to the
        single-request path (one compiled program per distinct filter
        pair) so the fields are honored, never silently ignored.  ``decode_horizon`` > 1 (engine mode only) generates that
        many tokens per device dispatch — same outputs, H-fold fewer host
        round-trips; streaming granularity coarsens to H tokens.

        Memory-plane knobs (engine mode only; docs/SERVING.md):
        ``kv_page_tokens`` > 0 switches the engine to the paged KV cache
        (``kv_pool_pages`` sizes the pool, 0 = auto) with chunked prefill
        (``prefill_chunk_tokens``/``prefill_lanes``);
        ``adapter_cache_slots`` > 0 demotes the adapter bank to an N-row
        cache over a host/disk store (``adapter_store_dir`` spills cold
        rows to disk) — use it INSTEAD of ``adapter_slots`` to register
        adapters past HBM."""
        self.apply_fn = apply_fn
        self.params = params
        self.tokenizer = tokenizer or ByteTokenizer()
        self.model_name = model_name
        self.host, self.port = host, port
        # fedmon live export: a sibling /metrics + /healthz endpoint over
        # the tracer's serve.* gauges (started/stopped with the server)
        self.metrics_port = metrics_port
        self.metrics_server = None
        # fedslo: objective-style SLO rules ride into the engine (per-
        # request burn-rate streams) and the metrics endpoint (/healthz
        # multi-window evaluation) — see docs/OBSERVABILITY.md
        self.slo_rules = slo_rules
        self.buf_len = buf_len
        self.model = model
        # speculative decode (requires model + a draft; greedy requests
        # only — sampled requests fall back to the plain paths)
        self.draft_model = draft_model
        self.draft_params = draft_params
        if draft_model is not None and model is None:
            raise ValueError("draft_model requires `model` (KV-cached "
                             "target) — speculative decode is cache-based")
        if draft_model is not None and draft_params is None:
            raise ValueError("draft_model requires draft_params")
        # prefix_cache_slots > 0 (requires ``model``): reuse prefill KV
        # for shared prompt prefixes.  Non-engine path: one PrefixCache
        # consulted by generate(); engine path: the engine builds its own
        # and consults it at admission (self.prefix_cache aliases it
        # below so stats stay reachable either way — but the sampled
        # fall-through around a greedy-only engine does NOT use it:
        # update_params() swaps the engine only after its in-flight
        # drain, so MID-SWAP the engine's tree and self.params diverge
        # and sharing one cache would ping-pong invalidation between the
        # two identities; separate caches keep each path self-consistent).
        self.prefix_cache = None
        if prefix_cache_slots and model is None:
            raise ValueError("prefix_cache_slots requires `model` "
                             "(prefix caching is KV-cache-based)")
        if prefix_cache_slots and not batch_slots:
            self.prefix_cache = PrefixCache(prefix_cache_slots,
                                            max_tail=int(prefix_max_tail))
        # adapters: {name: LoRA tree} over ONE shared base — per-request
        # personalization for federated clients (request field
        # {"adapter": name} or {"model": name}; neither = the zero adapter
        # = base behavior).  Requires a lora_rank>0 model config; one
        # compiled program serves every adapter (the tree is a traced
        # argument).  With ``batch_slots`` the adapters live in a
        # device-resident bank (serving/adapters.AdapterRegistry) of
        # ``adapter_slots`` rows and requests for DIFFERENT adapters share
        # one batched decode program; without an engine each request
        # carries its tree through the single-request path.  The reference
        # serves one full model copy per personalized endpoint.
        # serializes hot-swap writers (update_params / add_adapter /
        # evict_adapter on the training/promotion thread) against HTTP
        # worker threads snapshotting a coherent (params, draft_params,
        # prefix_cache, adapter) set at the top of _complete
        self._swap_lock = threading.Lock()
        self.adapters = None
        self._zero_lora = None
        self.registry = None
        # paged-KV / adapter-cache knobs are engine-mode only (the memory
        # plane they reshape IS the engine's) — reject up front instead of
        # silently serving dense
        if (kv_page_tokens or adapter_cache_slots) and not batch_slots:
            raise ValueError(
                "kv_page_tokens / adapter_cache_slots reshape the "
                "batching engine's memory plane — set batch_slots too")
        if kv_page_tokens and draft_model is not None:
            from ..batching import PagedKVUnsupportedError
            raise PagedKVUnsupportedError(
                "kv_page_tokens with draft_model: the speculative engine "
                "needs contiguous per-slot caches — drop one of the two")
        if adapter_cache_slots and adapter_slots:
            raise ValueError(
                "adapter_cache_slots and adapter_slots are mutually "
                "exclusive: the cache mode replaces the fixed bank")
        if adapters is not None or adapter_slots or adapter_cache_slots:
            if model is None:
                raise ValueError("adapters require `model` (KV-cached "
                                 "decode carries the lora collection)")
            if getattr(getattr(model, "cfg", None), "lora_rank", 0) <= 0:
                raise ValueError("adapters require a lora_rank>0 model "
                                 "config (LoRADense layers)")
            if batch_slots and draft_model is not None:
                raise ValueError(
                    "adapters and the speculative batching engine are "
                    "incompatible (it is single-tenant greedy) — drop "
                    "draft_model or batch_slots")
            if batch_slots and not adapter_cache_slots:
                from ..adapters import AdapterRegistry
                cap = int(adapter_slots) or len(adapters or {}) + 8
                self.registry = AdapterRegistry(model, capacity=cap)
                for name, tree in (adapters or {}).items():
                    self.registry.register(name, tree)
            elif not batch_slots:
                # (draft_model + adapters is fine here: greedy requests
                # route through speculative_generate, which carries the
                # lora tree — parity-tested)
                self.adapters = dict(adapters or {})
                # zero A/B -> the adapter term vanishes: base behavior.
                # eval_shape + zeros, NOT model.init: init would
                # materialize a full base-parameter tree (and trace a
                # forward) just to read the lora collection — a transient
                # full-model allocation a box sized for int8-quantized
                # weights may not survive
                shapes = jax.eval_shape(
                    lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
                    jax.random.PRNGKey(0))["lora"]
                self._zero_lora = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        self._engine = None
        self._engine_greedy_only = False
        if batch_slots:
            if model is None:
                raise ValueError(
                    "batch_slots requires `model` (a flax module supporting "
                    "decode=True) — the batching engine is KV-cache based")
            if draft_model is not None:
                # flagship serving config: speculative continuous batching
                # for greedy traffic; sampled requests fall through to the
                # single-request cached path below.  Requires
                # cfg.max_seq_len >= buf_len + spec_k + 1 (block slack).
                if int(decode_horizon) > 1:
                    raise ValueError(
                        "decode_horizon and draft_model are mutually "
                        "exclusive: the speculative engine advances up to "
                        "spec_k+1 tokens per dispatch already")
                from ..batching import SpeculativeBatchingEngine
                self._engine = SpeculativeBatchingEngine(
                    model, params, draft_model, draft_params,
                    slots=int(batch_slots), buf_len=buf_len,
                    k=int(spec_k),
                    prefix_cache_slots=int(prefix_cache_slots),
                    prefix_max_tail=int(prefix_max_tail),
                    slo_rules=slo_rules)
                self.prefix_cache = self._engine.prefix_cache
                self._engine_greedy_only = True
            else:
                from ..batching import ContinuousBatchingEngine
                self._engine = ContinuousBatchingEngine(
                    model, params, slots=int(batch_slots), buf_len=buf_len,
                    horizon=int(decode_horizon),
                    prefix_cache_slots=int(prefix_cache_slots),
                    prefix_max_tail=int(prefix_max_tail),
                    adapter_registry=self.registry,
                    slo_rules=slo_rules,
                    kv_page_tokens=int(kv_page_tokens),
                    kv_pool_pages=int(kv_pool_pages),
                    prefill_chunk_tokens=int(prefill_chunk_tokens),
                    prefill_lanes=int(prefill_lanes),
                    adapter_cache_slots=int(adapter_cache_slots),
                    adapter_store_dir=adapter_store_dir)
                self.prefix_cache = self._engine.prefix_cache
                if adapter_cache_slots:
                    # the engine owns the store-backed registry; alias it
                    # so add_adapter/evict_adapter and the fall-through
                    # path route through the same cache
                    self.registry = self._engine.registry
                    for name, tree in (adapters or {}).items():
                        self.registry.register(name, tree)
        self._server: Optional[ThreadingHTTPServer] = None

    # -- request handling --------------------------------------------------
    def _complete(self, prompt: str, req: dict,
                  on_text: Optional[Callable[[str], None]] = None,
                  traceparent: Optional[str] = None) -> str:
        """Run generation; ``on_text`` (if given) receives incremental text
        deltas on UTF-8 boundaries — a raw per-token decode would shred
        multi-byte characters with the byte tokenizer.  ``traceparent``
        (validated W3C header value) joins the request's span tree to the
        caller's fedscope trace."""
        tok = self.tokenizer
        ids: List[int] = []
        sent = 0
        t_submit = time.monotonic()

        def emit(t: int):
            nonlocal sent
            ids.append(t)
            text = tok.decode(ids)
            # trailing replacement chars mark an incomplete UTF-8 sequence;
            # hold those bytes back until the sequence completes
            clean = text.rstrip("�")
            if len(clean) > sent:
                on_text(clean[sent:])
                sent = len(clean)

        # adapter routing: an explicit {"adapter": name} field, or —
        # multi-tenant OpenAI convention — {"model": name} naming anything
        # other than the server's base model id (so a federated client
        # points its stock OpenAI SDK at its own cohort's adapter)
        adapter_name = req.get("adapter")
        # one coherent weight snapshot per request: update_params /
        # add_adapter / evict_adapter swap these under _swap_lock on the
        # promotion thread, so grab (params, draft_params, prefix_cache,
        # lora) together — a mid-request swap then serves entirely-old or
        # entirely-new weights, never a torn mix
        with self._swap_lock:
            if not adapter_name:
                m = req.get("model")
                if (isinstance(m, str) and m and m != self.model_name
                        and (self.adapters is not None
                             or self.registry is not None)):
                    adapter_name = m
            params = self.params
            draft_params = self.draft_params
            prefix_cache = self.prefix_cache
            lora = None
            if self.registry is not None:
                pass  # resolved (and pinned) per-path below
            elif self.adapters is not None:
                if adapter_name:
                    if adapter_name not in self.adapters:
                        raise RequestError(
                            f"unknown adapter {adapter_name!r}; have "
                            f"{sorted(self.adapters)}", status=404)
                    lora = self.adapters[adapter_name]
                else:
                    lora = self._zero_lora
            elif adapter_name:
                raise RequestError("server has no adapters configured")

        # per-request top_k/top_p cannot ride the engine (its sampler is
        # one compiled program for the pool) — rather than silently
        # IGNORING the fields, such requests fall through to the
        # single-request path, whose builder compiles one program per
        # distinct (top_k, top_p) pair (lru-cached); greedy requests are
        # filter-independent, so they stay on the engine either way
        # None-safe field parsing: OpenAI-style clients serialize unset
        # optionals as explicit JSON nulls, and dict.get's default does
        # not apply to a present null
        temp = float(req.get("temperature") or 0.0)
        req_top_k = int(req.get("top_k") or 0)
        req_top_p = float(1.0 if req.get("top_p") is None
                          else req.get("top_p"))
        wants_filters = (temp != 0.0
                         and (req_top_k > 0 or req_top_p < 1.0))
        if self._engine is not None and not wants_filters and not (
                self._engine_greedy_only and temp != 0.0):
            try:
                q = self._engine.submit(
                    tok.encode(prompt),
                    max_new_tokens=int(req.get("max_tokens", 64)),
                    temperature=temp,
                    seed=int(req.get("seed", 0)),
                    eos_id=getattr(tok, "eos_id", None),
                    adapter=adapter_name,
                    traceparent=traceparent)
            except KeyError as e:
                # unknown adapter — resolved at submit so the 404 happens
                # before any slot/queue state is touched
                raise RequestError(str(e.args[0] if e.args else e),
                                   status=404)
            out = []
            while True:
                try:
                    t = q.get(timeout=300)
                except queue.Empty:
                    break  # engine wedged/crashed — fail the request open
                if t is None:
                    break
                out.append(t)
                if on_text:
                    emit(t)
        else:
            release_row = None
            if self.registry is not None:
                # fall-through around the MT engine (per-request
                # top_k/top_p filters): pin the bank row for the whole
                # generation so an eviction can't reclaim it mid-request.
                # Cache-mode misses (row paging in from the store) block-
                # retry here — this path has a thread to park, unlike the
                # engine loop
                from ..adapters import AdapterMissError
                deadline = time.monotonic() + 30.0
                while True:
                    try:
                        release_row, _atok = self.registry.acquire(
                            adapter_name)
                        break
                    except AdapterMissError:
                        if time.monotonic() >= deadline:
                            raise RequestError(
                                f"adapter {adapter_name!r} did not page "
                                "in within 30s", status=503)
                        time.sleep(0.02)
                    except KeyError as e:
                        raise RequestError(
                            str(e.args[0] if e.args else e), status=404)
                lora = self.registry.lora_for_row(release_row)
            try:
                if self.draft_model is not None and temp == 0.0:
                    from ..speculative import speculative_generate
                    out, _spec_stats = speculative_generate(
                        self.model, params, self.draft_model,
                        draft_params, tok.encode(prompt),
                        max_new_tokens=int(req.get("max_tokens", 64)),
                        buf_len=self.buf_len,
                        eos_id=getattr(tok, "eos_id", None),
                        on_token=emit if on_text else None,
                        lora=lora)
                else:
                    out = generate(
                        self.apply_fn, params, tok.encode(prompt),
                        max_new_tokens=int(req.get("max_tokens", 64)),
                        temperature=temp,
                        top_k=req_top_k,
                        top_p=min(max(req_top_p, 0.0), 1.0),
                        seed=int(req.get("seed", 0)),
                        buf_len=self.buf_len,
                        eos_id=getattr(tok, "eos_id", None),
                        on_token=emit if on_text else None,
                        model=self.model,
                        prefix_cache=(prefix_cache
                                      if self._engine is None else None),
                        lora=lora)
            finally:
                if release_row is not None:
                    self.registry.release(release_row)
            # the engine emits its own request span tree at _finish; the
            # single-request fall-through emits one here (HTTP-thread
            # lane, host clocks) so every served request has a
            # serve.request span regardless of path
            tracer = get_tracer()
            if tracer.enabled:
                e2e_s = time.monotonic() - t_submit
                tracer.complete(
                    "serve.request", e2e_s, cat="serve",
                    tid=threading.get_ident(),
                    adapter=adapter_name or "base",
                    output_tokens=len(out), e2e_s=round(e2e_s, 6),
                    traceparent=traceparent, path="fallthrough")
        text = tok.decode(out)
        if on_text and len(text) > sent:
            on_text(text[sent:])  # flush any held-back tail
        return text

    def _make_handler(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/models":
                    names = [outer.model_name]
                    if outer.registry is not None:
                        names += outer.registry.names()
                    elif outer.adapters is not None:
                        names += sorted(outer.adapters)
                    self._send_json(200, {"object": "list", "data": [
                        {"id": n, "object": "model",
                         "owned_by": "fedml_tpu"} for n in names]})
                elif self.path in ("/ready", "/health"):
                    self._send_json(200, {"ready": True})
                else:
                    self._send_json(404, {"error": "not found"})

            def _sse_stream(self, make_chunk, run):
                """True streaming: chunks are flushed as generation emits
                them (``run`` is called with the per-delta writer)."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()

                def write_piece(piece: str):
                    data = json.dumps(make_chunk(piece))
                    self.wfile.write(f"data: {data}\n\n".encode())
                    self.wfile.flush()

                with get_tracer().span("serve.stream", cat="serve"):
                    run(write_piece)
                self.wfile.write(b"data: [DONE]\n\n")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self._send_json(400, {"error": "bad json"})
                    return
                rid = f"cmpl-{uuid.uuid4().hex[:24]}"
                now = int(time.time())
                # fedscope trace context: a valid W3C traceparent header
                # joins this request's span tree to the caller's trace
                # (malformed values are dropped, not propagated)
                tp_raw = self.headers.get("traceparent")
                tparent = tp_raw if (tp_raw and
                                     parse_traceparent(tp_raw)) else None
                try:
                    if self.path == "/v1/chat/completions":
                        prompt = _render_chat(req.get("messages", []))
                        if req.get("stream"):
                            self._sse_stream(
                                lambda p: {
                                    "id": rid, "object":
                                        "chat.completion.chunk",
                                    "created": now, "model": outer.model_name,
                                    "choices": [{"index": 0, "delta":
                                                 {"content": p},
                                                 "finish_reason": None}]},
                                lambda writer: outer._complete(
                                    prompt, req, on_text=writer,
                                    traceparent=tparent))
                            return
                        text = outer._complete(prompt, req,
                                               traceparent=tparent)
                        self._send_json(200, {
                            "id": rid, "object": "chat.completion",
                            "created": now, "model": outer.model_name,
                            "choices": [{"index": 0, "message":
                                         {"role": "assistant",
                                          "content": text},
                                         "finish_reason": "stop"}]})
                    elif self.path == "/v1/completions":
                        text = outer._complete(str(req.get("prompt", "")),
                                               req, traceparent=tparent)
                        self._send_json(200, {
                            "id": rid, "object": "text_completion",
                            "created": now, "model": outer.model_name,
                            "choices": [{"index": 0, "text": text,
                                         "finish_reason": "stop"}]})
                    else:
                        self._send_json(404, {"error": "not found"})
                except RequestError as e:
                    # client mistake (unknown adapter, bad field) — 4xx,
                    # not a retryable server fault
                    self._send_json(e.status, {"error": str(e)})
                except Exception as e:
                    log.exception("generation failed")
                    self._send_json(500, {"error": str(e)})

            def log_message(self, fmt, *args):
                log.debug("openai-compat: " + fmt, *args)

        return Handler

    def add_adapter(self, name: str, lora_tree) -> None:
        """Register/replace a personalization adapter (e.g. a client's
        trained LoRA from a federated round).  No recompile: the adapter
        tree is a traced argument of the shared decode program.  In
        multi-tenant engine mode this hot-swaps a bank row (in-flight
        requests on the old version finish on it — copy-on-write)."""
        if self.registry is not None:
            self.registry.register(str(name), lora_tree)
            return
        with self._swap_lock:
            if self.adapters is None:
                raise ValueError("server built without adapters= — construct "
                                 "with adapters={} (or batch_slots + "
                                 "adapter_slots) to enable personalization")
            self.adapters[str(name)] = lora_tree

    def evict_adapter(self, name: str) -> None:
        """Stop routing ``name``.  Engine mode delegates to the registry
        (in-flight requests drain on their pinned row); dict mode just
        drops the entry."""
        if self.registry is not None:
            self.registry.evict(str(name))
            return
        with self._swap_lock:
            if self.adapters is None or str(name) not in self.adapters:
                raise KeyError(f"unknown adapter {name!r}")
            del self.adapters[str(name)]

    def update_params(self, params, draft_params=None,
                      timeout: float = 60.0) -> None:
        """Swap the serving weights (federated round boundary).

        Engine mode: the swap is delegated to the batching engine, which
        applies it once in-flight requests drain (its admission pauses
        meanwhile) and clears its prefix cache atomically with the swap —
        so the engine path and the sampled fall-through path serve the
        SAME weight version once this returns.  Non-engine mode: swaps
        ``self.params`` and clears the prefix cache eagerly (its strong
        params ref would otherwise keep the old tree + stale KV resident
        until the next request).  ``draft_params`` also swaps the
        speculative draft (optional: a stale draft only lowers acceptance
        rate; greedy verification keeps outputs exact).  ``timeout``
        bounds the engine drain — size it to the slowest legal request
        (roughly ``buf_len`` x per-dispatch latency); on ``TimeoutError``
        NOTHING has been mutated, so the caller can simply retry.
        """
        if draft_params is not None and self.draft_model is None:
            # validate BEFORE mutating: a failed call must not leave the
            # fall-through path on new weights with the engine on old
            raise ValueError("draft_params given but the server was "
                             "built without draft_model")
        # engine swap FIRST, for the same reason: it can raise on a drain
        # timeout, and a failed call must leave the server fully on the
        # old version — assigning self.params before the engine landed
        # would split the sampled fall-through (new) from the engine (old)
        if self._engine is not None:
            if hasattr(self._engine, "raw_draft"):
                self._engine.update_params(params, draft_params=draft_params,
                                           timeout=timeout)
            else:
                self._engine.update_params(params, timeout=timeout)
        # the engine drain above can block for seconds — only the final
        # pointer swap runs under _swap_lock, paired with the coherent
        # snapshot HTTP workers take at the top of _complete
        with self._swap_lock:
            self.params = params
            if draft_params is not None:
                self.draft_params = draft_params
            if self._engine is None and self.prefix_cache is not None:
                self.prefix_cache.clear()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self._server = ThreadingHTTPServer((self.host, self.port),
                                           self._make_handler())
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        if self.metrics_port is not None and self.metrics_server is None:
            from ...obs.metricsd import MetricsServer
            extra, objectives = [], None
            if self._engine is not None:
                # the engine's request-lifecycle histograms append to
                # /metrics; its objective windows drive /healthz burn rates
                extra = [self._engine.serve_hists.render_prometheus]
                objectives = self._engine.slo_windows or None
            self.metrics_server = MetricsServer(
                port=int(self.metrics_port), host=self.host,
                slo_rules=self.slo_rules, extra_text=extra,
                objectives=objectives)
            self.metrics_server.start()
        log.info("openai-compatible endpoint on %s:%d", self.host, self.port)
        return self.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self._engine is not None:
            self._engine.stop()
            self._engine = None
