"""Serving templates (reference ``python/fedml/serving/templates/`` — the HF
chatbot template with its OpenAI-compatible ``main_openai.py``)."""

from .openai_compat import ByteTokenizer, OpenAICompatServer, generate

__all__ = ["ByteTokenizer", "OpenAICompatServer", "generate"]
