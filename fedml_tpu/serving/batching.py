"""Continuous-batching decode engine for the serving plane.

The reference's serving stack handles concurrency by running one request per
FastAPI worker against an HF ``generate`` call (``serving/templates/
hf_template/main_openai.py``) — concurrent requests time-share the
accelerator, each paying a full decode pass.  TPU-natively the accelerator
wants one BATCHED program: this engine keeps a fixed pool of decode slots,
runs a single jitted ``vmap``-ed KV-cache step for all live slots per tick,
and admits waiting requests into freed slots between ticks ("continuous
batching" — requests join/leave the batch at token granularity, so short
requests aren't held hostage by long ones and the MXU sees batch-B matmuls
instead of B sequential batch-1 passes).

Engine states are static-shaped throughout (slot count, buffer length), so
exactly two programs compile: the per-slot prefill and the batched step.
Per-slot KV caches live stacked on a leading slot axis and are inserted at
admission with a donated ``.at[slot].set``.

Multi-tenant LoRA (``adapter_slots``/``adapter_registry``, see
:mod:`fedml_tpu.serving.adapters` and docs/SERVING.md): N adapters live
stacked in a device-resident bank next to the ONE shared base; each slot
carries an ``adapter_id`` and the batched step computes ``base(x) +
gather(bank, slot_adapter_ids) @ x`` via grouped (slot-batched) adapter
einsums — bank capacity is static, membership is data, so serving a new
or different adapter never recompiles.

Greedy (temp=0) output is bit-identical to the single-request
:func:`fedml_tpu.serving.templates.openai_compat.generate` path (tested);
the per-request threefry key splits follow the same sequence as that path,
so sampling streams match it too.
"""

from __future__ import annotations

import dataclasses
import math
import os
import queue
import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import get_tracer
from ..obs.histogram import ServeHistograms
from .adapters import AdapterMissError, AdapterRegistry
from .paged_kv import PagedBlockPool, PagedPrefixCache, PageExhaustedError
from .templates.openai_compat import (TAIL_BLOCK, PrefixCache,
                                      _build_cached_decode,
                                      _replay_tail, _sample_live)


class PagedKVUnsupportedError(ValueError):
    """Raised at engine construction for the paged-KV × speculative
    combo: the draft/target verify blocks assume contiguous per-slot
    caches and would silently corrupt positions against a page pool.
    Use the dense speculative engine, or the paged non-speculative one."""


class _UnservableError(Exception):
    """A request whose page reservation can NEVER succeed on this pool
    (need exceeds total non-trash pages) — failed open instead of parked,
    or parking would deadlock the engine."""


def _unwrap_params(params):
    """Accept either a raw param tree or a ``{"params": tree}`` wrapper
    (the flax ``init`` convention) — one place, used by construction and
    weight-swap paths alike."""
    return params.get("params", params) if isinstance(params, dict) \
        else params


class _Slot:
    __slots__ = ("live", "q", "pos", "remaining", "eos_id", "cur_tok",
                 "adapter_row",
                 # paged-KV prefill state machine (free → prefilling →
                 # live): prompt ids + replay cursor for the chunked
                 # prefill lanes, the admission-split sample key, and the
                 # slot's block-table reservation size
                 "prefilling", "pf_ids", "pf_next", "pf_n", "pf_sub",
                 "pf_atok", "n_blocks",
                 # fedslo request-lifecycle telemetry (host monotonic
                 # clocks, engine-thread-confined like the decode state)
                 "t_submit", "t_admit", "t_prefill_end", "t_first",
                 "prompt_tokens", "out_tokens", "adapter_label",
                 "traceparent", "drafts_proposed", "drafts_accepted")

    def __init__(self):
        self.live = False
        self.q: Optional[queue.Queue] = None
        self.pos = 0
        self.remaining = 0
        self.eos_id: Optional[int] = None
        self.cur_tok = 0
        self.adapter_row = 0
        self.prefilling = False
        self.pf_ids: Optional[List[int]] = None
        self.pf_next = 0
        self.pf_n = 0
        self.pf_sub = None
        self.pf_atok = None
        self.n_blocks = 0
        self.t_submit = 0.0
        self.t_admit: Optional[float] = None
        self.t_prefill_end = 0.0
        self.t_first: Optional[float] = None
        self.prompt_tokens = 0
        self.out_tokens = 0
        self.adapter_label = "base"
        self.traceparent: Optional[str] = None
        self.drafts_proposed = 0
        self.drafts_accepted = 0


class ContinuousBatchingEngine:
    """``submit()`` returns a queue that yields generated token ids and then
    ``None``; a daemon thread drives the batched decode loop."""

    def __init__(self, model, params, slots: int = 4, buf_len: int = 256,
                 top_k: int = 0, top_p: float = 1.0, horizon: int = 1,
                 prefix_cache_slots: int = 0,
                 prefix_max_tail: int = TAIL_BLOCK,
                 adapter_registry: Optional[AdapterRegistry] = None,
                 adapter_slots: int = 0,
                 metrics_port: Optional[int] = None,
                 hist_labels: int = 8,
                 slo_rules: Optional[List[Dict[str, Any]]] = None,
                 kv_page_tokens: int = 0, kv_pool_pages: int = 0,
                 prefill_chunk_tokens: int = 0, prefill_lanes: int = 1,
                 adapter_cache_slots: int = 0,
                 adapter_store_dir: Optional[str] = None):
        self.model = model
        # fedslo (docs/OBSERVABILITY.md): per-request lifecycle histograms
        # (TTFT / e2e / queue wait / phase times / decode rate) with
        # bounded per-adapter labels (first-K + "other", hist_labels caps
        # the series count), and optional burn-rate objective streams fed
        # per finished request — host floats only, recorded on the engine
        # thread at request finish, never inside the jitted step
        self.serve_hists = ServeHistograms(max_labels=int(hist_labels))
        self.slo_windows: Dict[str, Any] = {}
        if slo_rules:
            from ..obs.slo import windows_for_rules
            self.slo_windows = windows_for_rules(slo_rules)
        # fedmon live export (docs/OBSERVABILITY.md): metrics_port serves
        # /metrics + /healthz over the global tracer's serve.* gauges
        # (0 = ephemeral; None = off); closed by stop().  The serve
        # histograms append to /metrics; the objective windows make
        # /healthz evaluate multi-window burn rates, not just point rules
        self.metrics_server = None
        if metrics_port is not None:
            from ..obs.metricsd import MetricsServer
            self.metrics_server = MetricsServer(
                port=int(metrics_port), slo_rules=slo_rules,
                extra_text=[self.serve_hists.render_prometheus],
                objectives=self.slo_windows or None)
            self.metrics_server.start()
        self.raw_params = _unwrap_params(params)
        self.n_slots = int(slots)
        self.buf_len = int(buf_len)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # multi-tenant LoRA (serving/adapters.py): an adapter bank stacked
        # on a leading axis next to the ONE shared base; each slot carries
        # an adapter_id and the batched step gathers its bank row inside
        # the compiled program — bank capacity is static, membership is
        # data, so requests landing on different adapters never recompile.
        # ``adapter_slots=N`` builds a capacity-N registry; passing
        # ``adapter_registry`` shares one bank across engines.
        # adapter cache mode (serving/adapter_store.py, docs/SERVING.md):
        # ``adapter_cache_slots=N`` demotes the bank to an N-row HBM
        # cache over a host/disk adapter store — registered adapters
        # scale past HBM like client state did (fedstore), misses page
        # in asynchronously and the request requeues.  Pins are deferred
        # to admission (the engine thread owns install/evict).
        self.registry = adapter_registry
        self._owns_registry = False
        if adapter_cache_slots and self.registry is None:
            from .adapter_store import AdapterStore
            store = AdapterStore(
                model, spill_dir=adapter_store_dir,
                max_resident_pages=(16 if adapter_store_dir else 0))
            self.registry = AdapterRegistry(
                model, capacity=int(adapter_cache_slots), store=store)
            self._owns_registry = True
        elif adapter_slots and self.registry is None:
            self.registry = AdapterRegistry(model, capacity=int(adapter_slots))
            self._owns_registry = True
        self._store_mode = (self.registry is not None
                            and self.registry.store is not None)
        if self._store_mode:
            self.registry.on_fetch_done = self._on_adapter_fetched
        # decode horizon: tokens generated per device dispatch.  horizon=1 is
        # token-granularity admission (lowest queueing latency); horizon=H
        # runs H steps as one lax.scan on-device so per-token host round-trip
        # cost (dominant over a network-attached TPU) amortizes H-fold.  The
        # per-step computation is the identical scanned body, so outputs are
        # bit-equal to horizon=1 for every request; requests only join the
        # batch every H tokens, and a slot that hits eos/budget mid-horizon
        # burns its remaining lanes (discarded on host, cache overwritten at
        # next admission).
        self.horizon = max(1, int(horizon))

        # paged KV (serving/paged_kv.py, docs/SERVING.md memory plane):
        # kv_page_tokens>0 replaces the per-slot stacked caches with ONE
        # page pool per layer + a per-slot block table carried as traced
        # data.  Admission reserves ceil(min(n+max_new, buf_len)/P)
        # pages host-side (parking the request when the pool is dry);
        # prefill runs in fixed prefill_chunk_tokens chunks on a per-tick
        # lane budget so long prompts stop head-of-line-blocking decode.
        self.kv_page_tokens = int(kv_page_tokens)
        self.paged = self.kv_page_tokens > 0
        self.paged_model = None
        self.page_pool = None
        if self.paged:
            cfg = getattr(model, "cfg", None)
            if cfg is None or not hasattr(cfg, "kv_page_tokens"):
                raise PagedKVUnsupportedError(
                    "paged KV needs a LlamaLM-style model carrying a "
                    "LlamaConfig (engine rebuilds it with the pool "
                    "geometry)")
            ptok = self.kv_page_tokens
            self.prefill_chunk = int(prefill_chunk_tokens) or \
                min(64, self.buf_len)
            self.prefill_lanes = max(1, int(prefill_lanes))
            # per-slot block-table width: the window covers buf_len plus
            # the worst chunk-padding / horizon-burn overhang, so every
            # out-of-reservation write lands on a real (trash) table
            # entry instead of index-clamping into a live page
            overhang = max(self.prefill_chunk, self.horizon)
            self.max_blocks = math.ceil((self.buf_len + overhang) / ptok)
            # pages a single slot may ever RESERVE (positions < buf_len)
            self.blocks_cap = math.ceil(self.buf_len / ptok)
            pool_pages = int(kv_pool_pages) or \
                (1 + self.n_slots * self.blocks_cap)
            self.kv_pool_pages = pool_pages
            self.paged_model = type(model)(dataclasses.replace(
                cfg, kv_page_tokens=ptok, kv_pool_pages=pool_pages))
            self.page_pool = PagedBlockPool(pool_pages)
            self._btabs = np.zeros((self.n_slots, self.max_blocks),
                                   np.int32)
            self._chunks_total = 0
            self._pages_shared = 0
            self._pages_private = 0

        self._prefill, self._tail_step, self._tail_block = \
            _build_cached_decode(model, self.top_k, self.top_p)
        # prefix_cache_slots > 0: admission reuses prefill KV for shared
        # prompt prefixes (templates/openai_compat.PrefixCache — LRU,
        # longest-common-prefix, params-identity invalidation); only the
        # engine thread touches it during _admit, but the cache carries
        # its own lock anyway.  Paged engines share *pages* instead of
        # copying KV: PagedPrefixCache lends refcounted full pages into
        # the new slot's block table, and the chunk replay starts past
        # the shared span, so lent pages stay read-only under sharers.
        self.prefix_cache = None
        if prefix_cache_slots:
            if self.paged:
                self.prefix_cache = PagedPrefixCache(
                    prefix_cache_slots, self.kv_page_tokens,
                    self.page_pool)
            else:
                self.prefix_cache = PrefixCache(prefix_cache_slots,
                                                max_tail=int(prefix_max_tail))

        from ..llm.quantization import dequantize_params, weight_dtype
        wdtype = weight_dtype(model)

        @jax.jit
        def batched_step(params, caches, toks, poss, keys, temps):
            # int8-quantized trees dequantize inside the trace (stays int8
            # in HBM; per-matmul dequant fuses) — no-op for plain trees
            params = dequantize_params(params, wdtype)

            def one(cache, tok, pos, key, temp):
                logits, mut = model.apply(
                    {"params": params, "cache": cache}, tok[None, None],
                    decode=True, start_pos=pos, mutable=["cache"])
                key, sub = jax.random.split(key)
                nxt = _sample_live(logits[0, 0], sub, temp, self.top_k,
                                   self.top_p)
                return nxt, mut["cache"], key

            def body(carry, _):
                caches, toks, poss, keys = carry
                toks, caches, keys = jax.vmap(one)(
                    caches, toks, poss, keys, temps)
                return (caches, toks, poss + 1, keys), toks

            (caches, toks, poss, keys), hist = jax.lax.scan(
                body, (caches, toks, poss, keys), None, length=self.horizon)
            # hist: (horizon, n_slots) → host iterates per-slot rows
            return hist.T, caches, keys

        @jax.jit
        def batched_step_mt(params, bank, caches, toks, poss, keys, temps,
                            aids):
            params = dequantize_params(params, wdtype)
            # gather(bank, slot_adapter_ids) — one batched gather per lora
            # leaf; the vmapped apply then runs the adapter matmuls
            # slot-batched against the shared base (grouped einsums after
            # vmap batching).  bank + aids are traced arguments: any
            # request→adapter assignment reuses this one program.
            lora_slots = jax.tree_util.tree_map(lambda b: b[aids], bank)

            def one(cache, tok, pos, key, temp, lora):
                logits, mut = model.apply(
                    {"params": params, "lora": lora, "cache": cache},
                    tok[None, None], decode=True, start_pos=pos,
                    mutable=["cache"])
                key, sub = jax.random.split(key)
                nxt = _sample_live(logits[0, 0], sub, temp, self.top_k,
                                   self.top_p)
                return nxt, mut["cache"], key

            def body(carry, _):
                caches, toks, poss, keys = carry
                toks, caches, keys = jax.vmap(one)(
                    caches, toks, poss, keys, temps, lora_slots)
                return (caches, toks, poss + 1, keys), toks

            (caches, toks, poss, keys), hist = jax.lax.scan(
                body, (caches, toks, poss, keys), None, length=self.horizon)
            return hist.T, caches, keys

        self._step = batched_step if self.registry is None \
            else batched_step_mt

        if self.paged:
            pm = self.paged_model

            @partial(jax.jit, donate_argnums=(1,))
            def paged_step(params, pool, btabs, toks, poss, keys, temps):
                # ONE batched apply against the shared pool — no vmap:
                # every slot addresses its own pages via the traced block
                # tables, per-slot depths ride the (b,) start_pos vector.
                # The per-slot key splits replay the dense engine's
                # sequence exactly (split[0]=carry, split[1]=sample).
                params = dequantize_params(params, wdtype)

                def body(carry, _):
                    pool, toks, poss, keys = carry
                    logits, mut = pm.apply(
                        {"params": params, "cache": pool}, toks[:, None],
                        decode=True, start_pos=poss, block_tables=btabs,
                        mutable=["cache"])
                    split = jax.vmap(jax.random.split)(keys)
                    keys2, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda lg, sub, temp: _sample_live(
                            lg, sub, temp, self.top_k, self.top_p)
                    )(logits[:, 0], subs, temps)
                    return (mut["cache"], nxt, poss + 1, keys2), nxt

                (pool, toks, poss, keys), hist = jax.lax.scan(
                    body, (pool, toks, poss, keys), None,
                    length=self.horizon)
                return hist.T, pool, keys

            @partial(jax.jit, donate_argnums=(2,))
            def paged_step_mt(params, bank, pool, btabs, toks, poss, keys,
                              temps, aids):
                params = dequantize_params(params, wdtype)
                lora_slots = jax.tree_util.tree_map(
                    lambda b: b[aids], bank)

                def body(carry, _):
                    pool, toks, poss, keys = carry
                    logits, mut = pm.apply(
                        {"params": params, "lora": lora_slots,
                         "cache": pool}, toks[:, None],
                        decode=True, start_pos=poss, block_tables=btabs,
                        mutable=["cache"])
                    split = jax.vmap(jax.random.split)(keys)
                    keys2, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda lg, sub, temp: _sample_live(
                            lg, sub, temp, self.top_k, self.top_p)
                    )(logits[:, 0], subs, temps)
                    return (mut["cache"], nxt, poss + 1, keys2), nxt

                (pool, toks, poss, keys), hist = jax.lax.scan(
                    body, (pool, toks, poss, keys), None,
                    length=self.horizon)
                return hist.T, pool, keys

            @partial(jax.jit, donate_argnums=(2,))
            def paged_chunk(params, lora, pool, chunk, btab, start, idx,
                            key, temp):
                # one fixed-shape (1, C) prefill chunk for one slot; the
                # sample index is TRACED so intermediate chunks (token
                # discarded) and the final chunk (token at n-1-chunk_start)
                # ride one compiled program
                params = dequantize_params(params, wdtype)
                variables = {"params": params, "cache": pool}
                if lora is not None:
                    variables["lora"] = lora
                logits, mut = pm.apply(
                    variables, chunk, decode=True, start_pos=start,
                    block_tables=btab, mutable=["cache"])
                tok = _sample_live(logits[0, idx], key, temp, self.top_k,
                                   self.top_p)
                return tok, mut["cache"]

            self._step = paged_step if self.registry is None \
                else paged_step_mt
            self._chunk = paged_chunk

        @partial(jax.jit, donate_argnums=(0,))
        def insert_cache(caches, cache, slot):
            return jax.tree_util.tree_map(
                lambda all_c, c: all_c.at[slot].set(c), caches, cache)

        self._insert = insert_cache

        dummy_lora = (self.registry.lora_for_row(0)
                      if self.registry is not None else None)
        if self.paged:
            # materialize the page pool from the chunk program's shape —
            # eval_shape only, nothing dense ever allocates
            self._caches = None
            chunk0 = jnp.zeros((1, self.prefill_chunk), jnp.int32)
            btab0 = jnp.zeros((1, self.max_blocks), jnp.int32)

            def _shape_probe(p):
                variables = {"params": p}
                if dummy_lora is not None:
                    variables["lora"] = dummy_lora
                return self.paged_model.apply(
                    variables, chunk0, decode=True,
                    start_pos=jnp.zeros((1,), jnp.int32),
                    block_tables=btab0, mutable=["cache"])

            _, shapes = jax.eval_shape(_shape_probe, self.raw_params)
            self._pool = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
        else:
            # materialize the stacked cache template from one dummy
            # prefill (MT engines pass the zero bank row — a lora_rank>0
            # model can't apply without its "lora" collection)
            dummy = jnp.zeros((1, self.buf_len), jnp.int32)
            _, cache0 = self._prefill(self.raw_params, dummy_lora, dummy,
                                      jnp.int32(1), jax.random.PRNGKey(0),
                                      jnp.float32(0.0))
            self._caches = jax.tree_util.tree_map(
                lambda c: jnp.zeros((self.n_slots,) + c.shape, c.dtype),
                cache0)

        self._slots = [_Slot() for _ in range(self.n_slots)]
        self._toks = np.zeros(self.n_slots, np.int32)
        self._poss = np.zeros(self.n_slots, np.int32)
        self._temps = np.zeros(self.n_slots, np.float32)
        self._aids = np.zeros(self.n_slots, np.int32)
        self._keys = np.stack(
            [np.asarray(jax.random.PRNGKey(i)) for i in range(self.n_slots)])
        self._waiting: "queue.Queue[dict]" = queue.Queue()
        # requests pulled off _waiting but not admittable yet (adapter
        # page-in in flight, page pool dry) — engine-thread-confined,
        # retried at the top of every iteration before new admissions
        self._parked: List[dict] = []
        # set (under _cond) by the adapter fetch worker; cleared by the
        # engine's parked-retry pass
        self._fetch_ready = False
        # engine-thread flag: a slot finish released an adapter pin (or
        # pages) — a parked request whose install lost to an all-pinned
        # cache must retry now, even with nothing live to keep the loop
        # ticking.  Cleared with _fetch_ready by the retry pass.
        self._pin_released = False
        self._cond = threading.Condition()
        self._stopped = False
        # weight swap staged by update_params(); applied by the engine
        # thread once live slots drain (admission pauses meanwhile)
        self._pending_params = None
        self._ticks = 0  # batched steps executed (observability)
        # host-side serving telemetry (always maintained; mirrored onto
        # fedtrace counters when tracing is on — host ints only, the
        # engine never adds a device sync for observability)
        self.serve_stats: Dict[str, Any] = {
            "admits": 0, "tokens": 0, "requests": {}}
        self._tok_window = [time.monotonic(), 0]
        # guards serve_stats/_tok_window (engine thread increments, HTTP
        # submit() and metrics scrapes read).  Strictly innermost: taken
        # with nothing else held, or nested inside _cond — never the
        # reverse, so it can never extend the lock-order graph into a cycle
        self._stats_lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- public api --------------------------------------------------------
    def submit(self, prompt_ids: List[int], max_new_tokens: int = 64,
               temperature: float = 0.0, seed: int = 0,
               eos_id: Optional[int] = None,
               adapter: Optional[str] = None,
               traceparent: Optional[str] = None) -> "queue.Queue":
        """Enqueue a request; returns a queue yielding token ids then
        ``None``.  ``adapter`` names a registered bank row (multi-tenant
        engines only; ``KeyError`` for unknown names) — the row is pinned
        until the request finishes, so an eviction or re-registration
        mid-stream can never change the weights under an in-flight slot.
        ``traceparent`` (W3C header value) joins the request's span tree
        to the caller's fedscope trace."""
        out: "queue.Queue" = queue.Queue()
        row, atok = 0, None
        if self._store_mode:
            # cache mode: validate the name against the store here (so
            # unknown adapters still fail the caller) but defer the PIN
            # to admission — the engine thread owns page-in/install, and
            # a miss parks the request instead of blocking submit
            if adapter is not None and adapter not in self.registry:
                raise KeyError(f"unknown adapter {adapter!r}; have "
                               f"{self.registry.names()}")
        elif self.registry is not None:
            # resolve at submit so unknown adapters fail the caller, not
            # the engine thread; the pin travels with the request
            row, atok = self.registry.acquire(adapter)
        elif adapter:
            raise ValueError("engine built without an adapter registry "
                             f"(adapter_slots=0) — cannot route {adapter!r}")
        # the put happens under _cond so it cannot interleave with the
        # shutdown/crash drain (which also holds _cond): either the request
        # lands before the drain and receives its sentinel, or the stopped
        # flag is already visible here and we raise
        try:
            with self._cond:
                if self._stopped or not self._thread.is_alive():
                    raise RuntimeError("engine stopped")
                name = adapter if adapter is not None else "base"
                self._waiting.put({
                    "prompt_ids": list(prompt_ids)[-(self.buf_len - 1):],
                    "max_new_tokens": int(max_new_tokens),
                    "temperature": float(temperature),
                    "seed": int(seed),
                    "eos_id": eos_id,
                    "adapter": adapter,
                    "adapter_row": row,
                    "adapter_token": atok,
                    "adapter_label": name,
                    "traceparent": traceparent,
                    "t_submit": time.monotonic(),
                    "q": out,
                })
                with self._stats_lock:   # _cond -> _stats_lock, never reversed
                    reqs = self.serve_stats["requests"]
                    reqs[name] = reqs.get(name, 0) + 1
                    nreq = reqs[name]
                # bounded-cardinality request counter: ONE metric with an
                # adapter label (capped at hist_labels + "other"), replacing
                # PR 9's per-adapter metric NAMES which grew one series per
                # registered adapter.  The old names re-appear only behind
                # the deprecation flag, kept for one release.
                label, label_n = self.serve_hists.labels.resolve(name)
                tracer = get_tracer()
                if tracer.enabled:
                    tracer.counter("serve.requests_by_adapter", label_n,
                                   adapter=label)
                    if os.environ.get(
                            "FEDML_SERVE_LEGACY_ADAPTER_COUNTERS") == "1":
                        tracer.counter(f"serve.requests.{name}", nreq)
                self._cond.notify()
        except BaseException:
            if self.registry is not None:
                self.registry.release(row)
            raise
        return out

    def generate(self, prompt_ids: List[int], **kw) -> List[int]:
        """Blocking convenience wrapper over :meth:`submit`."""
        q = self.submit(prompt_ids, **kw)
        out: List[int] = []
        while True:
            t = q.get()
            if t is None:
                return out
            out.append(t)

    def update_params(self, params, wait: bool = True,
                      timeout: float = 60.0) -> None:
        """Swap the serving weights (federated round boundary).

        The swap is staged and applied by the engine thread only once the
        in-flight slots drain — admission pauses while a swap is pending —
        so every request is served end-to-end by exactly one weight
        version (no mid-stream weight change, no old-weights engine vs
        new-weights fall-through split).  The engine's prefix cache is
        cleared atomically with the swap.  Same-structure trees reuse the
        compiled programs (params are traced arguments).  ``wait=True``
        blocks until the swap lands; the drain is bounded by in-flight
        ``max_new_tokens`` budgets.
        """
        raw = _unwrap_params(params)
        with self._cond:
            if self._stopped or not self._thread.is_alive():
                raise RuntimeError("engine stopped")
            self._pending_params = raw
            self._cond.notify_all()
            if not wait:
                return
            deadline = time.monotonic() + timeout
            while self._pending_params is not None:
                if self._stopped or not self._thread.is_alive():
                    raise RuntimeError("engine stopped during weight swap")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        "weight swap did not land within "
                        f"{timeout}s (in-flight requests still draining)")
                self._cond.wait(timeout=min(0.5, remaining))

    def _on_swap(self) -> None:
        """Hook run (under ``_cond``) when the staged swap is applied —
        the speculative subclass swaps its draft tree here."""

    def _on_adapter_fetched(self, name: str) -> None:
        """Fetch-worker callback (cache mode): wake the engine so parked
        adapter-miss requests retry immediately."""
        with self._cond:
            self._fetch_ready = True
            self._cond.notify()

    def stop(self):
        self._stopped = True
        with self._cond:
            self._cond.notify()
        self._thread.join(timeout=10)
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self._owns_registry and self.registry is not None:
            self.registry.close()

    def step_programs(self):
        """fedverify hook (ISSUE 10, docs/FEDVERIFY.md): the engine's
        compiled programs as ``(name, jitted_fn, args, donate_argnums)``
        on their resting buffer shapes, so the contract checker can
        AOT-lower them without serving a request.  ``decode_step`` is the
        per-tick batched decode ``_dispatch`` launches; ``insert_cache``
        is admission's donated slot write."""
        toks = jnp.asarray(self._toks)
        poss = jnp.asarray(self._poss)
        keys = jnp.asarray(self._keys)
        temps = jnp.asarray(self._temps)
        if self.paged:
            # paged memory plane: the decode step donates the page pool
            # (argnum after params[/bank]) and the chunk program is the
            # third compiled citizen — both pinned so a page-geometry
            # change shows up as a contract diff, not a silent regression
            btabs = jnp.asarray(self._btabs)
            if self.registry is not None:
                step_args = (self.raw_params, self.registry.bank,
                             self._pool, btabs, toks, poss, keys, temps,
                             jnp.asarray(self._aids))
                step_donate = (2,)
            else:
                step_args = (self.raw_params, self._pool, btabs, toks,
                             poss, keys, temps)
                step_donate = (1,)
            lora = (self.registry.lora_for_row(0)
                    if self.registry is not None else None)
            chunk_args = (self.raw_params, lora, self._pool,
                          jnp.zeros((1, self.prefill_chunk), jnp.int32),
                          jnp.zeros((1, self.max_blocks), jnp.int32),
                          jnp.zeros((1,), jnp.int32), jnp.int32(0),
                          jax.random.PRNGKey(0), jnp.float32(0.0))
            return [
                ("decode_step", self._step, step_args, step_donate),
                ("prefill_chunk", self._chunk, chunk_args, (2,)),
            ]
        if self.registry is not None:
            step_args = (self.raw_params, self.registry.bank, self._caches,
                         toks, poss, keys, temps, jnp.asarray(self._aids))
        else:
            step_args = (self.raw_params, self._caches, toks, poss, keys,
                         temps)
        cache0 = jax.tree_util.tree_map(lambda c: c[0], self._caches)
        return [
            ("decode_step", self._step, step_args, ()),
            ("insert_cache", self._insert,
             (self._caches, cache0, jnp.int32(0)), (0,)),
        ]

    # -- engine loop -------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if not s.live and not s.prefilling:
                return i
        return None

    def _finish(self, i: int, aborted: bool = False):
        s = self._slots[i]
        if not aborted and s.t_admit is not None:
            self._observe_finish(i, s)
        s.t_admit = None
        s.live = False
        s.prefilling = False
        s.pf_ids = None
        s.pf_sub = None
        if self.paged and s.n_blocks:
            # drop the slot's hold on its block-table pages (shared
            # prefix pages survive under the cache / other sharers)
            self.page_pool.release(
                [int(p) for p in self._btabs[i, :s.n_blocks]])
            self._btabs[i, :] = 0  # fedrace: disable=unguarded-shared-write
            s.n_blocks = 0
        if s.q is not None:
            s.q.put(None)
        s.q = None
        if self.registry is not None and s.adapter_row:
            self.registry.release(s.adapter_row)
            s.adapter_row = 0
        # fedrace: disable-next-line=unguarded-shared-write
        self._pin_released = True

    def _observe_finish(self, i: int, s: "_Slot") -> None:
        """fedslo request-lifecycle telemetry at natural completion
        (engine thread, host clocks only — the jitted step is untouched):
        the phase breakdown lands in the serve histograms, the objective
        windows, and — when tracing is on — a retroactive span tree on a
        per-slot synthetic lane (same-slot requests never overlap, so
        B/E pairing survives the export's timestamp sort)."""
        now = time.monotonic()
        queue_s = max(s.t_admit - s.t_submit, 0.0)
        prefill_s = max(s.t_prefill_end - s.t_admit, 0.0)
        e2e_s = max(now - s.t_submit, 0.0)
        decode_s = max(now - s.t_prefill_end, 0.0)
        ttft_s = max(s.t_first - s.t_submit, 0.0) \
            if s.t_first is not None else None
        self.serve_hists.record_request(
            s.adapter_label, queue_s=queue_s, prefill_s=prefill_s,
            e2e_s=e2e_s, ttft_s=ttft_s, decode_s=decode_s,
            output_tokens=s.out_tokens)
        for win in self.slo_windows.values():
            v = {"serve_ttft_seconds": ttft_s,
                 "serve_e2e_seconds": e2e_s,
                 "serve_queue_wait_seconds": queue_s,
                 "serve_prefill_seconds": prefill_s,
                 "serve_decode_seconds": decode_s}.get(win.metric)
            if v is not None:
                win.observe(v)
        tracer = get_tracer()
        if not tracer.enabled:
            return
        lane = -16 - i  # per-slot synthetic lane, clear of COMPILE_TID
        tracer.complete(
            "serve.request", e2e_s, cat="serve", tid=lane,
            adapter=s.adapter_label, slot=i,
            prompt_tokens=s.prompt_tokens, output_tokens=s.out_tokens,
            queue_s=round(queue_s, 6), prefill_s=round(prefill_s, 6),
            ttft_s=round(ttft_s, 6) if ttft_s is not None else None,
            decode_s=round(decode_s, 6), e2e_s=round(e2e_s, 6),
            traceparent=s.traceparent,
            drafts_proposed=s.drafts_proposed or None,
            drafts_accepted=(s.drafts_accepted if s.drafts_proposed
                             else None))
        tracer.complete("serve.queue", queue_s, cat="serve", tid=lane,
                        end_s_ago=max(e2e_s - queue_s, 0.0), slot=i)
        tracer.complete("serve.decode", decode_s, cat="serve", tid=lane,
                        slot=i)

    def _emit(self, i: int, tok: int) -> bool:
        """Deliver one sampled token; returns False when the slot is done
        (eos / budget / buffer end).  Delivery rules mirror ``generate()``
        exactly: eos is not delivered, nor is a token whose successor
        position would fall outside the buffer window."""
        s = self._slots[i]
        if s.remaining <= 0 or s.pos >= self.buf_len:
            return False
        if s.eos_id is not None and tok == s.eos_id:
            return False
        s.q.put(tok)
        s.remaining -= 1
        s.cur_tok = tok
        if s.t_first is None:
            s.t_first = time.monotonic()
        s.out_tokens += 1
        with self._stats_lock:
            self.serve_stats["tokens"] += 1
            self._tok_window[1] += 1
        return s.remaining > 0 and s.pos < self.buf_len

    def _admit(self, req: dict, slot: int):
        t_admit = time.monotonic()
        ids = req["prompt_ids"]
        n = len(ids)
        buf = np.zeros((1, self.buf_len), np.int32)
        buf[0, :n] = ids
        key = jax.random.PRNGKey(req["seed"])
        temp = jnp.float32(req["temperature"])
        # multi-tenant: prefill against the request's gathered bank row
        # (row 0 = the zero adapter for base traffic, so the lora arg is
        # ALWAYS a tree on MT engines — one compiled prefill).  The prefix
        # cache keys on the registration token, not the gathered tree
        # (fresh identity per gather): KV computed under one adapter
        # version can never serve another.
        row = req.get("adapter_row", 0)
        atok = req.get("adapter_token")
        lora = (self.registry.lora_for_row(row)
                if self.registry is not None else None)
        hit_len, hit_cache = (self.prefix_cache.lookup(ids, self.raw_params,
                                                       atok)
                              if self.prefix_cache is not None and n > 0
                              else (0, None))
        # serve.prefill is the one LIVE phase span (nests under the
        # caller's serve.admit); it closes on the int() below — the
        # engine's pre-existing sync point, not a new one
        with get_tracer().span("serve.prefill", cat="serve", slot=slot,
                               prompt_tokens=n,
                               cache_hit=int(hit_cache is not None)):
            if hit_cache is not None:
                # shared replay discipline (openai_compat._replay_tail):
                # exact hits rewrite only the last position (idempotent);
                # fitting multi-token tails replay as ONE tail_block
                # dispatch
                cache = hit_cache
                start = min(hit_len, n - 1)
                max_seq = getattr(getattr(self.model, "cfg", None),
                                  "max_seq_len", self.buf_len)
                tok, cache, key = _replay_tail(
                    partial(self._tail_step, self.raw_params, lora),
                    partial(self._tail_block, self.raw_params, lora),
                    cache, jnp.asarray(buf), ids, start, n, max_seq, key,
                    temp)
            else:
                key, sub = jax.random.split(key)
                tok, cache = self._prefill(self.raw_params, lora,
                                           jnp.asarray(buf), jnp.int32(n),
                                           sub, temp)
            tok_host = int(tok)
        t_prefill_end = time.monotonic()
        if self.prefix_cache is not None and n > 0:
            # the cache object is internally locked; the reference itself
            # is set once in the ctor and never rebound
            # fedrace: disable-next-line=unguarded-shared-write
            self.prefix_cache.insert(ids, cache, self.raw_params, atok)
        # decode-state arrays (_caches/_aids/_temps/_keys, and _toks/_poss
        # in _dispatch) are engine-thread-confined: written only between
        # dispatches on the engine thread, never touched by submit()/HTTP
        # threads, so they need no lock despite living next to shared state
        # fedrace: disable-next-line=unguarded-shared-write
        self._caches = self._insert(self._caches, cache, jnp.int32(slot))
        s = self._slots[slot]
        s.live = True
        s.q = req["q"]
        s.pos = n
        s.remaining = req["max_new_tokens"]
        s.eos_id = req["eos_id"]
        s.adapter_row = row
        # request-lifecycle telemetry (engine-thread-confined, read back
        # by _observe_finish): host clocks + counts only
        s.t_submit = req.get("t_submit", t_admit)
        s.t_admit = t_admit
        s.t_prefill_end = t_prefill_end
        s.t_first = None
        s.prompt_tokens = n
        s.out_tokens = 0
        s.adapter_label = req.get("adapter_label", "base")
        s.traceparent = req.get("traceparent")
        s.drafts_proposed = 0
        s.drafts_accepted = 0
        self._aids[slot] = row  # fedrace: disable=unguarded-shared-write
        self._temps[slot] = req["temperature"]  # fedrace: disable=unguarded-shared-write
        self._keys[slot] = np.asarray(key)  # fedrace: disable=unguarded-shared-write
        if not self._emit(slot, tok_host):
            self._finish(slot)

    # -- paged admission ---------------------------------------------------
    def _reserve_pages(self, req: dict, slot: int) -> None:
        """Wire ``slot``'s block table: longest shareable prefix pages
        (incref'd) + fresh private pages for the rest of the request's
        worst-case window.  Raises :class:`PageExhaustedError` when the
        pool is dry (caller parks) and :class:`_UnservableError` when the
        reservation can never fit (caller fails the request open)."""
        ids = req["prompt_ids"]
        n = len(ids)
        ptok = self.kv_page_tokens
        need = min(n + req["max_new_tokens"], self.buf_len)
        need_blocks = max(1, math.ceil(need / ptok))
        if need_blocks > self.page_pool.n_pages - 1:
            raise _UnservableError(
                f"request needs {need_blocks} pages; pool has "
                f"{self.page_pool.n_pages - 1} usable")
        atok = req.get("adapter_token")
        full, shared = (self.prefix_cache.lookup(ids, self.raw_params, atok)
                        if self.prefix_cache is not None and n > 0
                        else (0, []))
        # incref the lent pages FIRST: evict_for_pages below may drop the
        # very entry we matched, and only our hold keeps its pages alive
        self.page_pool.share(shared)
        priv = need_blocks - full
        try:
            if not self.page_pool.can_reserve(priv) \
                    and self.prefix_cache is not None:
                self.prefix_cache.evict_for_pages(priv)
            pages = self.page_pool.reserve(priv)
        except PageExhaustedError:
            self.page_pool.release(shared)
            raise
        self._btabs[slot, :] = 0  # fedrace: disable=unguarded-shared-write
        self._btabs[slot, :full] = shared
        self._btabs[slot, full:need_blocks] = pages
        req["_kv"] = (full, need_blocks)
        with self._stats_lock:  # kv_stats() reads from caller threads
            self._pages_shared += full
            self._pages_private += priv

    def _admit_paged(self, req: dict, slot: int) -> None:
        """Enter the prefilling state (free → prefilling): block table is
        already wired by ``_reserve_pages``; the chunk lanes in
        ``_prefill_tick`` replay the prompt from the shared-page boundary
        and flip the slot live on the final chunk."""
        t_admit = time.monotonic()
        ids = req["prompt_ids"]
        n = len(ids)
        full, need_blocks = req.pop("_kv")
        key = jax.random.PRNGKey(req["seed"])
        # same split sequence as the dense prefill path: sub samples the
        # first token (on the final chunk), key carries into decode
        key, sub = jax.random.split(key)
        s = self._slots[slot]
        s.prefilling = True
        s.live = False
        s.q = req["q"]
        s.pos = 0
        s.remaining = req["max_new_tokens"]
        s.eos_id = req["eos_id"]
        s.cur_tok = 0
        s.adapter_row = req.get("adapter_row", 0)
        s.pf_ids = ids
        s.pf_n = n
        s.pf_next = full * self.kv_page_tokens
        s.pf_sub = sub
        s.pf_atok = req.get("adapter_token")
        s.n_blocks = need_blocks
        s.t_submit = req.get("t_submit", t_admit)
        s.t_admit = t_admit
        s.t_prefill_end = t_admit
        s.t_first = None
        s.prompt_tokens = n
        s.out_tokens = 0
        s.adapter_label = req.get("adapter_label", "base")
        s.traceparent = req.get("traceparent")
        s.drafts_proposed = 0
        s.drafts_accepted = 0
        self._aids[slot] = s.adapter_row  # fedrace: disable=unguarded-shared-write
        self._temps[slot] = req["temperature"]  # fedrace: disable=unguarded-shared-write
        self._keys[slot] = np.asarray(key)  # fedrace: disable=unguarded-shared-write

    def _prefill_tick(self) -> None:
        """Run up to ``prefill_lanes`` fixed-shape prefill chunks, one per
        prefilling slot — chunked prefill shares the tick with decode, so
        a 4k-token prompt costs each tick one chunk, not a stall."""
        lanes = self.prefill_lanes
        C = self.prefill_chunk
        for i, s in enumerate(self._slots):
            if lanes <= 0:
                break
            if not s.prefilling:
                continue
            lanes -= 1
            cs = s.pf_next
            n = s.pf_n
            chunk = np.zeros((1, C), np.int32)
            seg = s.pf_ids[cs:cs + C]
            chunk[0, :len(seg)] = seg
            final = cs + C >= n
            # sample index is traced: intermediate chunks discard token 0,
            # the final chunk samples at the prompt's last position
            idx = max(n - 1 - cs, 0) if final else 0
            lora = (self.registry.lora_for_row(s.adapter_row)
                    if self.registry is not None else None)
            tok, self._pool = self._chunk(
                self.raw_params, lora, self._pool, jnp.asarray(chunk),
                jnp.asarray(self._btabs[i][None]),
                jnp.asarray([cs], jnp.int32), jnp.int32(idx), s.pf_sub,
                jnp.float32(self._temps[i]))
            with self._stats_lock:
                self._chunks_total += 1
            if not final:
                s.pf_next = cs + C
                continue
            tok_host = int(tok)
            s.prefilling = False
            s.live = True
            s.pos = n
            s.t_prefill_end = time.monotonic()
            if self.prefix_cache is not None and n > 0:
                fullpages = n // self.kv_page_tokens
                if fullpages:
                    self.prefix_cache.insert(
                        s.pf_ids,
                        [int(p) for p in self._btabs[i, :fullpages]],
                        self.raw_params, s.pf_atok)
            s.pf_ids = None
            s.pf_sub = None
            if not self._emit(i, tok_host):
                self._finish(i)

    def _admit_one(self, req: dict, slot: int, tracer) -> bool:
        """Admission front door for both engines: cache-mode adapter pin
        (deferred from submit) + paged page reservation, then the real
        admit.  Returns False when the request parked (adapter page-in in
        flight / pool dry) or failed open — the slot stays free."""
        try:
            if (self._store_mode and req.get("adapter") is not None
                    and req.get("adapter_token") is None):
                row, atok = self.registry.acquire(req["adapter"])
                req["adapter_row"], req["adapter_token"] = row, atok
            if self.paged:
                self._reserve_pages(req, slot)
        except AdapterMissError:
            req["_park_reason"] = "adapter"
            self._parked.append(req)
            return False
        except PageExhaustedError:
            # drop a just-taken pin so the row isn't held while parked
            if self._store_mode and req.get("adapter_row"):
                self.registry.release(req["adapter_row"])
                req["adapter_row"], req["adapter_token"] = 0, None
            req["_park_reason"] = "pages"
            self._parked.append(req)
            return False
        except (_UnservableError, KeyError, RuntimeError):
            # unservable reservation, adapter evicted between submit and
            # admission, or a fetch failure re-raised from take(): fail
            # this request open, keep the engine alive
            if self._store_mode and req.get("adapter_row"):
                self.registry.release(req["adapter_row"])
            req["q"].put(None)
            return False
        with tracer.span("serve.admit", cat="serve", slot=slot,
                         adapter_row=req.get("adapter_row", 0)):
            if self.paged:
                self._admit_paged(req, slot)
            else:
                self._admit(req, slot)
        with self._stats_lock:
            self.serve_stats["admits"] += 1
        return True

    def _parked_actionable(self) -> bool:
        """Caller holds ``_cond``: is a parked retry worth waking for?
        Page-parked requests retry whenever pages may have freed (any
        finish notifies); adapter-parked ones only once a fetch landed."""
        if not self._parked:
            return False
        if self._fetch_ready or self._pin_released:
            return True
        return any(r.get("_park_reason") == "pages" for r in self._parked)

    def kv_stats(self) -> Dict[str, Any]:
        """Host-side memory-plane stats (bench + tests): pool occupancy,
        chunk counts, prefix page-sharing, adapter cache counters."""
        with self._stats_lock:
            out: Dict[str, Any] = {"ticks": self._ticks}
            chunks = self._chunks_total
            shared, private = self._pages_shared, self._pages_private
        if self.paged:
            out["pool"] = dict(self.page_pool.stats)
            out["pages_free"] = self.page_pool.pages_free
            out["pool_pages"] = self.page_pool.n_pages
            out["prefill_chunks"] = chunks
            out["pages_shared"] = shared
            out["pages_private"] = private
            if self.prefix_cache is not None:
                out["prefix"] = dict(self.prefix_cache.stats)
        if self.registry is not None:
            out["adapter"] = dict(self.registry.stats)
        return out

    def _drain_waiting(self):
        """Fail-open every queued AND parked request (caller holds
        ``_cond``), dropping adapter pins so evicted rows can still
        reclaim."""
        while not self._waiting.empty():
            req = self._waiting.get()
            req["q"].put(None)
            if self.registry is not None and req.get("adapter_row"):
                self.registry.release(req["adapter_row"])
        for req in self._parked:
            req["q"].put(None)
            if self.registry is not None and req.get("adapter_row"):
                self.registry.release(req["adapter_row"])
        self._parked.clear()

    def _run(self):
        try:
            self._run_loop()
        except Exception:  # noqa: BLE001 — a dead engine must not hang HTTP
            import logging
            logging.getLogger(__name__).exception(
                "continuous-batching engine crashed; failing open")
            with self._cond:  # excludes concurrent submit() puts
                self._stopped = True
                for i, s in enumerate(self._slots):
                    if s.live:
                        self._finish(i, aborted=True)
                self._drain_waiting()
                self._cond.notify_all()  # wake update_params waiters

    def _run_loop(self):
        while True:
            with self._cond:
                while (not self._stopped and self._waiting.empty()
                       and self._pending_params is None
                       and not any(s.live or s.prefilling
                                   for s in self._slots)
                       and not self._parked_actionable()):
                    self._cond.wait(timeout=0.5)
                if self._stopped:
                    for i, s in enumerate(self._slots):
                        if s.live or s.prefilling:
                            self._finish(i, aborted=True)
                    self._drain_waiting()
                    self._cond.notify_all()
                    return
                # apply a staged weight swap once in-flight slots drain
                # (prefilling counts — its KV is half-written under the
                # old weights); the prefix cache clears atomically with it
                # (its old entries are keyed by the old params identity
                # anyway — clearing frees the old tree + stale KV eagerly)
                swap_pending = self._pending_params is not None
                if swap_pending and not any(s.live or s.prefilling
                                            for s in self._slots):
                    # raw_params is swapped only here on the engine thread
                    # (update_params merely STAGES via _pending_params under
                    # _cond); all other raw_params uses are engine-thread
                    # dispatch reads, so the write needs no extra guard
                    # fedrace: disable-next-line=unguarded-shared-write
                    self.raw_params = self._pending_params
                    self._pending_params = None
                    if self.prefix_cache is not None:
                        self.prefix_cache.clear()
                    self._on_swap()
                    swap_pending = False
                    self._cond.notify_all()
                retry_parked = bool(self._parked) and not swap_pending
                if retry_parked:
                    self._fetch_ready = False
                    self._pin_released = False

            # admit into free slots (token-granularity join) — paused
            # while a swap waits for the drain, so no request straddles
            # the weight boundary.  Parked requests retry first (their
            # adapter may have paged in / pages may have freed); a parked
            # head never blocks fresh admissions behind it — _admit_one
            # re-parks and the loop moves on.
            tracer = get_tracer()
            if retry_parked:
                retry, self._parked = self._parked, []
                for j, req in enumerate(retry):
                    slot = self._free_slot()
                    if slot is None:
                        self._parked.extend(retry[j:])
                        break
                    self._admit_one(req, slot, tracer)
            while not swap_pending and not self._waiting.empty():
                slot = self._free_slot()
                if slot is None:
                    break
                req = self._waiting.get()
                self._admit_one(req, slot, tracer)
            if tracer.enabled:
                tracer.counter("serve.queue_depth",
                               self._waiting.qsize() + len(self._parked))

            if self.paged:
                self._prefill_tick()
            live = [i for i, s in enumerate(self._slots) if s.live]
            if live:
                self._dispatch(live)
                with self._stats_lock:
                    self._ticks += 1
            elif not any(s.prefilling for s in self._slots):
                continue
            if tracer.enabled:
                now = time.monotonic()
                rolled = None
                with self._stats_lock:
                    t0, ntok = self._tok_window
                    if now - t0 >= 0.5:
                        rolled = (ntok, self.serve_stats["tokens"])
                        self._tok_window = [now, 0]
                if rolled is not None:   # counter emits outside _stats_lock
                    tracer.counter("serve.tokens_per_s",
                                   rolled[0] / (now - t0))
                    tracer.counter("serve.tokens_total", rolled[1])
                if self.paged:
                    with self._stats_lock:
                        shared = self._pages_shared
                        tot = shared + self._pages_private
                        chunks = self._chunks_total
                    tracer.counter("serve.kv_pages_free",
                                   self.page_pool.pages_free)
                    tracer.counter("serve.kv_page_hit_rate",
                                   shared / tot if tot else 0.0)
                    tracer.counter("serve.prefill_chunks", chunks)
                if self._store_mode:
                    st = self.registry.stats
                    tracer.counter("serve.adapter_cache_hits",
                                   st["cache_hits"])
                    tracer.counter("serve.adapter_cache_misses",
                                   st["cache_misses"])
                    tracer.counter("serve.adapter_cache_evictions",
                                   st["cache_evictions"])
                    tot = st["cache_hits"] + st["cache_misses"]
                    tracer.counter("serve.adapter_miss_rate",
                                   st["cache_misses"] / tot if tot else 0.0)

    def _dispatch(self, live):
        """One device tick for the live slots (overridden by the
        speculative engine): horizon-scanned batched decode + emission."""
        for i in live:
            # engine-thread-confined decode state (see _admit)
            self._toks[i] = self._slots[i].cur_tok  # fedrace: disable=unguarded-shared-write
            self._poss[i] = self._slots[i].pos  # fedrace: disable=unguarded-shared-write
        if self.paged:
            # block tables ride as TRACED data — page moves, admissions
            # and evictions between ticks never recompile.  Non-live slots
            # must see all-trash tables so their burn writes land in
            # garbage: freed rows are already zeroed, but PREFILLING slots
            # have real (possibly shared-prefix) pages wired — mask their
            # rows here or the burn write at their stale position would
            # scribble into a page another slot is reading
            bt = self._btabs
            prefilling = [i for i, s in enumerate(self._slots)
                          if s.prefilling]
            if prefilling:
                bt = bt.copy()
                bt[prefilling] = 0
            btabs = jnp.asarray(bt)
            if self.registry is not None:
                with self.registry.lock:
                    toks, self._pool, keys = self._step(
                        self.raw_params, self.registry.bank, self._pool,
                        btabs, jnp.asarray(self._toks),
                        jnp.asarray(self._poss), jnp.asarray(self._keys),
                        jnp.asarray(self._temps), jnp.asarray(self._aids))
            else:
                toks, self._pool, keys = self._step(
                    self.raw_params, self._pool, btabs,
                    jnp.asarray(self._toks), jnp.asarray(self._poss),
                    jnp.asarray(self._keys), jnp.asarray(self._temps))
        elif self.registry is not None:
            # snapshot + dispatch under the registry lock so a concurrent
            # register()'s donated row write cannot invalidate the bank
            # buffer between the read and the launch (the dispatch itself
            # is async and fast; registration is the rare path)
            with self.registry.lock:
                toks, self._caches, keys = self._step(
                    self.raw_params, self.registry.bank, self._caches,
                    jnp.asarray(self._toks), jnp.asarray(self._poss),
                    jnp.asarray(self._keys), jnp.asarray(self._temps),
                    jnp.asarray(self._aids))
        else:
            toks, self._caches, keys = self._step(
                self.raw_params, self._caches, jnp.asarray(self._toks),
                jnp.asarray(self._poss), jnp.asarray(self._keys),
                jnp.asarray(self._temps))
        toks_host = np.asarray(toks)  # (n_slots, horizon)
        # copy carry keys back for LIVE slots only: a prefilling slot's
        # admission key must not advance with the burn splits its lane
        # rode along for (its first real sample comes later)
        keys_host = np.asarray(keys)
        for i in live:
            self._keys[i] = keys_host[i]  # fedrace: disable=unguarded-shared-write
        for i in live:
            for j in range(self.horizon):
                self._slots[i].pos += 1
                if not self._emit(i, int(toks_host[i, j])):
                    self._finish(i)
                    break


class SpeculativeBatchingEngine(ContinuousBatchingEngine):
    """Continuous batching × speculative decoding (greedy-only).

    Every tick runs ONE fused device program: a vmapped draft
    catch-up+propose block (k tokens per slot) followed by a vmapped
    target verify block — so each live slot advances up to k+1 tokens
    per dispatch at full acceptance, and the expensive model runs one
    (k+1)-token forward per slot per tick regardless of acceptance.
    Output is bit-identical to the non-speculative engine / single-request
    ``generate`` (the draft only changes how many target forwards are
    spent — see :mod:`fedml_tpu.serving.speculative`).

    Cache-overrun discipline: verify/propose blocks write up to position
    ``buf_len + k`` (positions past a rejection self-heal, per the
    speculative module's argument), so both models must be built with
    ``max_seq_len >= buf_len + k + 1`` — asserted at construction instead
    of silently clamping writes (which would corrupt canonical K/V).
    """

    def __init__(self, model, params, draft_model, draft_params,
                 slots: int = 4, buf_len: int = 256, k: int = 4,
                 prefix_cache_slots: int = 0,
                 prefix_max_tail: int = TAIL_BLOCK,
                 hist_labels: int = 8,
                 slo_rules: Optional[List[Dict[str, Any]]] = None):
        self.k = int(k)
        assert self.k >= 1
        for m, name in ((model, "model"), (draft_model, "draft_model")):
            if getattr(getattr(m, "cfg", None), "kv_page_tokens", 0):
                raise PagedKVUnsupportedError(
                    f"{name} is built with kv_page_tokens="
                    f"{m.cfg.kv_page_tokens}: speculative decoding needs "
                    "contiguous per-slot caches (the draft/target verify "
                    "blocks write multi-token windows that would corrupt "
                    "a shared page pool) — use ContinuousBatchingEngine "
                    "for paged serving, or a dense model here")
            msl = getattr(getattr(m, "cfg", None), "max_seq_len", None)
            if msl is None:
                raise ValueError(
                    f"{name} has no cfg.max_seq_len — cannot prove the "
                    "speculative block writes stay in-bounds (a clamped "
                    "write would silently corrupt canonical K/V)")
            if msl < buf_len + self.k + 1:
                raise ValueError(
                    f"{name}.cfg.max_seq_len={msl} < buf_len+k+1="
                    f"{buf_len + self.k + 1}: speculative blocks would "
                    "clamp their cache writes")
        self.draft_model = draft_model
        self.raw_draft = _unwrap_params(draft_params)
        self._pending_draft = None
        self._hist: Dict[int, List[int]] = {}
        self._fds = np.zeros(int(slots), np.int32)
        super().__init__(model, params, slots=slots, buf_len=buf_len,
                         top_k=0, horizon=1,
                         prefix_cache_slots=prefix_cache_slots,
                         prefix_max_tail=prefix_max_tail,
                         hist_labels=hist_labels, slo_rules=slo_rules)

        from ..llm.quantization import dequantize_params, weight_dtype
        t_wdtype = weight_dtype(model)
        d_wdtype = weight_dtype(draft_model)
        k_ = self.k

        self._d_prefill, _, _ = _build_cached_decode(draft_model, 0, 1.0)
        dummy = jnp.zeros((1, self.buf_len), jnp.int32)
        _, dcache0 = self._d_prefill(self.raw_draft, None, dummy,
                                     jnp.int32(1),
                                     jax.random.PRNGKey(0), jnp.float32(0.0))
        self._d_caches = jax.tree_util.tree_map(
            lambda c: jnp.zeros((self.n_slots,) + c.shape, c.dtype), dcache0)

        from .speculative import propose_block, verify_greedy_block

        @jax.jit
        def spec_tick(draw, raw, d_caches, t_caches, sync_bufs, sync_lens,
                      fds, curs, poss):
            # one fused program per tick: vmapped draft propose (shared
            # body: speculative.propose_block) + vmapped target verify
            draw = dequantize_params(draw, d_wdtype)
            raw = dequantize_params(raw, t_wdtype)
            d_tokens, d_caches = jax.vmap(
                lambda cache, sync, slen, fd: propose_block(
                    draft_model, draw, cache, sync, slen, fd, k_)
            )(d_caches, sync_bufs, sync_lens, fds)
            blocks = jnp.concatenate([curs[:, None], d_tokens], axis=1)
            greedy, t_caches = jax.vmap(
                lambda cache, block, pos: verify_greedy_block(
                    model, raw, cache, block, pos)
            )(t_caches, blocks, poss)
            return d_tokens, greedy, d_caches, t_caches

        self._spec_tick = spec_tick
        # observability: target forwards vs tokens out (acceptance rate)
        self.stats = {"target_block_forwards": 0, "proposed": 0,
                      "accepted": 0}

    def update_params(self, params, draft_params=None, wait: bool = True,
                      timeout: float = 60.0) -> None:
        """Swap target (and optionally draft) weights after the in-flight
        drain.  A stale draft only lowers the acceptance rate — greedy
        verification against the target keeps outputs exact — so the
        draft swap is optional."""
        if draft_params is not None:
            with self._cond:
                self._pending_draft = _unwrap_params(draft_params)
        super().update_params(params, wait=wait, timeout=timeout)

    def _on_swap(self) -> None:
        if self._pending_draft is not None:
            self.raw_draft = self._pending_draft
            self._pending_draft = None

    def submit(self, prompt_ids, max_new_tokens: int = 64,
               temperature: float = 0.0, seed: int = 0, eos_id=None,
               adapter: Optional[str] = None,
               traceparent: Optional[str] = None):
        if float(temperature) != 0.0:
            raise ValueError("SpeculativeBatchingEngine is greedy-only "
                             "(temperature 0); use ContinuousBatchingEngine "
                             "for sampled requests")
        # single-tenant: the base class rejects non-None adapters (no
        # registry), so the kwarg just rides through for signature parity
        return super().submit(prompt_ids, max_new_tokens=max_new_tokens,
                              temperature=0.0, seed=seed, eos_id=eos_id,
                              adapter=adapter, traceparent=traceparent)

    def _admit(self, req, slot):
        self._hist[slot] = list(req["prompt_ids"])
        super()._admit(req, slot)  # target prefill + first emitted token
        ids = req["prompt_ids"]
        n = len(ids)
        buf = np.zeros((1, self.buf_len), np.int32)
        buf[0, :n] = ids
        _, dcache = self._d_prefill(self.raw_draft, None, jnp.asarray(buf),
                                    jnp.int32(n), jax.random.PRNGKey(0),
                                    jnp.float32(0.0))
        self._d_caches = self._insert(self._d_caches, dcache,
                                      jnp.int32(slot))
        self._fds[slot] = n

    def _emit(self, i: int, tok: int) -> bool:
        s = self._slots[i]
        before = s.remaining
        cont = super()._emit(i, tok)
        if s.remaining < before:  # token was actually delivered
            self._hist[i].append(tok)
        return cont

    def _dispatch(self, live):
        kp1 = self.k + 1
        sync_bufs = np.zeros((self.n_slots, kp1), np.int32)
        sync_lens = np.ones(self.n_slots, np.int32)
        for i in live:
            s = self._slots[i]
            hist = self._hist[i]
            self._toks[i] = s.cur_tok
            self._poss[i] = s.pos
            sync = hist[self._fds[i]: s.pos + 1]
            assert 1 <= len(sync) <= kp1, (len(sync), self.k)
            sync_bufs[i, :len(sync)] = sync
            sync_lens[i] = len(sync)

        d_tokens, greedy, self._d_caches, self._caches = self._spec_tick(
            self.raw_draft, self.raw_params, self._d_caches, self._caches,
            jnp.asarray(sync_bufs), jnp.asarray(sync_lens),
            jnp.asarray(self._fds), jnp.asarray(self._toks),
            jnp.asarray(self._poss))
        d_host = np.asarray(d_tokens)
        g_host = np.asarray(greedy)
        self.stats["target_block_forwards"] += len(live)

        for i in live:
            s = self._slots[i]
            self._fds[i] = s.pos + 1  # draft confirmed through old cur
            for j in range(self.k):
                # count only proposals actually examined — eos/budget can
                # truncate the acceptance loop mid-block, and charging the
                # full k would understate real draft acceptance
                self.stats["proposed"] += 1
                s.drafts_proposed += 1
                dj, gj = int(d_host[i, j]), int(g_host[i, j])
                s.pos += 1
                if dj != gj:
                    # first disagreement: the target's own token replaces it
                    if not self._emit(i, gj):
                        self._finish(i)
                    break
                self.stats["accepted"] += 1
                s.drafts_accepted += 1
                if not self._emit(i, dj):
                    self._finish(i)
                    break
            else:
                # every proposal accepted: the target's continuation token
                s.pos += 1
                if not self._emit(i, int(g_host[i, self.k])):
                    self._finish(i)
