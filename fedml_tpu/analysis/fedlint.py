"""fedlint — JAX-aware static analysis for federated TPU hot paths.

Why a bespoke lint instead of pyflakes/ruff: the failure modes that matter
at mesh scale are *semantic to JAX*, invisible to generic linters, and only
surface at trace time on real hardware (or worse, silently corrupt numerics):

- a stray ``float()``/``.item()``/``print`` on a traced value inside a
  jitted round forces a host sync (or a trace-time crash),
- a PRNG key consumed twice correlates client sampling streams,
- a collective whose axis name doesn't match any declared mesh axis dies
  only when the enclosing ``shard_map`` traces on a real mesh,
- touching a buffer after it was donated to ``jit`` reads garbage,
- unhashable static args and Python ``if`` on tracers retrace every call,
- iterating an unordered dict into ``tree_map`` reorders leaves between
  processes and breaks multi-host checkpoint/collective agreement.

Design:

- **Pure stdlib.** Only ``ast``/``tokenize``; linting needs no jax install
  and never executes the target code.
- **Two passes.** Pass 1 indexes every module: module-level string
  constants (``CLIENT_AXIS = "client"``), imports, and *declared* mesh axis
  names (``Mesh(devs, axis_names)``, ``pmap(..., axis_name=...)``,
  ``shard_map`` kwargs).  Pass 2 runs the rules per module with the
  package-wide index available for cross-module constant resolution.
- **Jit-reachability.** Host-side ``float(loss)`` is fine; the same call
  inside a jitted function is a bug.  A function is considered
  jit-reachable when it is (a) decorated/wrapped with ``jax.jit`` /
  ``pmap`` / ``shard_map`` (including ``partial(jax.jit, ...)``), (b)
  passed by name to one of those or to ``vmap`` / ``lax.scan`` /
  ``while_loop`` / ``cond`` / ``fori_loop`` / ``grad`` /
  ``value_and_grad`` / ``checkpoint``, (c) lexically nested inside a
  reachable function, (d) called by name from a reachable function in the
  same module, or (e) its own body directly uses trace-only primitives
  (``jax.lax.*`` collectives/scan, ``jax.vmap``, ``jax.grad``).  This is a
  lint-grade approximation: factories that return closures jitted in
  *another* module are covered by (e) in practice.

Suppression: trailing ``# fedlint: disable=rule-a,rule-b`` on the flagged
line, ``# fedlint: disable-next-line=...`` on the line above, or
``disable=all``.  Suppressions should carry a reason after an extra ``--``
comment; ``tests/test_fedlint.py`` keeps the package at zero unsuppressed
errors.

Adding a rule: subclass nothing — write ``def check_<name>(module, out)``
appending :class:`Finding`, then register it in :data:`RULES` with a
severity and a one-line doc.  See ``docs/FEDLINT.md``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass
class Rule:
    name: str
    severity: str
    doc: str


RULES: Dict[str, Rule] = {
    r.name: r
    for r in [
        Rule("jit-host-sync", ERROR,
             "float()/int()/.item()/np.asarray/print on values inside "
             "jit-reachable functions force a host sync or trace error"),
        Rule("rng-key-reuse", ERROR,
             "a PRNG key consumed more than once (or across loop "
             "iterations, or PRNGKey built inside a loop) correlates "
             "random streams"),
        Rule("collective-axis-check", ERROR,
             "psum/psum_scatter/all_gather/... axis name must match an "
             "axis declared by a Mesh/pmap/shard_map in the package "
             "(multi-axis tuples like axis_name=('client','model') check "
             "every element against 2-D mesh declarations); also "
             "flags an fp32 upcast (.astype(float32)) fed directly into a "
             "collective payload — quantize or keep the compute dtype so "
             "the interconnect doesn't move full-width bytes "
             "(docs/COLLECTIVE_PRECISION.md)"),
        Rule("donation-after-use", ERROR,
             "an argument listed in donate_argnums is read after the "
             "jitted call — its buffer now holds garbage"),
        Rule("recompile-hazard", WARNING,
             "jit built inside a loop, unhashable static args, or Python "
             "if/while on a traced parameter retrace/recompile every call"),
        Rule("pytree-order", WARNING,
             "iterating an unordered dict into tree_map/flatten/stack "
             "makes leaf order process-dependent"),
        Rule("eval-shape-safety", ERROR,
             "concrete-array construction on a data-dependent shape "
             "(jnp.zeros(x.max()), int()/.item() coercions in a shape "
             "position) or jax.device_put of a traced value inside a "
             "jit-reachable function — works on concrete test inputs "
             "but breaks AOT lowering on eval_shape abstractions, the "
             "contract fedverify relies on (docs/FEDVERIFY.md)"),
        Rule("raw-msg-type", ERROR,
             "Message(<literal>, ...) constructions and "
             "register_message_receive_handler(<literal>, ...) call "
             "sites bypass the MyMessage-family constants — fedproto "
             "cannot pair the send with its handler, and a typo'd int "
             "is a silent protocol fork (docs/FEDPROTO.md)"),
    ]
}


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def key(self):
        return (self.path, self.line, self.rule)


# --------------------------------------------------------------------------
# small AST helpers
# --------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for Attribute chains, 'psum' for Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_attr(node: ast.AST) -> Optional[str]:
    d = dotted_name(node)
    return d.rsplit(".", 1)[-1] if d else None


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)


def enclosing(node: ast.AST, parents, kinds) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def func_name(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


# names that wrap a function into a jit-reachable one when it is the first
# positional argument (or the wrapped partial's first argument)
_JIT_WRAPPERS = {"jit", "pmap", "shard_map", "xmap", "pjit"}
_TRACE_WRAPPERS = _JIT_WRAPPERS | {
    "vmap", "scan", "while_loop", "fori_loop", "cond", "switch", "grad",
    "value_and_grad", "checkpoint", "remat", "custom_vjp", "custom_jvp",
    "associative_scan",
}
# primitives whose presence in a function BODY marks it as traced code
_TRACE_MARKERS = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "psum", "pmean",
    "pmax", "pmin", "psum_scatter", "all_gather", "all_to_all", "ppermute",
    "collective_permute",
    "pshuffle", "axis_index", "axis_size", "vmap", "grad", "value_and_grad",
    "stop_gradient", "dynamic_slice", "dynamic_update_slice", "select",
    "associative_scan",
}

_COLLECTIVES_AXIS_POS = {
    # call -> positional index of the axis-name argument
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    # the stage-ring activation mover of the 3-D pipeline layout
    # (docs/PIPELINE.md); ``collective_permute`` is the wrapper alias
    # some call sites use for the same primitive
    "collective_permute": 1,
    "axis_index": 0, "axis_size": 0,
}

_HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_SYNC_NP = {"asarray", "array", "copy", "save", "savez", "allclose",
                 "array_equal", "asnumpy"}
_HOST_SYNC_ATTRS = {"item", "tolist", "to_py"}
# host client-state store access (fedml_tpu/store, docs/CLIENT_STORE.md):
# method calls on a *store-named* receiver that read/write host-paged rows
# — a Python-dict/page lookup inside a traced round body either fails to
# trace or silently closes over ONE round's rows at trace time
# value-carrying tracer sinks (fedscope, docs/OBSERVABILITY.md): feeding
# a traced/device value into one inside a jitted region forces a host
# sync at that exact line — the sanctioned pattern returns the value
# through the round's outputs (ObsCarry) and feeds the tracer at the
# driver's existing sync point
_TRACER_SINK_ATTRS = {"counter", "add_bytes", "round_obs"}
# fedmon health sinks (docs/OBSERVABILITY.md): the HealthMonitor is a
# host-side detector — feeding it a traced per-client stat inside a jitted
# region forces the same sync the tracer sinks do; the sanctioned pattern
# returns the stat rows through the metrics pytree and observes at the
# driver's flush
_HEALTH_SINK_ATTRS = {"observe", "observe_round", "flag"}
# fedslo histogram sinks (docs/OBSERVABILITY.md): Histogram.record /
# .observe_latency take already-materialized host floats on the engine
# or HTTP threads — feeding one a traced value inside a jitted region is
# the same hidden sync the tracer sinks are; the sanctioned pattern
# measures with host clocks at the engine's existing sync points
_HISTOGRAM_SINK_ATTRS = {"record", "observe_latency"}

_HOST_STORE_ATTRS = {"get", "gather", "scatter", "page_in", "write_back",
                     "lookup", "load"}

_RNG_DERIVERS = {"split", "fold_in", "clone", "key_data", "wrap_key_data",
                 "key_impl"}
# this repo's own key-derivation helpers (core/rng.py)
_RNG_LOCAL_PRODUCERS = {"root_key", "round_key", "client_key", "purpose_key"}

_TREE_CONSUMERS = {"tree_map", "tree_multimap", "tree_flatten",
                   "tree_leaves", "tree_stack", "tree_unflatten",
                   "weighted_average", "stacked_weighted_average",
                   "tree_all", "tree_reduce"}

_STATIC_ANNOTATIONS = {"str", "bool", "int", "float"}


# --------------------------------------------------------------------------
# pass 1 — per-module index
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ModuleIndex:
    path: str
    tree: ast.AST
    lines: List[str]
    constants: Dict[str, object]           # module-level NAME -> str|tuple
    imports: Dict[str, str]                # local name -> source module
    declared_axes: Set[str]                # axis names declared HERE


def _const_value(node: ast.AST, constants: Dict[str, object]):
    """Resolve a literal/Name/tuple to python values using module consts."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_const_value(e, constants) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)
    return None


def index_module(path: str, source: str) -> Optional[ModuleIndex]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    constants: Dict[str, object] = {}
    imports: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            val = _const_value(node.value, constants)
            if isinstance(val, (str, tuple)):
                constants[node.targets[0].id] = val
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                imports[alias.asname or alias.name] = node.module

    declared: Set[str] = set()

    def note_axes(val):
        if isinstance(val, str):
            declared.add(val)
        elif isinstance(val, tuple):
            for v in val:
                note_axes(v)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = last_attr(node.func)
        if fn in ("Mesh", "make_mesh", "AbstractMesh"):
            # positional axis names: Mesh(devices, names) and the 2-D
            # factories jax.make_mesh(axis_shapes, axis_names) /
            # AbstractMesh(axis_shapes, axis_names) — a ("client",
            # "model") tuple here declares BOTH axes (docs/MESH_2D.md)
            if len(node.args) >= 2:
                note_axes(_const_value(node.args[1], constants))
        if fn in ("pmap", "shard_map", "xmap", "vmap", "make_mesh",
                  "Mesh", "AbstractMesh"):
            for kw in node.keywords:
                # spmd_axis_name: vmap's named batch axis over a mesh —
                # collectives inside a vmapped round may reduce over it
                if kw.arg in ("axis_name", "axis_names", "spmd_axis_name"):
                    note_axes(_const_value(kw.value, constants))
    return ModuleIndex(path=path, tree=tree, lines=source.splitlines(),
                       constants=constants, imports=imports,
                       declared_axes=declared)


@dataclasses.dataclass
class PackageIndex:
    """Cross-module context: every declared axis name and every module-level
    string constant in the analyzed file set, keyed by bare name (imports in
    this package re-export constants under their defining name)."""
    axes: Set[str]
    constants: Dict[str, object]

    @classmethod
    def build(cls, modules: Iterable[ModuleIndex]) -> "PackageIndex":
        axes: Set[str] = set()
        constants: Dict[str, object] = {}
        for m in modules:
            axes |= m.declared_axes
            for k, v in m.constants.items():
                constants.setdefault(k, v)
        return cls(axes=axes, constants=constants)


# --------------------------------------------------------------------------
# jit-reachability
# --------------------------------------------------------------------------

class Reachability:
    def __init__(self, mod: ModuleIndex, parents):
        self.parents = parents
        self.funcs: List[ast.AST] = [
            n for n in ast.walk(mod.tree) if isinstance(n, FUNC_NODES)]
        self.by_name: Dict[str, List[ast.AST]] = {}
        for f in self.funcs:
            if not isinstance(f, ast.Lambda):
                self.by_name.setdefault(f.name, []).append(f)
        self.aliases: Dict[str, Set[str]] = {}   # name -> names of defs
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Name):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.aliases.setdefault(t.id, set()).add(
                            node.value.id)
        self.reachable: Set[ast.AST] = set()
        self._seed(mod)
        self._close()

    def _defs_for(self, name: str, seen=None) -> List[ast.AST]:
        seen = seen or set()
        if name in seen:
            return []
        seen.add(name)
        out = list(self.by_name.get(name, []))
        for alias in self.aliases.get(name, ()):
            out.extend(self._defs_for(alias, seen))
        return out

    def _wrapped_fn_names(self, call: ast.Call) -> List[ast.AST]:
        """Defs referenced by the wrapped-function argument of a call."""
        out: List[ast.AST] = []
        args = list(call.args)
        # cond/switch pass branch callables at positions 1..n
        fn_attr = last_attr(call.func)
        cand = args[:1] if fn_attr not in ("cond", "switch") else args[1:]
        for a in cand:
            if isinstance(a, ast.Name):
                out.extend(self._defs_for(a.id))
            elif isinstance(a, ast.Lambda):
                out.append(a)
            elif isinstance(a, ast.Call) and \
                    last_attr(a.func) == "partial" and a.args:
                inner = a.args[0]
                if isinstance(inner, ast.Name):
                    out.extend(self._defs_for(inner.id))
                elif isinstance(inner, ast.Lambda):
                    out.append(inner)
        return out

    def _is_jit_decorator(self, dec: ast.AST) -> bool:
        name = last_attr(dec if not isinstance(dec, ast.Call) else dec.func)
        if name in _JIT_WRAPPERS:
            return True
        if isinstance(dec, ast.Call) and name == "partial" and dec.args:
            return last_attr(dec.args[0]) in _JIT_WRAPPERS
        return False

    def _seed(self, mod: ModuleIndex):
        for f in self.funcs:
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._is_jit_decorator(d) for d in f.decorator_list):
                    self.reachable.add(f)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = last_attr(node.func)
            if name in _TRACE_WRAPPERS:
                for f in self._wrapped_fn_names(node):
                    self.reachable.add(f)
        # marker pass: a body that itself calls trace-only primitives
        for f in self.funcs:
            if f in self.reachable:
                continue
            for node in self._own_body_walk(f):
                if isinstance(node, ast.Call) and \
                        last_attr(node.func) in _TRACE_MARKERS:
                    d = dotted_name(node.func) or ""
                    if d.startswith(("jax.", "lax.")) or "." not in d:
                        self.reachable.add(f)
                        break

    def _own_body_walk(self, fn: ast.AST):
        """Walk a function's body WITHOUT descending into nested defs."""
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        stack = list(body) if isinstance(body, list) else [body]
        while stack:
            n = stack.pop()
            if isinstance(n, FUNC_NODES):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _close(self):
        changed = True
        while changed:
            changed = False
            for f in list(self.reachable):
                # lexically nested defs trace with their parent
                for node in ast.walk(f):
                    if node is f or not isinstance(node, FUNC_NODES):
                        continue
                    if node not in self.reachable:
                        self.reachable.add(node)
                        changed = True
                # calls by name from a traced body trace too
                for node in self._own_body_walk(f):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Name):
                        for d in self._defs_for(node.func.id):
                            if d not in self.reachable:
                                self.reachable.add(d)
                                changed = True

    def innermost_fn(self, node: ast.AST) -> Optional[ast.AST]:
        return enclosing(node, self.parents, FUNC_NODES)

    def in_reachable(self, node: ast.AST) -> bool:
        fn = self.innermost_fn(node)
        return fn is not None and fn in self.reachable


# --------------------------------------------------------------------------
# module view shared by the rules
# --------------------------------------------------------------------------

class ModuleView:
    def __init__(self, mod: ModuleIndex, pkg: PackageIndex):
        self.mod = mod
        self.pkg = pkg
        self.parents = build_parents(mod.tree)
        self.reach = Reachability(mod, self.parents)

    def resolve_str(self, node: ast.AST):
        """Resolve an axis-name expression to str / tuple-of-str / None."""
        v = _const_value(node, self.mod.constants)
        if v is None and isinstance(node, ast.Name):
            v = self.pkg.constants.get(node.id)
        if v is None and isinstance(node, (ast.Tuple, ast.List)):
            # multi-axis collectives (axis_name=("client", "model"),
            # docs/MESH_2D.md) may mix literals with constants imported
            # from other modules — resolve element-wise with the package
            # index as fallback; any unresolvable element keeps the whole
            # tuple unproven (no guessing)
            vals = [self.resolve_str(e) for e in node.elts]
            if all(isinstance(x, str) for x in vals):
                v = tuple(vals)
        return v


# --------------------------------------------------------------------------
# rule: jit-host-sync
# --------------------------------------------------------------------------

def _is_staticish(node: ast.AST) -> bool:
    """Expressions that are static under tracing: literals, shape/dtype
    attribute chains, len() of those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute) and node.attr in (
            "shape", "ndim", "dtype", "size"):
        return True
    if isinstance(node, ast.Subscript):
        return _is_staticish(node.value)
    if isinstance(node, ast.Call):
        f = last_attr(node.func)
        if f in ("len", "getattr", "prod"):
            return True
    if isinstance(node, ast.BinOp):
        return _is_staticish(node.left) and _is_staticish(node.right)
    return False


def _receiver_name(node: ast.AST) -> Optional[str]:
    """Last identifier of a call/subscript receiver (``page_store`` in
    ``page_store.get(...)``, ``client_store`` in ``self.client_store[c]``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_store_name(name: Optional[str]) -> bool:
    return name is not None and "store" in name.lower()


def _is_tracer_receiver(node: ast.AST) -> bool:
    """``tracer.counter(...)`` / ``self._tracer.add_bytes(...)`` /
    ``get_tracer().round_obs(...)`` — receivers that name the fedtrace
    tracer either lexically or through the accessor call."""
    name = _receiver_name(node)
    if name is not None and "tracer" in name.lower():
        return True
    if isinstance(node, ast.Call):
        return last_attr(node.func) == "get_tracer"
    return False


def _is_health_receiver(node: ast.AST) -> bool:
    """``health_monitor.observe_round(...)`` / ``self._health.flag(...)``
    — receivers naming the fedmon monitor (the ``health``/``monitor``
    lexical convention, like the store-name rule)."""
    name = _receiver_name(node)
    return name is not None and ("health" in name.lower()
                                 or "monitor" in name.lower())


def _is_histogram_receiver(node: ast.AST) -> bool:
    """``ttft_hist.record(...)`` / ``self.serve_hists.ttft
    .observe_latency(...)`` — receivers naming a fedslo histogram (the
    ``hist`` lexical convention; ``histogram`` matches too)."""
    name = _receiver_name(node)
    return name is not None and "hist" in name.lower()


def check_jit_host_sync(mv: ModuleView, out: List[Finding]):
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, (ast.Call, ast.Subscript)):
            continue
        if not mv.reach.in_reachable(node):
            continue
        if isinstance(node, ast.Subscript):
            # host client-state store indexed inside traced code: the
            # lookup happens ONCE at trace time (or fails on a traced id)
            if _is_store_name(_receiver_name(node.value)):
                out.append(Finding(
                    "jit-host-sync", RULES["jit-host-sync"].severity,
                    mv.mod.path, node.lineno, node.col_offset,
                    "host client-state store subscript inside "
                    f"jit-reachable "
                    f"'{func_name(mv.reach.innermost_fn(node))}' — page "
                    "rows in on the host and pass the gathered cohort "
                    "stack into the round (docs/CLIENT_STORE.md)"))
            continue
        fn = node.func
        msg = None
        if isinstance(fn, ast.Name) and fn.id in _HOST_SYNC_BUILTINS:
            if len(node.args) == 1 and not _is_staticish(node.args[0]):
                msg = (f"{fn.id}() on a (possibly traced) value inside "
                       "jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' forces "
                       "a host sync / trace error")
        elif isinstance(fn, ast.Name) and fn.id in ("print", "breakpoint"):
            msg = (f"{fn.id}() inside jit-reachable "
                   f"'{func_name(mv.reach.innermost_fn(node))}' — use "
                   "jax.debug.print/breakpoint")
        elif isinstance(fn, ast.Attribute):
            d = dotted_name(fn) or ""
            if d.startswith(("np.", "numpy.")) and \
                    fn.attr in _HOST_SYNC_NP and node.args and \
                    not _is_staticish(node.args[0]):
                msg = (f"{d}() materializes its argument on host inside "
                       f"jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}'")
            elif fn.attr in _HOST_SYNC_ATTRS and not node.args:
                msg = (f".{fn.attr}() inside jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' blocks "
                       "on device and breaks under tracing")
            elif fn.attr in _TRACER_SINK_ATTRS and \
                    _is_tracer_receiver(fn.value) and \
                    any(not _is_staticish(a) for a in
                        list(node.args[1:])
                        + [kw.value for kw in node.keywords]):
                msg = (f"tracer sink .{fn.attr}() fed a (possibly traced) "
                       "value inside jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' — a "
                       "host sync at this line; return the value through "
                       "the round's outputs (ObsCarry) and feed the "
                       "tracer at the driver's sync point "
                       "(docs/OBSERVABILITY.md)")
            elif fn.attr in _HEALTH_SINK_ATTRS and \
                    _is_health_receiver(fn.value) and \
                    any(not _is_staticish(a) for a in
                        list(node.args[1:])
                        + [kw.value for kw in node.keywords]):
                msg = (f"fedmon health sink .{fn.attr}() fed a (possibly "
                       "traced) value inside jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' — a "
                       "host sync at this line; return the per-client "
                       "stat rows through the metrics pytree and observe "
                       "at the driver's flush (docs/OBSERVABILITY.md)")
            elif fn.attr in _HISTOGRAM_SINK_ATTRS and \
                    _is_histogram_receiver(fn.value) and \
                    any(not _is_staticish(a) for a in
                        list(node.args)
                        + [kw.value for kw in node.keywords]):
                msg = (f"fedslo histogram sink .{fn.attr}() fed a "
                       "(possibly traced) value inside jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' — a "
                       "host sync at this line; histograms take host-"
                       "clock measurements at the engine's existing sync "
                       "points (docs/OBSERVABILITY.md)")
            elif fn.attr in _HOST_STORE_ATTRS and \
                    _is_store_name(_receiver_name(fn.value)):
                msg = (f"host client-state store access "
                       f"(.{fn.attr}()) inside jit-reachable "
                       f"'{func_name(mv.reach.innermost_fn(node))}' — "
                       "page rows in on the host and pass the gathered "
                       "cohort stack into the round "
                       "(docs/CLIENT_STORE.md)")
            elif d == "jax.device_get":
                msg = ("jax.device_get inside a jit-reachable function "
                       "forces a device→host transfer")
        if msg:
            out.append(Finding("jit-host-sync", RULES["jit-host-sync"]
                               .severity, mv.mod.path, node.lineno,
                               node.col_offset, msg))


# --------------------------------------------------------------------------
# rule: rng-key-reuse
# --------------------------------------------------------------------------

def _stmt_assigned_names(stmt: ast.AST) -> Set[str]:
    names: Set[str] = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                names.add(n.id)
    return names


def _is_rng_producer(call: ast.Call) -> bool:
    f = last_attr(call.func)
    if f in ("PRNGKey", "key", "fold_in"):
        d = dotted_name(call.func) or f
        return "random" in d or f in ("PRNGKey", "fold_in")
    return f in _RNG_LOCAL_PRODUCERS


def _rng_uses_in(call: ast.Call, key: str) -> Optional[str]:
    """Classify how `call` uses name `key`: 'sample'|'derive'|'opaque'|None.
    Only first-arg / key= positions count for jax.random calls."""
    d = dotted_name(call.func) or ""
    f = last_attr(call.func)
    argexprs = list(call.args) + [kw.value for kw in call.keywords]
    used = any(isinstance(a, ast.Name) and a.id == key for a in argexprs)
    if not used:
        return None
    if "random" in d or f in _RNG_DERIVERS | _RNG_LOCAL_PRODUCERS:
        return "derive" if f in _RNG_DERIVERS | _RNG_LOCAL_PRODUCERS \
            else "sample"
    return "opaque"


def _fn_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs} | \
        ({a.vararg.arg} if a.vararg else set()) | \
        ({a.kwarg.arg} if a.kwarg else set())


def _param_tainted_names(fn: ast.AST) -> Set[str]:
    """Names inside ``fn`` whose values (may) derive from its parameters —
    a two-pass fixpoint over simple assignments, enough for the
    ``k = fold_in(key, i); sample(k)`` idiom."""
    tainted = set(_fn_params(fn))
    for _ in range(2):
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                val = getattr(stmt, "value", None)
                if val is None:
                    continue
                used = {n.id for n in ast.walk(val)
                        if isinstance(n, ast.Name)}
                if used & tainted:
                    tainted |= _stmt_assigned_names(stmt)
    return tainted


def _check_vmap_member_keys(mv: ModuleView, out: List[Finding]):
    """Population/member pattern (docs/PRIMITIVES.md): a function mapped by
    ``jax.vmap`` that consumes a PRNG key NOT derived from any of its own
    (mapped) parameters gives every member the SAME stream — e.g.
    ``vmap(lambda i: fold_in(key, 0))`` or sampling a closed-over key.
    ``fold_in(key, member_idx)`` is the clean form."""
    sev = RULES["rng-key-reuse"].severity
    local: Dict[str, ast.AST] = {}
    for node in ast.walk(mv.mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local[node.name] = node
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Lambda):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local[t.id] = node.value

    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call) or \
                last_attr(node.func) != "vmap" or not node.args:
            continue
        mapped = node.args[0]
        if isinstance(mapped, ast.Name):
            mapped = local.get(mapped.id)
        if not isinstance(mapped, (ast.Lambda, ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        tainted = _param_tainted_names(mapped)
        for sub in ast.walk(mapped):
            if not isinstance(sub, ast.Call):
                continue
            f = last_attr(sub.func)
            d = dotted_name(sub.func) or ""
            if not ("random" in d or f in _RNG_DERIVERS
                    | _RNG_LOCAL_PRODUCERS):
                continue
            if f in ("PRNGKey", "key"):
                if sub.args and all(isinstance(a, ast.Constant)
                                    for a in sub.args):
                    out.append(Finding(
                        "rng-key-reuse", sev, mv.mod.path, sub.lineno,
                        sub.col_offset,
                        "PRNGKey with a constant seed inside a vmapped "
                        "function — every member draws the SAME stream; "
                        "fold_in the member index instead"))
                continue
            if f in _RNG_DERIVERS | _RNG_LOCAL_PRODUCERS:
                # a deriver is member-distinct if ANY argument depends on
                # the mapped params: fold_in(key, member_idx) is the clean
                # form even though the key itself is closed over
                exprs = list(sub.args) + [kw.value for kw in sub.keywords]
            else:
                # a sampler is member-distinct only through its KEY
                exprs = [sub.args[0]] if sub.args else []
                for kw in sub.keywords:
                    if kw.arg == "key":
                        exprs = [kw.value]
            if not exprs:
                continue
            names = {n.id for e in exprs for n in ast.walk(e)
                     if isinstance(n, ast.Name)}
            if names and not (names & tainted):
                out.append(Finding(
                    "rng-key-reuse", sev, mv.mod.path, sub.lineno,
                    sub.col_offset,
                    f"{f}() consumes a member-independent key inside a "
                    "vmapped function — every member draws the SAME "
                    "stream; derive it from the mapped argument "
                    "(fold_in(key, member_idx))"))


def check_rng_key_reuse(mv: ModuleView, out: List[Finding]):
    sev = RULES["rng-key-reuse"].severity
    _check_vmap_member_keys(mv, out)

    # (b) PRNGKey(...) built inside a loop body
    for node in ast.walk(mv.mod.tree):
        if isinstance(node, ast.Call) and \
                last_attr(node.func) in ("PRNGKey", "key") and \
                "random" in (dotted_name(node.func) or ""):
            loop = enclosing(node, mv.parents, LOOP_NODES)
            if loop is not None:
                const = node.args and isinstance(node.args[0], ast.Constant)
                out.append(Finding(
                    "rng-key-reuse", sev, mv.mod.path, node.lineno,
                    node.col_offset,
                    "PRNGKey constructed inside a loop "
                    + ("with a constant seed — every iteration gets the "
                       "SAME stream" if const else
                       "— derive per-iteration keys with fold_in/split "
                       "from one root key")))

    # (a)/(c) linear def-use scan per function body
    for fn in mv.reach.funcs:
        if isinstance(fn, ast.Lambda):
            continue
        events: List[Tuple[int, str, str, ast.AST]] = []
        # (line, kind, name, node): kind in assign|sample|derive|opaque
        for stmt in ast.walk(fn):
            if isinstance(stmt, FUNC_NODES) and stmt is not fn:
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.For, ast.AsyncFor)):
                val = getattr(stmt, "value", None) or getattr(
                    stmt, "iter", None)
                produced = isinstance(val, ast.Call) and (
                    _is_rng_producer(val) or
                    last_attr(val.func) in _RNG_DERIVERS)
                for name in _stmt_assigned_names(stmt):
                    events.append((stmt.lineno,
                                   "assign_key" if produced else "assign",
                                   name, stmt))
        key_names = {n for (_, k, n, _) in events if k == "assign_key"}
        if not key_names:
            continue
        def innermost_nonlambda(node):
            cur = enclosing(node, mv.parents, FUNC_NODES)
            while isinstance(cur, ast.Lambda):
                cur = enclosing(cur, mv.parents, FUNC_NODES)
            return cur

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if innermost_nonlambda(node) is not fn:
                    continue
                for key in key_names:
                    use = _rng_uses_in(node, key)
                    if use:
                        events.append((node.lineno, use, key, node))
        events.sort(key=lambda e: e[0])
        state: Dict[str, List[Tuple[int, str, ast.AST]]] = {}
        for line, kind, name, node in events:
            if name not in key_names:
                continue
            if kind.startswith("assign"):
                state[name] = []
                continue
            uses = state.setdefault(name, [])
            uses.append((line, kind, node))
            samples = [u for u in uses if u[1] == "sample"]
            total = [u for u in uses if u[1] in ("sample", "opaque")]
            if len(total) >= 2 and len(samples) >= 1:
                out.append(Finding(
                    "rng-key-reuse", sev, mv.mod.path, line,
                    node.col_offset,
                    f"key '{name}' consumed again without an intervening "
                    f"split/fold_in (first use line {total[0][0]}) — "
                    "reused streams correlate"))
                state[name] = []  # report once per reuse site

        # cross-iteration: sample inside a loop, key bound outside it
        assigns = {}
        for line, kind, name, node in events:
            if kind.startswith("assign"):
                assigns.setdefault(name, []).append((line, node))
        for line, kind, name, node in events:
            if kind != "sample":
                continue
            loop = enclosing(node, mv.parents, LOOP_NODES)
            if loop is None or enclosing(
                    loop, mv.parents, FUNC_NODES) is not fn:
                continue
            rebound = any(
                loop.lineno <= aline <= max(
                    getattr(loop, "end_lineno", aline), aline)
                for aline, _ in assigns.get(name, []))
            if not rebound:
                out.append(Finding(
                    "rng-key-reuse", sev, mv.mod.path, line,
                    node.col_offset,
                    f"key '{name}' sampled inside a loop but never "
                    "re-split per iteration — every pass reuses the "
                    "same stream"))


# --------------------------------------------------------------------------
# rule: collective-axis-check
# --------------------------------------------------------------------------

#: collectives with a data payload at position 0 (axis_index/axis_size
#: take no payload) — targets of the fp32-upcast sub-check
_COLLECTIVES_WITH_PAYLOAD = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "collective_permute",
}

_F32_NAMES = {"float32", "f32"}


def _is_f32_dtype_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return node.value in _F32_NAMES
    name = last_attr(node)
    return name in _F32_NAMES


def _payload_f32_upcast(payload: ast.AST) -> Optional[ast.Call]:
    """First ``<expr>.astype(float32-ish)`` call inside a collective's
    payload expression (the value upcast was available at its compute
    dtype, so full-width bytes crossing the interconnect is a choice that
    deserves at least a suppression comment).  Bool sources are exempt:
    ``(w > 0).astype(float32)`` widens a mask for arithmetic — there is no
    narrower compute dtype to keep."""
    for sub in ast.walk(payload):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "astype" and sub.args \
                and _is_f32_dtype_expr(sub.args[0]) \
                and not isinstance(sub.func.value, ast.Compare):
            return sub
    return None


def check_collective_axis(mv: ModuleView, out: List[Finding]):
    sev = RULES["collective-axis-check"].severity
    declared = mv.pkg.axes | mv.mod.declared_axes
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = last_attr(node.func)
        if f not in _COLLECTIVES_AXIS_POS:
            continue
        d = dotted_name(node.func) or ""
        if not (d.startswith(("jax.lax.", "lax.")) or d == f):
            continue
        if f in _COLLECTIVES_WITH_PAYLOAD and node.args:
            upcast = _payload_f32_upcast(node.args[0])
            if upcast is not None:
                out.append(Finding(
                    "collective-axis-check", sev, mv.mod.path,
                    node.lineno, node.col_offset,
                    f"{f}() payload contains an fp32 upcast "
                    "(.astype(float32)) — the collective moves full-width "
                    "bytes although a compute-dtype input was available; "
                    "quantize the payload (collective_precision, "
                    "docs/COLLECTIVE_PRECISION.md) or suppress with a "
                    "reason if fp32 on the wire is intentional"))
        pos = _COLLECTIVES_AXIS_POS[f]
        axis_expr = None
        if len(node.args) > pos:
            axis_expr = node.args[pos]
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_expr = kw.value
        if axis_expr is None:
            continue
        val = mv.resolve_str(axis_expr)
        if val is None:
            continue  # parameter/dynamic — can't prove, don't guess
        names = val if isinstance(val, tuple) else (val,)
        for name in names:
            if isinstance(name, str) and name not in declared:
                out.append(Finding(
                    "collective-axis-check", sev, mv.mod.path,
                    node.lineno, node.col_offset,
                    f"{f}(axis {name!r}) does not match any declared "
                    f"mesh/pmap axis "
                    f"({', '.join(sorted(declared)) or 'none declared'})"))


# --------------------------------------------------------------------------
# rule: donation-after-use  (+ static-arg tracking for recompile-hazard)
# --------------------------------------------------------------------------

def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _collect_jit_bindings(mv: ModuleView):
    """Map binding ('name'|'attr', identifier) -> info about the jit call:
    donate positions/names, static positions/names."""
    bindings: Dict[Tuple[str, str], dict] = {}
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not isinstance(call, ast.Call) or \
                last_attr(call.func) not in _JIT_WRAPPERS:
            continue
        info = {"donate_nums": (), "donate_names": (),
                "static_nums": (), "static_names": (), "node": call}
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                info["donate_nums"] = _int_tuple(kw.value) or ()
            elif kw.arg == "donate_argnames":
                info["donate_names"] = _str_tuple(kw.value) or ()
            elif kw.arg == "static_argnums":
                info["static_nums"] = _int_tuple(kw.value) or ()
            elif kw.arg == "static_argnames":
                info["static_names"] = _str_tuple(kw.value) or ()
        if not any(info[k] for k in ("donate_nums", "donate_names",
                                     "static_nums", "static_names")):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                bindings[("name", t.id)] = info
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                bindings[("attr", t.attr)] = info
    return bindings


def _call_binding(call: ast.Call, bindings):
    if isinstance(call.func, ast.Name):
        return bindings.get(("name", call.func.id))
    if isinstance(call.func, ast.Attribute) and \
            isinstance(call.func.value, ast.Name) and \
            call.func.value.id == "self":
        return bindings.get(("attr", call.func.attr))
    return None


def _expr_key(node: ast.AST) -> Optional[str]:
    """Stable dotted string for Name / self.attr chains."""
    return dotted_name(node)


def check_donation_after_use(mv: ModuleView, out: List[Finding]):
    sev = RULES["donation-after-use"].severity
    bindings = _collect_jit_bindings(mv)
    if not bindings:
        return
    for fn in mv.reach.funcs:
        if isinstance(fn, ast.Lambda):
            continue
        body = list(ast.walk(fn))
        calls = [n for n in body if isinstance(n, ast.Call)
                 and _call_binding(n, bindings)]
        for call in calls:
            info = _call_binding(call, bindings)
            donated: List[str] = []
            for p in info["donate_nums"]:
                if p < len(call.args):
                    k = _expr_key(call.args[p])
                    if k:
                        donated.append(k)
            for nm in info["donate_names"]:
                for kw in call.keywords:
                    if kw.arg == nm:
                        k = _expr_key(kw.value)
                        if k:
                            donated.append(k)
            if not donated:
                continue
            # the statement holding this call; rebinding in the SAME
            # statement (x = f(x)) is the sanctioned idiom
            stmt = call
            while not isinstance(stmt, ast.stmt) and \
                    mv.parents.get(stmt) is not None:
                stmt = mv.parents[stmt]
            rebound_here: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for sub in ast.walk(t):
                        k = _expr_key(sub)
                        if k:
                            rebound_here.add(k)
            stmt_end = getattr(stmt, "end_lineno", call.lineno)
            for key in donated:
                if key in rebound_here:
                    continue
                # scan statements after the call STATEMENT for a read of
                # key (multi-line call args are part of the call itself)
                for node in body:
                    if not isinstance(node, (ast.Name, ast.Attribute)):
                        continue
                    if node.lineno <= stmt_end:
                        continue
                    if _expr_key(node) != key:
                        continue
                    if not isinstance(getattr(node, "ctx", None), ast.Load):
                        continue
                    # stop at a rebind between call and use
                    rebind = False
                    for st in ast.walk(fn):
                        if isinstance(st, (ast.Assign, ast.AugAssign)) and \
                                call.lineno < st.lineno < node.lineno:
                            tgts = st.targets if isinstance(
                                st, ast.Assign) else [st.target]
                            for t in tgts:
                                if _expr_key(t) == key:
                                    rebind = True
                    if not rebind:
                        out.append(Finding(
                            "donation-after-use", sev, mv.mod.path,
                            node.lineno, node.col_offset,
                            f"'{key}' was donated to the jitted call on "
                            f"line {call.lineno} (donate_argnums) — its "
                            "buffer is dead after that call"))
                        break
                # call inside a loop without rebinding key in the loop
                loop = enclosing(call, mv.parents, LOOP_NODES)
                if loop is not None and key not in rebound_here:
                    rebound_in_loop = False
                    for st in ast.walk(loop):
                        if isinstance(st, ast.Assign):
                            for t in st.targets:
                                for sub in ast.walk(t):
                                    if _expr_key(sub) == key:
                                        rebound_in_loop = True
                    if not rebound_in_loop:
                        out.append(Finding(
                            "donation-after-use", sev, mv.mod.path,
                            call.lineno, call.col_offset,
                            f"'{key}' is donated inside a loop but never "
                            "rebound — iteration 2 passes a dead buffer"))


# --------------------------------------------------------------------------
# rule: recompile-hazard
# --------------------------------------------------------------------------

def check_recompile_hazard(mv: ModuleView, out: List[Finding]):
    sev = RULES["recompile-hazard"].severity
    bindings = _collect_jit_bindings(mv)

    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = last_attr(node.func)
        # (s1) jit/shard_map/pmap constructed inside a loop
        if f in _JIT_WRAPPERS:
            loop = enclosing(node, mv.parents, LOOP_NODES)
            if loop is not None:
                out.append(Finding(
                    "recompile-hazard", sev, mv.mod.path, node.lineno,
                    node.col_offset,
                    f"{f}() constructed inside a loop — every iteration "
                    "builds (and compiles) a fresh callable; hoist it"))
            # fresh lambda jitted at call depth inside a function that is
            # itself re-invoked is caught by (s1); module level is fine
        # (s2) unhashable literal passed at a static position
        info = _call_binding(node, bindings)
        if info:
            def unhashable(a):
                return isinstance(a, (ast.Dict, ast.List, ast.Set,
                                      ast.Lambda, ast.JoinedStr,
                                      ast.ListComp, ast.DictComp,
                                      ast.SetComp))
            for p in info["static_nums"]:
                if p < len(node.args) and unhashable(node.args[p]):
                    out.append(Finding(
                        "recompile-hazard", sev, mv.mod.path,
                        node.args[p].lineno, node.args[p].col_offset,
                        f"unhashable/freshly-built object at static arg "
                        f"position {p} — every call is a new cache entry "
                        "(or a TypeError)"))
            for nm in info["static_names"]:
                for kw in node.keywords:
                    if kw.arg == nm and unhashable(kw.value):
                        out.append(Finding(
                            "recompile-hazard", sev, mv.mod.path,
                            kw.value.lineno, kw.value.col_offset,
                            f"unhashable/freshly-built object for static "
                            f"arg {nm!r} — every call recompiles"))

    # (s3) Python if/while on a bare traced parameter
    for fn in mv.reach.funcs:
        if fn not in mv.reach.reachable or isinstance(fn, ast.Lambda):
            continue
        static_params: Set[str] = set()
        dyn_params: Set[str] = set()
        args = fn.args
        all_args = (args.posonlyargs + args.args + args.kwonlyargs)
        defaults = list(args.defaults)
        # map trailing defaults to their params
        defaulted = {a.arg for a in args.args[len(args.args)
                                             - len(defaults):]}
        for a in all_args:
            ann = getattr(a.annotation, "id", None) or \
                last_attr(a.annotation) if a.annotation else None
            if ann in _STATIC_ANNOTATIONS or ann in ("Mesh", "Callable"):
                static_params.add(a.arg)
            elif a.arg in defaulted:
                static_params.add(a.arg)   # bool/str default idiom
            elif a.arg not in ("self", "cls"):
                dyn_params.add(a.arg)
        for node in ast.walk(fn):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            if test is None:
                continue
            if enclosing(node, mv.parents, FUNC_NODES) is not fn:
                continue
            for sub in ast.walk(test):
                if not (isinstance(sub, ast.Name)
                        and sub.id in dyn_params):
                    continue
                # climb to the test root: static-attribute access,
                # is/is-not comparisons and shape-ish calls are all fine
                exempt = False
                cur = sub
                while cur is not test and cur is not None:
                    parent = mv.parents.get(cur)
                    if isinstance(parent, ast.Attribute) and parent.attr \
                            in ("shape", "ndim", "dtype", "size"):
                        exempt = True
                        break
                    if isinstance(parent, ast.Compare) and all(
                            isinstance(op, (ast.Is, ast.IsNot))
                            for op in parent.ops):
                        exempt = True
                        break
                    if isinstance(parent, ast.Call) and last_attr(
                            parent.func) in ("len", "isinstance", "getattr",
                                             "hasattr", "callable"):
                        exempt = True
                        break
                    cur = parent
                if exempt:
                    continue
                out.append(Finding(
                    "recompile-hazard", sev, mv.mod.path, sub.lineno,
                    sub.col_offset,
                    f"Python branch on parameter '{sub.id}' of "
                    f"jit-reachable '{func_name(fn)}' — a tracer here "
                    "raises at trace time; use lax.cond/jnp.where"))
                break


# --------------------------------------------------------------------------
# rule: pytree-order
# --------------------------------------------------------------------------

def _dict_iteration(node: ast.AST) -> Optional[str]:
    """Return a description if `node` iterates dict views unsorted."""
    gens = []
    if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
        gens = node.generators
    elif isinstance(node, ast.DictComp):
        gens = node.generators
    for g in gens:
        it = g.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "keys", "values") \
                and not it.args:
            return f".{it.func.attr}()"
    if isinstance(node, ast.Call) and last_attr(node.func) == "list" and \
            node.args and isinstance(node.args[0], ast.Call) and \
            isinstance(node.args[0].func, ast.Attribute) and \
            node.args[0].func.attr in ("items", "keys", "values"):
        return f".{node.args[0].func.attr}()"
    return None


def check_pytree_order(mv: ModuleView, out: List[Finding]):
    sev = RULES["pytree-order"].severity
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = last_attr(node.func)
        if f not in _TREE_CONSUMERS:
            continue
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Starred):
                a = a.value
            desc = _dict_iteration(a)
            if desc:
                out.append(Finding(
                    "pytree-order", sev, mv.mod.path, a.lineno,
                    a.col_offset,
                    f"{f}() fed by unsorted dict {desc} iteration — leaf "
                    "order is insertion-dependent and breaks cross-host "
                    "agreement; iterate sorted(...)"))


# --------------------------------------------------------------------------
# rule: eval-shape-safety
# --------------------------------------------------------------------------

#: array constructors whose first/``shape=`` argument is a shape
_SHAPE_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange",
                       "linspace", "eye", "tri", "zeros_like_shape"}
#: data reductions that make a shape expression value-dependent
_DATA_REDUCERS = {"max", "min", "sum", "item", "argmax", "argmin",
                  "count_nonzero", "nonzero", "prod"}


def _shape_expr_data_dependent(node: ast.AST, tainted: Set[str]) -> bool:
    """A shape expression whose VALUE depends on traced data: a
    ``.max()``-style reduction of a parameter-tainted name, an
    ``int()``/``float()`` coercion of a non-static argument, or an
    ``np.asarray`` of a tainted name.  Plain ``x.shape[0]`` / ``len(x)``
    chains are static under tracing and stay exempt."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = last_attr(n.func)
        if f in _DATA_REDUCERS:
            root = (dotted_name(n.func) or "").split(".")[0]
            if root in ("jnp", "np", "numpy", "jax"):
                # jnp.max(x) form: a tainted name in the reduced operand
                for a in n.args:
                    names = {m.id for m in ast.walk(a)
                             if isinstance(m, ast.Name)}
                    if names & tainted and not _is_staticish(a):
                        return True
            elif isinstance(n.func, ast.Attribute) and \
                    not _is_staticish(n.func.value):
                # x.max() form; x.shape-chains stay static
                names = {m.id for m in ast.walk(n.func.value)
                         if isinstance(m, ast.Name)}
                if names & tainted:
                    return True
        elif isinstance(n.func, ast.Name) and n.func.id in ("int", "float"):
            if n.args and not _is_staticish(n.args[0]):
                return True
        elif f in ("asarray", "array") and n.args:
            names = {m.id for m in ast.walk(n.args[0])
                     if isinstance(m, ast.Name)}
            if names & tainted:
                return True
    return False


def _data_valued_names(fn: ast.AST, tainted: Set[str]) -> Set[str]:
    """Names assigned from a data-dependent expression (``n_live =
    jnp.sum(mask)``) — using one in a shape position is the same bug one
    assignment later."""
    out: Set[str] = set()
    for stmt in ast.walk(fn):
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            val = getattr(stmt, "value", None)
            if val is not None and _shape_expr_data_dependent(val, tainted):
                out |= _stmt_assigned_names(stmt)
    return out


def check_eval_shape_safety(mv: ModuleView, out: List[Finding]):
    sev = RULES["eval-shape-safety"].severity
    taint_cache: Dict[ast.AST, tuple] = {}
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not mv.reach.in_reachable(node):
            continue
        fn = mv.reach.innermost_fn(node)
        if fn is None:
            continue
        if fn not in taint_cache:
            t = _param_tainted_names(fn)
            taint_cache[fn] = (t, _data_valued_names(fn, t))
        tainted, data_valued = taint_cache[fn]
        d = dotted_name(node.func) or ""
        f = last_attr(node.func) or ""
        root = d.split(".")[0]
        if f in _SHAPE_CONSTRUCTORS and root in ("jnp", "jax", "np",
                                                 "numpy"):
            shape_args = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "shape"]

            def bad(a):
                if _shape_expr_data_dependent(a, tainted):
                    return True
                names = {m.id for m in ast.walk(a)
                         if isinstance(m, ast.Name)}
                return bool(names & data_valued)

            if any(bad(a) for a in shape_args):
                out.append(Finding(
                    "eval-shape-safety", sev, mv.mod.path, node.lineno,
                    node.col_offset,
                    f"{d or f}() builds a concrete array whose shape "
                    f"depends on traced data inside jit-reachable "
                    f"'{func_name(fn)}' — the shape must be a "
                    "trace-time static so fedverify can lower the "
                    "program on eval_shape abstractions "
                    "(pad to a static bound instead)"))
        elif d == "jax.device_put" and node.args:
            names = {m.id for m in ast.walk(node.args[0])
                     if isinstance(m, ast.Name)}
            if names & tainted:
                out.append(Finding(
                    "eval-shape-safety", sev, mv.mod.path, node.lineno,
                    node.col_offset,
                    "jax.device_put of a (possibly traced) value inside "
                    f"jit-reachable '{func_name(fn)}' — placement is a "
                    "host-side effect that cannot lower abstractly; use "
                    "jax.lax.with_sharding_constraint inside the "
                    "program"))


# --------------------------------------------------------------------------
# rule: raw-msg-type
# --------------------------------------------------------------------------

def _is_raw_literal(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and \
        isinstance(node.value, (int, str)) and \
        not isinstance(node.value, bool)


def check_raw_msg_type(mv: ModuleView, out: List[Finding]):
    """The message-FSM plane keys everything on msg_type constants
    (``MyMessage.MSG_TYPE_*``-family classes, module-level ``MSG_*``
    names).  A literal at a ``Message(...)`` construction or a
    ``register_message_receive_handler(...)`` registration site is
    invisible to fedproto's protocol pairing and one typo away from a
    handler that never fires (docs/FEDPROTO.md)."""
    sev = RULES["raw-msg-type"].severity
    for node in ast.walk(mv.mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = last_attr(node.func)
        if f == "Message" and _is_raw_literal(node.args[0]):
            out.append(Finding(
                "raw-msg-type", sev, mv.mod.path, node.lineno,
                node.col_offset,
                f"Message({node.args[0].value!r}, ...) constructed from a "
                "raw literal — use a MyMessage-family msg_type constant "
                "so fedproto can pair the send with its handler"))
        elif f == "register_message_receive_handler" and \
                _is_raw_literal(node.args[0]):
            out.append(Finding(
                "raw-msg-type", sev, mv.mod.path, node.lineno,
                node.col_offset,
                f"handler registered for raw literal msg_type "
                f"{node.args[0].value!r} — use a MyMessage-family "
                "constant shared with the sender"))


# --------------------------------------------------------------------------
# suppression + driver
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*fedlint:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_,\-]+|all)")


def _suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    supp: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        which, rules = m.groups()
        names = {r.strip() for r in rules.split(",") if r.strip()}
        target = i + 1 if which == "disable-next-line" else i
        supp.setdefault(target, set()).update(names)
    return supp


ALL_CHECKS = [
    check_jit_host_sync,
    check_rng_key_reuse,
    check_collective_axis,
    check_donation_after_use,
    check_recompile_hazard,
    check_pytree_order,
    check_eval_shape_safety,
    check_raw_msg_type,
]


def analyze_module(mod: ModuleIndex, pkg: PackageIndex,
                   rules: Optional[Set[str]] = None) -> List[Finding]:
    mv = ModuleView(mod, pkg)
    raw: List[Finding] = []
    for check in ALL_CHECKS:
        check(mv, raw)
    if rules is not None:
        raw = [f for f in raw if f.rule in rules]
    supp = _suppressions(mod.lines)
    seen = set()
    out = []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        if f.key() in seen:
            continue
        seen.add(f.key())
        marked = supp.get(f.line, set())
        if "all" in marked or f.rule in marked:
            f.suppressed = True
        out.append(f)
    return out


def iter_py_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
    return files


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Set[str]] = None,
                  severity_overrides: Optional[Dict[str, str]] = None
                  ) -> List[Finding]:
    """Lint every .py under `paths`. Two passes: package index, then rules."""
    files = iter_py_files(paths)
    modules: List[ModuleIndex] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        mod = index_module(path, src)
        if mod is not None:
            modules.append(mod)
    pkg = PackageIndex.build(modules)
    findings: List[Finding] = []
    for mod in modules:
        findings.extend(analyze_module(mod, pkg, rules))
    if severity_overrides:
        for f in findings:
            if f.rule in severity_overrides:
                f.severity = severity_overrides[f.rule]
    return findings


def analyze_source(source: str, path: str = "<string>",
                   extra_axes: Iterable[str] = (),
                   rules: Optional[Set[str]] = None) -> List[Finding]:
    """Single-source entry point (fixture tests use this)."""
    mod = index_module(path, source)
    if mod is None:
        raise SyntaxError(f"cannot parse {path}")
    pkg = PackageIndex.build([mod])
    pkg.axes |= set(extra_axes)
    return analyze_module(mod, pkg, rules)


def render_findings(findings: Sequence[Finding],
                    show_suppressed: bool = False,
                    tool: str = "fedlint") -> str:
    lines = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: "
                     f"[{f.severity}] {f.rule}: {f.message}{tag}")
    active = [f for f in findings if not f.suppressed]
    errs = sum(1 for f in active if f.severity == ERROR)
    warns = sum(1 for f in active if f.severity == WARNING)
    sup = sum(1 for f in findings if f.suppressed)
    lines.append(f"{tool}: {errs} error(s), {warns} warning(s), "
                 f"{sup} suppressed")
    return "\n".join(lines)


def findings_to_json(findings: Sequence[Finding]) -> str:
    return json.dumps([dataclasses.asdict(f) for f in findings], indent=2)


def exit_code(findings: Sequence[Finding], strict: bool = False) -> int:
    active = [f for f in findings if not f.suppressed]
    if any(f.severity == ERROR for f in active):
        return 1
    if strict and active:
        return 1
    return 0
