"""Runtime auditor — counts XLA compilations and explicit host↔device
transfers inside a scope.

fedlint (the static half of :mod:`fedml_tpu.analysis`) proves properties of
the *source*; this context manager checks the property that actually costs
wall-clock at mesh scale: **a steady-state federated round must not
compile**.  A recompile per round means a shape leak (unpadded cohort, a
Python scalar that should be a traced array, a fresh closure handed to
``jax.jit``) and turns a 0.2 s round into a 20 s one on a real TPU — the
exact regression class PR 1's pow2 step padding exists to prevent.

Compilations are observed through the shared :mod:`fedml_tpu.obs.jaxhooks`
monitoring hub (ONE process-wide jax listener fanned out to subscribers —
the fedtrace tracer attaches to the same hub, so audits and Perfetto
traces see the identical compile stream;
``/jax/core/compile/backend_compile_duration`` fires once per XLA backend
compile, cache misses only).  Explicit transfers are counted by wrapping
``jax.device_put`` / ``jax.device_get`` for the duration of the scope —
implicit syncs (``float(arr)``, ``np.asarray(arr)``) go through the C++
array path and are *not* observable here; fedlint's ``jit-host-sync`` rule
covers those statically.

Usage::

    with JaxRuntimeAudit() as audit:
        api.train_one_round(2)
        api.train_one_round(3)
    assert audit.compilations == 0, audit.compiled

``tests/test_mesh.py::test_mesh_round_compiles_once`` pins the mesh engine
to exactly this contract, and ``tests/test_fedtrace.py`` uses the same
auditor to pin the fedtrace overhead contract (tracing on adds zero
compiles and zero explicit transfers).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax

from ..obs import jaxhooks

_BACKEND_COMPILE_EVENT = jaxhooks.BACKEND_COMPILE_EVENT


class JaxRuntimeAudit:
    """Counts backend compiles + explicit transfers within a ``with`` scope.

    Attributes after (or during) the scope:

    - ``compilations`` — number of XLA backend compiles observed.
    - ``compiled`` — the event names seen (one entry per compile; jax's
      duration events don't carry the function name in this version, so
      entries are the event key — the *count* is the contract).
    - ``device_puts`` / ``device_gets`` — explicit transfer calls.

    The hub's jax listener registers once per process and stays
    registered; this auditor merely subscribes/unsubscribes its callback
    (guarded by ``self._active``), so nested or repeated scopes are safe.
    """

    def __init__(self):
        self.compilations = 0
        self.compiled: List[str] = []
        self.device_puts = 0
        self.device_gets = 0
        self._active = False
        self._lock = threading.Lock()
        self._orig_put = None
        self._orig_get = None

    # -- monitoring hub callback -------------------------------------------
    def _on_event_duration(self, event: str, duration: float = 0.0,
                           **kw) -> None:
        if not self._active or event != _BACKEND_COMPILE_EVENT:
            return
        with self._lock:
            self.compilations += 1
            self.compiled.append(event)

    def __enter__(self) -> "JaxRuntimeAudit":
        jaxhooks.subscribe(self._on_event_duration)
        self._active = True

        audit = self
        self._orig_put, self._orig_get = jax.device_put, jax.device_get

        def counted_put(*a, **kw):
            with audit._lock:
                audit.device_puts += 1
            return audit._orig_put(*a, **kw)

        def counted_get(*a, **kw):
            with audit._lock:
                audit.device_gets += 1
            return audit._orig_get(*a, **kw)

        jax.device_put, jax.device_get = counted_put, counted_get
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self._active = False
        jax.device_put, jax.device_get = self._orig_put, self._orig_get
        jaxhooks.unsubscribe(self._on_event_duration)
        return None


def count_compilations(fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)``; return ``(result, n_compilations)``."""
    with JaxRuntimeAudit() as audit:
        result = fn(*args, **kwargs)
    return result, audit.compilations


# --------------------------------------------------------------------------
# LockOrderAudit — runtime half of the fedrace plane (docs/FEDRACE.md)
# --------------------------------------------------------------------------

class _AuditedLock:
    """Transparent proxy over a ``threading`` lock primitive that reports
    acquire/release ordering to a :class:`LockOrderAudit`.  Supports the
    context protocol plus ``acquire``/``release``/``locked``, so both
    ``with obj._lock:`` and explicit acquire/release call sites keep
    working unchanged while wrapped."""

    def __init__(self, audit: "LockOrderAudit", name: str, inner):
        self._audit = audit
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._audit._on_acquire(self._name)
        return got

    def release(self) -> None:
        self._audit._on_release(self._name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_AuditedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<_AuditedLock {self._name} over {self._inner!r}>"

    def __getattr__(self, item):
        # Condition attrs (wait/notify/notify_all) and RLock internals
        # pass straight through; only acquire/release order is audited.
        return getattr(self._inner, item)


class LockOrderAudit:
    """Observed-acquisition-order audit over package locks.

    The static half (:mod:`fedml_tpu.analysis.fedrace`) pins the *lexical*
    acquisition graph; this wraps live lock attributes in audited proxies
    and records what threads actually do under load — the per-thread
    acquisition-order edges (top-of-held-stack → newly acquired) and any
    blocking events noted while locks are held.  Two verdicts:

    - :meth:`assert_acyclic` — the observed graph has no cycle (a cycle
      is a witnessed deadlock *schedule*, not just a potential one).
    - :meth:`assert_subgraph_of` — every observed edge appears in the
      static pin (``tests/data/fedrace/concurrency.json``), i.e. runtime
      never discovered an ordering the extractor didn't see.

    Usage (the chaos + serving-load harnesses run exactly this shape)::

        audit = LockOrderAudit()
        audit.wrap(engine, "_cond", name="ContinuousBatchingEngine._cond")
        audit.wrap(engine, "_stats_lock",
                   name="ContinuousBatchingEngine._stats_lock")
        try:
            ... hammer the object from many threads ...
        finally:
            audit.unwrap_all()
        audit.assert_acyclic()
        audit.assert_subgraph_of("tests/data/fedrace/concurrency.json")

    Limitation: a ``Condition`` built on a lock *before* it was wrapped
    keeps a reference to the raw primitive, so acquisitions through that
    condition bypass the proxy — wrap plain ``Lock``/``RLock`` attributes,
    or the condition attribute itself.  Reentrant re-acquisition of the
    same name records no self-edge (RLocks are legal to nest).
    """

    def __init__(self):
        self._tls = threading.local()
        self._mu = threading.Lock()          # guards the aggregates below
        self.edges: Dict[Tuple[str, str], int] = {}
        self.acquisitions: Dict[str, int] = {}
        self.blocking: List[Tuple[str, Tuple[str, ...]]] = []
        self._wrapped: List[Tuple[Any, str, Any]] = []

    # -- per-thread bookkeeping -------------------------------------------
    def _held_stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, name: str) -> None:
        st = self._held_stack()
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            if st and st[-1] != name:
                key = (st[-1], name)
                self.edges[key] = self.edges.get(key, 0) + 1
        st.append(name)

    def _on_release(self, name: str) -> None:
        st = self._held_stack()
        # locks may release out of LIFO order; drop the LAST occurrence
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return

    def held(self) -> Tuple[str, ...]:
        """Audited locks currently held by the CALLING thread."""
        return tuple(self._held_stack())

    def note_blocking(self, event: str) -> None:
        """Record a blocking operation (a send, a join, a device sync);
        kept only when the calling thread holds audited locks — the
        runtime analogue of the static blocking-under-lock rule."""
        held = self.held()
        if held:
            with self._mu:
                self.blocking.append((str(event), held))

    # -- wrapping ----------------------------------------------------------
    def wrap(self, obj: Any, attr: str, name: Optional[str] = None):
        """Replace ``obj.<attr>`` with an audited proxy.  ``name``
        defaults to ``"<Class>.<attr>"`` — the manifest's qualified lock
        form, so observed edges compare directly against the pin."""
        inner = getattr(obj, attr)
        if isinstance(inner, _AuditedLock):
            return inner
        nm = name or f"{type(obj).__name__}.{attr}"
        proxy = _AuditedLock(self, nm, inner)
        setattr(obj, attr, proxy)
        self._wrapped.append((obj, attr, inner))
        return proxy

    def unwrap_all(self) -> None:
        """Restore every wrapped attribute (reverse order); idempotent."""
        for obj, attr, inner in reversed(self._wrapped):
            setattr(obj, attr, inner)
        self._wrapped.clear()

    def __enter__(self) -> "LockOrderAudit":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.unwrap_all()
        return None

    # -- verdicts ----------------------------------------------------------
    def observed_edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self.edges)

    def find_cycle(self) -> Optional[List[str]]:
        """A lock-name cycle in the observed graph, or ``None``."""
        graph: Dict[str, List[str]] = {}
        for s, d in self.observed_edges():
            graph.setdefault(s, []).append(d)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: List[str] = []

        def dfs(n: str) -> Optional[List[str]]:
            color[n] = GRAY
            path.append(n)
            for m in graph.get(n, ()):
                c = color.get(m, WHITE)
                if c == GRAY:
                    return path[path.index(m):] + [m]
                if c == WHITE:
                    found = dfs(m)
                    if found:
                        return found
            path.pop()
            color[n] = BLACK
            return None

        for n in list(graph):
            if color.get(n, WHITE) == WHITE:
                cyc = dfs(n)
                if cyc:
                    return cyc
        return None

    def assert_acyclic(self) -> None:
        cyc = self.find_cycle()
        if cyc:
            raise AssertionError(
                "observed lock-order cycle (witnessed deadlock schedule): "
                + " -> ".join(cyc))

    def assert_subgraph_of(self, pinned) -> None:
        """Every observed edge must appear in ``pinned`` — a manifest
        dict, a path to one, or an iterable of ``(src, dst)`` pairs.
        Manifest dicts contribute both the global ``lock_order`` edges and
        each scope's intra-class ``order`` list."""
        if isinstance(pinned, (str, os.PathLike)):
            with open(pinned) as fh:
                pinned = json.load(fh)
        if isinstance(pinned, dict):
            edges: List[Iterable[str]] = list(pinned.get("lock_order", []))
            for entry in pinned.get("scopes", {}).values():
                edges.extend(entry.get("order", []))
            pinned = edges
        allowed = {tuple(e) for e in pinned}
        extra = [e for e in self.observed_edges() if e not in allowed]
        if extra:
            raise AssertionError(
                "observed lock-order edge(s) missing from the static pin "
                "(run tools/fedrace.py check --update-manifest and review "
                "the diff): " + ", ".join(f"{s} -> {d}" for s, d in extra))
