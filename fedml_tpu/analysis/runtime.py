"""Runtime auditor — counts XLA compilations and explicit host↔device
transfers inside a scope.

fedlint (the static half of :mod:`fedml_tpu.analysis`) proves properties of
the *source*; this context manager checks the property that actually costs
wall-clock at mesh scale: **a steady-state federated round must not
compile**.  A recompile per round means a shape leak (unpadded cohort, a
Python scalar that should be a traced array, a fresh closure handed to
``jax.jit``) and turns a 0.2 s round into a 20 s one on a real TPU — the
exact regression class PR 1's pow2 step padding exists to prevent.

Compilations are observed through the shared :mod:`fedml_tpu.obs.jaxhooks`
monitoring hub (ONE process-wide jax listener fanned out to subscribers —
the fedtrace tracer attaches to the same hub, so audits and Perfetto
traces see the identical compile stream;
``/jax/core/compile/backend_compile_duration`` fires once per XLA backend
compile, cache misses only).  Explicit transfers are counted by wrapping
``jax.device_put`` / ``jax.device_get`` for the duration of the scope —
implicit syncs (``float(arr)``, ``np.asarray(arr)``) go through the C++
array path and are *not* observable here; fedlint's ``jit-host-sync`` rule
covers those statically.

Usage::

    with JaxRuntimeAudit() as audit:
        api.train_one_round(2)
        api.train_one_round(3)
    assert audit.compilations == 0, audit.compiled

``tests/test_mesh.py::test_mesh_round_compiles_once`` pins the mesh engine
to exactly this contract, and ``tests/test_fedtrace.py`` uses the same
auditor to pin the fedtrace overhead contract (tracing on adds zero
compiles and zero explicit transfers).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax

from ..obs import jaxhooks

_BACKEND_COMPILE_EVENT = jaxhooks.BACKEND_COMPILE_EVENT


class JaxRuntimeAudit:
    """Counts backend compiles + explicit transfers within a ``with`` scope.

    Attributes after (or during) the scope:

    - ``compilations`` — number of XLA backend compiles observed.
    - ``compiled`` — the event names seen (one entry per compile; jax's
      duration events don't carry the function name in this version, so
      entries are the event key — the *count* is the contract).
    - ``device_puts`` / ``device_gets`` — explicit transfer calls.

    The hub's jax listener registers once per process and stays
    registered; this auditor merely subscribes/unsubscribes its callback
    (guarded by ``self._active``), so nested or repeated scopes are safe.
    """

    def __init__(self):
        self.compilations = 0
        self.compiled: List[str] = []
        self.device_puts = 0
        self.device_gets = 0
        self._active = False
        self._lock = threading.Lock()
        self._orig_put = None
        self._orig_get = None

    # -- monitoring hub callback -------------------------------------------
    def _on_event_duration(self, event: str, duration: float = 0.0,
                           **kw) -> None:
        if not self._active or event != _BACKEND_COMPILE_EVENT:
            return
        with self._lock:
            self.compilations += 1
            self.compiled.append(event)

    def __enter__(self) -> "JaxRuntimeAudit":
        jaxhooks.subscribe(self._on_event_duration)
        self._active = True

        audit = self
        self._orig_put, self._orig_get = jax.device_put, jax.device_get

        def counted_put(*a, **kw):
            with audit._lock:
                audit.device_puts += 1
            return audit._orig_put(*a, **kw)

        def counted_get(*a, **kw):
            with audit._lock:
                audit.device_gets += 1
            return audit._orig_get(*a, **kw)

        jax.device_put, jax.device_get = counted_put, counted_get
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self._active = False
        jax.device_put, jax.device_get = self._orig_put, self._orig_get
        jaxhooks.unsubscribe(self._on_event_duration)
        return None


def count_compilations(fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)``; return ``(result, n_compilations)``."""
    with JaxRuntimeAudit() as audit:
        result = fn(*args, **kwargs)
    return result, audit.compilations
