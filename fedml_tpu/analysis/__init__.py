"""Static + runtime analysis for the federated hot paths.

Two complementary auditors, both born from the multi-chip engine in PR 1
(reduce-scatter merges, shard-resident optimizer state, donated ServerState
buffers, double-buffered staging) — every one of those patterns fails
*silently* in JAX when misused:

- :mod:`fedml_tpu.analysis.fedlint` — a pure-stdlib AST pass (no jax import
  needed to lint) with a rule registry: jit-boundary host syncs, RNG key
  discipline, collective axis names vs. declared mesh axes, buffer donation
  hazards, recompilation hazards, pytree iteration order.  Exposed as
  ``tools/fedlint.py`` and enforced in tier-1 by ``tests/test_fedlint.py``.
- :mod:`fedml_tpu.analysis.runtime` — a context manager that counts XLA
  backend compilations and explicit host↔device transfers through jax's
  monitoring hooks, so tests can pin "the mesh round compiles exactly once".
- :mod:`fedml_tpu.analysis.fedproto` — the message-FSM plane's checker:
  extracts each manager family's protocol (handlers, sends + params,
  handler reads, finish reachability), checks coverage / param contracts /
  liveness against the manifest pinned in
  ``tests/data/fedproto/protocols.json``, and replays fedscope comm spans
  against the same manifest (``check-trace``).  Exposed as
  ``tools/fedproto.py`` and enforced in tier-1 by ``tests/test_fedproto.py``.
- :mod:`fedml_tpu.analysis.fedverify` — AOT lowering-level contract checks
  over the canonical program registry (``tools/fedverify.py``).
- :mod:`fedml_tpu.analysis.fedrace` — the host concurrency plane's checker:
  extracts thread roots, lock objects and shared mutable attributes
  package-wide, then checks unguarded shared writes, lock-order cycles,
  blocking calls under held locks, and leaked threads against the surface
  pinned in ``tests/data/fedrace/concurrency.json``.  The runtime half
  (:class:`~fedml_tpu.analysis.runtime.LockOrderAudit`) wraps live locks
  and asserts the OBSERVED acquisition graph is acyclic and a subgraph of
  that pin.  Exposed as ``tools/fedrace.py`` and enforced in tier-1 by
  ``tests/test_fedrace.py``.
"""

from .fedlint import (  # noqa: F401
    Finding,
    RULES,
    analyze_paths,
    analyze_source,
    render_findings,
    findings_to_json,
)
from . import fedproto  # noqa: F401  (pure stdlib, like fedlint)
from . import fedrace  # noqa: F401  (pure stdlib, like fedlint)

__all__ = [
    "Finding",
    "RULES",
    "analyze_paths",
    "analyze_source",
    "render_findings",
    "findings_to_json",
    "fedproto",
    "fedrace",
]
