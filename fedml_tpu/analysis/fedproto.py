"""fedproto — static protocol checker for the distributed message-FSM plane.

The WAN half of this system is an actor-style message loop (reference
``FedMLCommManager`` FSMs, PAPER.md L1/L2): ~10 hand-wired manager families
exchange :class:`Message` objects whose types and params are plain constants.
Nothing type-checks that plane: a sent ``msg_type`` with no registered
handler on the other side is a silent hang (``receive_message`` logs a
warning and drops it), and a handler ``msg_params.get(KEY)`` whose sender
never ``add_params``-set that key is a silent ``None`` that surfaces three
frames later as a numeric crash — arXiv:2604.10859 shows the comm layer
dominates cross-silo behavior, yet fedlint covers source idioms and
fedverify covers compiled HLO while the message plane had no checker.

fedproto closes the gap with the same architecture as its siblings:

- **Pure stdlib.** Only ``ast``; extraction needs no jax and never executes
  the target code (``tools/fedproto.py`` loads this module by file path).
- **Extraction.** Per manager class (or module-level driver function), the
  protocol: registered handlers (``register_message_receive_handler(TYPE,
  fn)``, loop-expanded tuples, lambda handlers, and ``receive_message``
  observer ``==``-dispatch), send sites (``Message(TYPE, src, dst)``
  constructions tracked to their ``send_message``/``send`` call with every
  ``add_params`` key attached, parametric broadcast helpers resolved at
  their intra-class call sites), handler-internal reads (``msg.get(KEY)``
  required vs ``msg.get(KEY, default)`` optional vs ``msg.require(KEY)``),
  and ``finish()`` reachability over the intra-class call graph (including
  ``threading.Timer`` callback edges).  ``MyMessage``-style constants
  resolve cross-module through imports (including package ``__init__``
  re-export chains) and class-attribute tables.
- **Four check families** (see :data:`PROTO_RULES`): coverage
  (``unhandled-send`` / ``orphan-handler``), param contract
  (``missing-param``), liveness (``no-finish-path``: a ``finish()``-bearing
  handler must be reachable from the protocol entry, and no handler cycle
  may be unable to reach one), and runtime conformance
  (:func:`check_trace`: replay fedscope's merged ``comm.send``/``comm.recv``
  span sequences against the same extracted protocol).
- **Manifest.** Extracted protocols pin in
  ``tests/data/fedproto/protocols.json`` (``--update-manifest`` refreshes
  measured fields, preserves suppressions; the git diff is the review
  surface — the fedverify pattern).
- **Suppression.** ``# fedproto: disable=rule`` /
  ``disable-next-line=rule`` source comments for site-anchored findings,
  plus manifest-level ``{"family", "rule", "reason"}`` suppressions for
  family-level findings — both should carry a reason.

See ``docs/FEDPROTO.md`` for the full model and its limits.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

try:  # normal package import (tests, fedml_tpu.analysis)
    from .fedlint import (ERROR, WARNING, Finding, Rule, dotted_name,
                          exit_code, findings_to_json, iter_py_files,
                          last_attr, render_findings)
except ImportError:  # file-path load from tools/fedproto.py (no package)
    from fedlint import (ERROR, WARNING, Finding, Rule, dotted_name,  # type: ignore
                         exit_code, findings_to_json, iter_py_files,
                         last_attr, render_findings)

__all__ = [
    "PROTO_RULES", "PROTOCOL_FAMILIES", "extract_protocols",
    "check_protocols", "check_trace", "load_manifest", "update_manifest",
    "protocols_to_manifest", "render_findings", "findings_to_json",
    "exit_code", "DEFAULT_MANIFEST",
]

# --------------------------------------------------------------------------
# rule catalog
# --------------------------------------------------------------------------

PROTO_RULES: Dict[str, Rule] = {
    r.name: r
    for r in [
        Rule("unhandled-send", ERROR,
             "a sent msg_type has no registered handler on the destination "
             "role — the message is logged and dropped at runtime, usually "
             "a hang"),
        Rule("orphan-handler", ERROR,
             "a registered handler's msg_type is never sent by any family "
             "member — dead protocol state (or the sender was deleted)"),
        Rule("missing-param", ERROR,
             "a handler requires a msg_params key that at least one sender "
             "of that msg_type never add_params-sets — a silent None at "
             "the read site"),
        Rule("no-finish-path", ERROR,
             "liveness: no finish()-bearing handler is reachable from the "
             "protocol entry, or a handler cycle cannot reach one — a "
             "hang candidate"),
        Rule("manifest-drift", ERROR,
             "the extracted protocol differs from the pinned manifest "
             "(tests/data/fedproto/protocols.json) — review the diff and "
             "refresh with --update-manifest"),
        Rule("manifest-missing", WARNING,
             "a protocol family has no manifest entry yet — run "
             "--update-manifest to pin it"),
        Rule("unresolved-protocol", WARNING,
             "a msg_type or params key at this call site could not be "
             "resolved statically — the checkers skip it; prefer "
             "MyMessage-family constants"),
        # runtime conformance (check-trace) findings
        Rule("trace-unknown-type", ERROR,
             "an observed comm.send/comm.recv span carries a msg_type the "
             "extracted protocol does not know"),
        Rule("trace-message-loss", ERROR,
             "a comm.send span has no matching comm.recv on any captured "
             "process — dropped in transit, or delivered to a rank with "
             "no handler (coverage gap in the observed sequence)"),
        Rule("trace-duplicate-delivery", ERROR,
             "one logical message (fedscope.msg_id) produced more than one "
             "comm.recv span — re-delivery the FSM must be idempotent "
             "against"),
        Rule("trace-observed-drop", ERROR,
             "the fault-injection layer recorded a comm.drop for this "
             "message — it was never delivered"),
    ]
}

#: message-params keys every Message carries by construction
IMPLICIT_KEYS = {"msg_type", "sender", "receiver"}
#: runtime-injected context keys (obs/context.py) — never a handler contract
CONTEXT_KEY_PREFIX = "fedscope."
#: transport-plane params keys (core/distributed/reliability.py) — like
#: the fedscope context, below every FSM's param contract
TRANSPORT_KEY_PREFIX = "fedguard."
#: fedwire framing params keys (core/distributed/chunking.py) — also a
#: transport-plane concern, below the FSM contract
WIRE_KEY_PREFIX = "fedwire."
#: transport-plane message types exchanged BELOW every FSM: fedguard's
#: ack/retransmit + heartbeat leases (docs/FAULT_TOLERANCE.md) and
#: fedwire's chunk frames (docs/WIRE.md).  Values mirror
#: ``reliability.MSG_TYPE_ACK`` / ``MSG_TYPE_HEARTBEAT`` /
#: ``chunking.MSG_TYPE_CHUNK`` (pinned in sync by
#: tests/test_reliability.py); families flagged ``"transport": True`` in
#: PROTOCOL_FAMILIES pin this block in their manifest and
#: :func:`check_trace` accepts the types in both directions.
TRANSPORT_TYPES = {"ack": "690", "heartbeat": "691", "chunk": "692"}
#: fedwire codec parameters pinned alongside the transport types for
#: transport families (docs/WIRE.md): the chunk frame type + params
#: contract and the wire precisions a peer may negotiate — review
#: surface for the wire format, mirrored by core/wire.py and
#: core/distributed/chunking.py.
WIRE_CODEC_PARAMS = {
    "chunk_type": "692",
    "chunk_keys": ["fedwire.data", "fedwire.msg_type", "fedwire.parent",
                   "fedwire.seq", "fedwire.total"],
    "precisions": ["fp32", "bf16", "int8"],
}
#: constant-name suffix of the runtime-emitted readiness message: handlers
#: for it are entry points, never orphans, and nobody "sends" it
CONNECTION_READY_SUFFIX = "MSG_TYPE_CONNECTION_IS_READY"

# --------------------------------------------------------------------------
# protocol family table — the reviewed grouping of manager classes into
# paired-role FSMs.  ``members`` maps a class/function name to (role, path
# suffix); ``sources`` lists the modules whose msg-type constants belong to
# the family (everything else a member sends/handles — e.g. the bridge's
# global-plane traffic inside a regional family — is filtered out).
# ``queue_style`` families consume messages from a driver loop instead of
# per-type handlers, so param attribution and handler liveness don't apply.
# --------------------------------------------------------------------------

PROTOCOL_FAMILIES: Dict[str, Dict[str, Any]] = {
    "cross_silo": {
        "members": {
            "FedMLServerManager":
                ("server", "cross_silo/server/fedml_server_manager.py"),
            "ClientMasterManager":
                ("client", "cross_silo/client/fedml_client_master_manager.py"),
        },
        "sources": ("cross_silo/message_define.py",),
    },
    "cross_silo_async": {
        "members": {
            "AsyncFedMLServerManager":
                ("server", "cross_silo/server/async_server_manager.py"),
            "ClientMasterManager":
                ("client", "cross_silo/client/fedml_client_master_manager.py"),
        },
        "sources": ("cross_silo/message_define.py",),
    },
    "secagg": {
        "members": {
            "SAServerManager":
                ("server", "cross_silo/secagg/sa_fedml_server_manager.py"),
            "SAClientManager":
                ("client", "cross_silo/secagg/sa_fedml_client_manager.py"),
        },
        "sources": ("cross_silo/secagg/sa_message_define.py",),
    },
    "lightsecagg": {
        "members": {
            "LSAServerManager":
                ("server", "cross_silo/lightsecagg/lsa_fedml_server_manager.py"),
            "LSAClientManager":
                ("client", "cross_silo/lightsecagg/lsa_fedml_client_manager.py"),
        },
        "sources": ("cross_silo/lightsecagg/lsa_message_define.py",),
    },
    "vertical": {
        "members": {
            "VflGuestManager": ("server", "cross_silo/vertical_manager.py"),
            "VflHostManager": ("client", "cross_silo/vertical_manager.py"),
        },
        "sources": ("cross_silo/vertical_manager.py",),
    },
    "decentralized": {
        "members": {
            "DecentralizedWorkerManager":
                ("peer", "cross_silo/decentralized_manager.py"),
        },
        "sources": ("cross_silo/decentralized_manager.py",),
    },
    "fa_cross_silo": {
        "members": {
            "FACrossSiloServer": ("server", "fa/cross_silo/fa_managers.py"),
            "FACrossSiloClient": ("client", "fa/cross_silo/fa_managers.py"),
        },
        "sources": ("fa/cross_silo/fa_managers.py",),
    },
    "cross_cloud_global": {
        "members": {
            "GlobalCoordinator": ("server", "cross_cloud/hierarchy.py"),
            "CloudBridgeManager": ("client", "cross_cloud/hierarchy.py"),
        },
        "sources": ("cross_cloud/hierarchy.py",),
    },
    # the bridge's REGIONAL plane: CloudBridgeManager acts as the
    # cross-silo server toward its own clients (handlers inherited from
    # FedMLServerManager, round close overridden to escalate upward; the
    # SYNC fan-out runs from the global-sync callback, which is an entry
    # context for this family)
    "cross_silo_bridge": {
        "members": {
            "CloudBridgeManager": ("server", "cross_cloud/hierarchy.py"),
            "ClientMasterManager":
                ("client", "cross_silo/client/fedml_client_master_manager.py"),
        },
        "sources": ("cross_silo/message_define.py",),
    },
    "store_hierarchy": {
        "members": {
            "_run_combine_tier": ("server", "store/hierarchy.py"),
            "_run_silo_tier": ("client", "store/hierarchy.py"),
        },
        # the queue endpoint registers one handler per protocol type for
        # BOTH roles (the driver loops consume from the inbox)
        "shared_members": {"_Mgr": "store/hierarchy.py"},
        "sources": ("store/hierarchy.py",),
        "queue_style": True,
        # fedguard reliable delivery rides below this FSM: ack/heartbeat
        # transport types pin into the manifest (docs/FAULT_TOLERANCE.md)
        "transport": True,
    },
    # buffered-async federation (docs/ASYNC.md): the server buffers
    # staleness-discounted worker partials and applies at K; the same
    # queue-endpoint idiom as store_hierarchy
    "async_buffered": {
        "members": {
            "_run_async_server": ("server", "simulation/async_driver.py"),
            "_run_async_worker": ("client", "simulation/async_driver.py"),
        },
        "shared_members": {"_Mgr": "simulation/async_driver.py"},
        "sources": ("simulation/async_driver.py",),
        "queue_style": True,
        # fedguard reliable delivery rides below this FSM too
        "transport": True,
    },
}


# --------------------------------------------------------------------------
# records
# --------------------------------------------------------------------------

@dataclasses.dataclass
class MsgConst:
    """A resolved msg-type constant: its value, canonical name, and the
    path of the module that DEFINES it (the family-source filter key)."""
    value: Any
    name: Optional[str]
    source: str

    @property
    def key(self) -> str:
        return str(self.value)

    @property
    def is_connection_ready(self) -> bool:
        return bool(self.name) and \
            self.name.endswith(CONNECTION_READY_SUFFIX)


@dataclasses.dataclass
class SendSite:
    msg: MsgConst
    params: List[str]              # resolved add_params keys (sorted)
    unresolved_params: int         # count of keys that didn't resolve
    dst_is_server: Optional[bool]  # receiver expr resolved to literal 0?
    scope: str                     # class/function name
    method: str
    path: str
    line: int


@dataclasses.dataclass
class HandlerReg:
    msg: MsgConst
    handler: str                   # method name or "<lambda>"
    lambda_node: Optional[ast.AST]
    scope: str
    path: str
    line: int


@dataclasses.dataclass
class ScopeProtocol:
    """Everything extracted from one class (inheritance-resolved) or one
    module-level driver function."""
    name: str
    path: str
    line: int
    handlers: List[HandlerReg]
    sends: List[SendSite]
    #: method -> set of transitively self-called methods (incl. itself)
    closures: Dict[str, Set[str]]
    #: method -> does its body contain a .finish() call
    finishing: Dict[str, bool]
    #: handler method -> {key: required} reads of the msg parameter
    reads: Dict[str, Dict[str, bool]]
    warnings: List[Finding]

    def closure_of(self, method: str) -> Set[str]:
        return self.closures.get(method, {method})

    def handler_finishes(self, reg: HandlerReg) -> bool:
        if reg.lambda_node is not None:
            return any(isinstance(n, ast.Call)
                       and last_attr(n.func) == "finish"
                       for n in ast.walk(reg.lambda_node))
        return any(self.finishing.get(m, False)
                   for m in self.closure_of(reg.handler))

    def handler_sends(self, reg: HandlerReg) -> List[SendSite]:
        if reg.lambda_node is not None:
            return []
        cl = self.closure_of(reg.handler)
        return [s for s in self.sends if s.method in cl]


# --------------------------------------------------------------------------
# pass 1 — module indexing (constants, class tables, imports)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PModule:
    path: str
    tree: ast.AST
    lines: List[str]
    constants: Dict[str, Any]                    # module-level NAME -> int|str
    class_tables: Dict[str, Dict[str, Any]]      # ClassName -> {attr: value}
    class_defs: Dict[str, ast.ClassDef]          # ClassName -> node
    func_defs: Dict[str, ast.FunctionDef]        # top-level functions
    imports: Dict[str, Tuple[int, str, str]]     # local -> (level, mod, orig)
    aliases: Dict[str, str]                      # alias -> Name it was bound to


def _literal(node: ast.AST) -> Optional[Any]:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, str)) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def index_module(path: str, source: str) -> Optional[PModule]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    constants: Dict[str, Any] = {}
    imports: Dict[str, Tuple[int, str, str]] = {}
    aliases: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            val = _literal(node.value)
            if val is not None:
                constants[node.targets[0].id] = val
            elif isinstance(node.value, ast.Name):
                aliases[node.targets[0].id] = node.value.id
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                imports[alias.asname or alias.name] = (
                    node.level, node.module or "", alias.name)
    class_tables: Dict[str, Dict[str, Any]] = {}
    class_defs: Dict[str, ast.ClassDef] = {}
    func_defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            table: Dict[str, Any] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    val = _literal(stmt.value)
                    if val is None and isinstance(stmt.value, ast.Name):
                        # class-attr alias of a module constant (the
                        # Message class re-exports MSG_ARG_KEY_* this way)
                        val = constants.get(stmt.value.id)
                    if val is not None:
                        table[stmt.targets[0].id] = val
            class_tables.setdefault(node.name, table)
            class_defs.setdefault(node.name, node)
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            func_defs[node.name] = node
    return PModule(path=path, tree=tree, lines=source.splitlines(),
                   constants=constants, class_tables=class_tables,
                   class_defs=class_defs, func_defs=func_defs,
                   imports=imports, aliases=aliases)


class PackageView:
    """Cross-module resolution: constants, class tables, base classes —
    following imports (absolute by dotted-suffix match, relative by
    filesystem walk) including ``__init__`` re-export chains."""

    def __init__(self, modules: Sequence[PModule]):
        self.modules = list(modules)
        self.by_path = {m.path: m for m in modules}
        self._norm = {os.path.normpath(m.path): m for m in modules}

    # -- import-target lookup ---------------------------------------------
    def _module_for_import(self, importer: PModule, level: int,
                           module: str) -> Optional[PModule]:
        if level > 0:
            base = os.path.dirname(importer.path)
            for _ in range(level - 1):
                base = os.path.dirname(base)
            rel = module.replace(".", os.sep) if module else ""
            cands = [os.path.normpath(os.path.join(base, rel + ".py")),
                     os.path.normpath(os.path.join(base, rel,
                                                   "__init__.py"))]
            for c in cands:
                if c in self._norm:
                    return self._norm[c]
            return None
        suffix = module.replace(".", os.sep)
        for m in self.modules:
            norm = os.path.normpath(m.path)
            if norm.endswith(suffix + ".py") or \
                    norm.endswith(os.path.join(suffix, "__init__.py")):
                return m
        return None

    def resolve_name(self, mod: PModule, name: str, seen=None
                     ) -> Optional[Tuple[PModule, str]]:
        """Follow aliases + import chains until ``name`` lands on a module
        that defines it (class, function, or module constant)."""
        seen = seen or set()
        if (mod.path, name) in seen:
            return None
        seen.add((mod.path, name))
        if name in mod.class_defs or name in mod.func_defs or \
                name in mod.constants:
            return mod, name
        if name in mod.aliases:
            return self.resolve_name(mod, mod.aliases[name], seen)
        if name in mod.imports:
            level, module, orig = mod.imports[name]
            target = self._module_for_import(mod, level, module)
            if target is not None:
                return self.resolve_name(target, orig, seen)
        return None

    def class_table(self, mod: PModule, name: str
                    ) -> Optional[Tuple[Dict[str, Any], str]]:
        hit = self.resolve_name(mod, name)
        if hit is None:
            return None
        dmod, dname = hit
        if dname in dmod.class_tables:
            return dmod.class_tables[dname], dmod.path
        return None

    def class_def(self, mod: PModule, name: str
                  ) -> Optional[Tuple[ast.ClassDef, PModule]]:
        hit = self.resolve_name(mod, name)
        if hit is None:
            return None
        dmod, dname = hit
        if dname in dmod.class_defs:
            return dmod.class_defs[dname], dmod
        return None

    # -- constant resolution ----------------------------------------------
    def resolve_const(self, mod: PModule, node: ast.AST,
                      local_aliases: Optional[Dict[str, str]] = None
                      ) -> Optional[MsgConst]:
        """Resolve a msg-type / params-key expression to a MsgConst."""
        lit = _literal(node)
        if lit is not None:
            return MsgConst(lit, None, mod.path)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            base = node.value.id
            if local_aliases and base in local_aliases:
                base = local_aliases[base]
            hit = self.class_table(mod, base)
            if hit is not None:
                table, dpath = hit
                if node.attr in table:
                    canon = self.resolve_name(mod, base)
                    cname = canon[1] if canon else base
                    return MsgConst(table[node.attr],
                                    f"{cname}.{node.attr}", dpath)
            return None
        if isinstance(node, ast.Name):
            name = node.id
            if local_aliases and name in local_aliases:
                # alias of a class, not a constant
                return None
            if name in mod.constants:
                return MsgConst(mod.constants[name], name, mod.path)
            hit = self.resolve_name(mod, name)
            if hit is not None:
                dmod, dname = hit
                if dname in dmod.constants:
                    return MsgConst(dmod.constants[dname], dname, dmod.path)
        return None


# --------------------------------------------------------------------------
# pass 2 — per-scope extraction
# --------------------------------------------------------------------------

def _method_aliases(fn: ast.AST) -> Dict[str, str]:
    """Local ``M = MyMessage``-style aliases inside one method body."""
    out: Dict[str, str] = {}
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Name):
            out[stmt.targets[0].id] = stmt.value.id
    return out


def _fn_param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    return [n for n in names if n not in ("self", "cls")]


def _for_binding(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                 name: str) -> Optional[ast.For]:
    cur: Optional[ast.AST] = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.For) and isinstance(cur.target, ast.Name) \
                and cur.target.id == name:
            return cur
        cur = parents.get(cur)
    return None


class ScopeExtractor:
    """Extract one class's (or driver function's) protocol surface."""

    def __init__(self, pkg: PackageView, mod: PModule, name: str,
                 node: ast.AST):
        self.pkg = pkg
        self.mod = mod
        self.name = name
        self.node = node
        self.warnings: List[Finding] = []
        # method table (inheritance-resolved for classes; single entry
        # for module functions)
        self.methods: Dict[str, Tuple[PModule, ast.FunctionDef]] = {}
        if isinstance(node, ast.ClassDef):
            self._build_method_table(mod, node, set())
        else:
            self.methods[name] = (mod, node)

    # -- inheritance -------------------------------------------------------
    def _build_method_table(self, mod: PModule, cls: ast.ClassDef,
                            seen: Set[str]):
        if cls.name in seen:
            return
        seen.add(cls.name)
        for stmt in cls.body:
            if isinstance(stmt, ast.FunctionDef):
                self.methods.setdefault(stmt.name, (mod, stmt))
        for base in cls.bases:
            bname = last_attr(base)
            if not bname:
                continue
            hit = self.pkg.class_def(mod, bname)
            if hit is not None:
                bcls, bmod = hit
                self._build_method_table(bmod, bcls, seen)

    # -- warnings ----------------------------------------------------------
    def _warn(self, mod: PModule, node: ast.AST, msg: str):
        self.warnings.append(Finding(
            "unresolved-protocol", PROTO_RULES["unresolved-protocol"]
            .severity, mod.path, node.lineno, node.col_offset, msg))

    # -- registrations -----------------------------------------------------
    def extract_handlers(self) -> List[HandlerReg]:
        out: List[HandlerReg] = []
        for mname, (mod, fn) in sorted(self.methods.items()):
            aliases = _method_aliases(fn)
            parents = {c: p for p in ast.walk(fn)
                       for c in ast.iter_child_nodes(p)}
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        last_attr(node.func) == \
                        "register_message_receive_handler" and \
                        len(node.args) >= 2:
                    for msg in self._msg_values(mod, node.args[0], aliases,
                                                parents, node):
                        hname, lam = self._handler_target(node.args[1])
                        out.append(HandlerReg(
                            msg, hname, lam, self.name, mod.path,
                            node.lineno))
            if mname == "receive_message" and \
                    self.name != "FedMLCommManager":
                out.extend(self._observer_dispatch(mod, fn))
        # observer classes nested inside a member's methods (the
        # cross_cloud ``_Obs`` idiom: an inner class whose
        # ``receive_message`` ==-dispatches onto the outer manager's
        # methods) — their dispatch belongs to THIS scope's protocol
        if isinstance(self.node, ast.ClassDef):
            for sub in ast.walk(self.node):
                if isinstance(sub, ast.ClassDef) and sub is not self.node:
                    for stmt in sub.body:
                        if isinstance(stmt, ast.FunctionDef) and \
                                stmt.name == "receive_message":
                            out.extend(self._observer_dispatch(
                                self.mod, stmt))
        return out

    def _msg_values(self, mod: PModule, expr: ast.AST, aliases, parents,
                    site: ast.AST) -> List[MsgConst]:
        c = self.pkg.resolve_const(mod, expr, aliases)
        if c is not None:
            return [c]
        if isinstance(expr, ast.Name):
            loop = _for_binding(expr, parents, expr.id)
            if loop is not None and isinstance(loop.iter,
                                               (ast.Tuple, ast.List)):
                vals = [self.pkg.resolve_const(mod, e, aliases)
                        for e in loop.iter.elts]
                if all(v is not None for v in vals):
                    return vals  # loop-expanded registration
        self._warn(mod, site, f"{self.name}: msg_type expression at this "
                   "call site did not resolve to a constant")
        return []

    @staticmethod
    def _handler_target(expr: ast.AST) -> Tuple[str, Optional[ast.AST]]:
        if isinstance(expr, ast.Lambda):
            return "<lambda>", expr
        name = last_attr(expr)
        return (name or "<unknown>"), None

    def _observer_dispatch(self, mod: PModule, fn: ast.FunctionDef
                           ) -> List[HandlerReg]:
        """``def receive_message(self, mtype, msg)`` observer classes
        dispatching with ``if mtype == CONST: self.x._handler(msg)`` —
        the hand-rolled twin of handler registration (cross_cloud's
        global-plane observer)."""
        params = _fn_param_names(fn)
        if len(params) < 2:
            return []
        mtype_p, msg_p = params[0], params[1]
        aliases = _method_aliases(fn)
        out: List[HandlerReg] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            t = node.test
            if not (isinstance(t, ast.Compare) and len(t.ops) == 1 and
                    isinstance(t.ops[0], ast.Eq) and
                    isinstance(t.left, ast.Name) and t.left.id == mtype_p):
                continue
            msg = self.pkg.resolve_const(mod, t.comparators[0], aliases)
            if msg is None:
                self._warn(mod, node, f"{self.name}: receive_message "
                           "dispatch compares against an unresolvable "
                           "constant")
                continue
            for sub in node.body:
                for call in ast.walk(sub):
                    if isinstance(call, ast.Call) and any(
                            isinstance(a, ast.Name) and a.id == msg_p
                            for a in call.args):
                        out.append(HandlerReg(
                            msg, last_attr(call.func) or "<unknown>",
                            None, self.name, mod.path, node.lineno))
                        break
        return out

    # -- sends -------------------------------------------------------------
    def extract_sends(self) -> List[SendSite]:
        out: List[SendSite] = []
        for mname, (mod, fn) in sorted(self.methods.items()):
            out.extend(self._sends_in_method(mod, mname, fn))
        return out

    def _sends_in_method(self, mod: PModule, mname: str,
                         fn: ast.FunctionDef) -> List[SendSite]:
        aliases = _method_aliases(fn)
        events: List[Tuple[int, str, Any]] = []   # (line, kind, payload)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = last_attr(node.func)
            if f == "Message" and node.args:
                events.append((node.lineno, "construct", node))
            elif f in ("add_params", "add") and len(node.args) >= 2 and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name):
                events.append((node.lineno, "add", node))
            elif f in ("send_message", "send") and node.args:
                events.append((node.lineno, "send", node))
        # construct-var bindings, in statement order
        binds: Dict[str, dict] = {}
        out: List[SendSite] = []
        # map Message-construct node -> assigned name (if any)
        assign_of: Dict[ast.AST, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and \
                    last_attr(node.value.func) == "Message" and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                assign_of[node.value] = node.targets[0].id
        for line, kind, node in sorted(events, key=lambda e: e[0]):
            if kind == "construct":
                var = assign_of.get(node)
                rec = {"node": node, "params": [], "unresolved": 0,
                       "line": line}
                if var is not None:
                    binds[var] = rec
                else:
                    rec["inline"] = True
                    binds.setdefault("<inline>", rec)
            elif kind == "add":
                var = node.func.value.id
                rec = binds.get(var)
                if rec is None:
                    continue
                key = self.pkg.resolve_const(mod, node.args[0], aliases)
                if key is None:
                    rec["unresolved"] += 1
                    self._warn(mod, node, f"{self.name}.{mname}: "
                               "add_params key did not resolve")
                else:
                    rec["params"].append(str(key.value))
            elif kind == "send":
                arg = node.args[0]
                rec = None
                if isinstance(arg, ast.Name):
                    rec = binds.get(arg.id)
                elif isinstance(arg, ast.Call) and \
                        last_attr(arg.func) == "Message":
                    rec = {"node": arg, "params": [], "unresolved": 0,
                           "line": line}
                if rec is None:
                    continue
                out.extend(self._finish_send(mod, mname, fn, rec, aliases))
        return out

    def _finish_send(self, mod: PModule, mname: str, fn: ast.FunctionDef,
                     rec: dict, aliases) -> List[SendSite]:
        ctor: ast.Call = rec["node"]
        type_expr = ctor.args[0]
        dst = None
        if len(ctor.args) >= 3:
            lit = _literal(ctor.args[2])
            dst = (lit == 0) if lit is not None else None
        msgs: List[Tuple[Optional[MsgConst], str]] = [
            (self.pkg.resolve_const(mod, type_expr, aliases), mname)]
        if msgs[0][0] is None:
            # local binding: mtype = (FINISH if done else SYNC) — resolve
            # every arm of the assigned expression
            local = self._resolve_local_binding(mod, fn, type_expr, aliases)
            if local is not None:
                msgs = [(m, mname) for m in local]
        if msgs[0][0] is None:
            # parametric constructor: resolve the parameter at intra-scope
            # call sites of this method (the _broadcast/_dispatch idiom);
            # each resolved send is attributed to its CALLER so entry /
            # handler-edge classification sees the real context
            msgs = self._resolve_parametric(mod, mname, fn, type_expr)
        if not msgs:
            return []
        out = []
        for m, attributed in msgs:
            if m is None:
                self._warn(mod, ctor, f"{self.name}.{mname}: Message "
                           "msg_type did not resolve to a constant")
                continue
            out.append(SendSite(
                m, sorted(set(rec["params"])), rec["unresolved"], dst,
                self.name, attributed, mod.path, rec["line"]))
        return out

    def _resolve_local_binding(self, mod: PModule, fn: ast.FunctionDef,
                               type_expr: ast.AST, aliases
                               ) -> Optional[List[Optional[MsgConst]]]:
        if not isinstance(type_expr, ast.Name):
            return None
        if type_expr.id in _fn_param_names(fn):
            return None

        def arms(expr: ast.AST) -> List[ast.AST]:
            if isinstance(expr, ast.IfExp):
                return arms(expr.body) + arms(expr.orelse)
            return [expr]

        vals: List[Optional[MsgConst]] = []
        seen: Set[str] = set()
        found = False
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    stmt.targets[0].id == type_expr.id:
                found = True
                for arm in arms(stmt.value):
                    c = self.pkg.resolve_const(mod, arm, aliases)
                    if c is None:
                        vals.append(None)
                    elif c.key not in seen:
                        seen.add(c.key)
                        vals.append(c)
        if not found or not vals:
            return None
        return vals

    def _resolve_parametric(self, mod: PModule, mname: str,
                            fn: ast.FunctionDef, type_expr: ast.AST
                            ) -> List[Tuple[Optional[MsgConst], str]]:
        if not isinstance(type_expr, ast.Name):
            return [(None, mname)]
        params = _fn_param_names(fn)
        if type_expr.id not in params:
            return [(None, mname)]
        pos = params.index(type_expr.id)
        resolved: List[Tuple[Optional[MsgConst], str]] = []
        seen_vals: Set[Tuple[str, str]] = set()
        found_call = False
        for cname, (cmod, cfn) in sorted(self.methods.items()):
            caliases = _method_aliases(cfn)
            for node in ast.walk(cfn):
                if not isinstance(node, ast.Call):
                    continue
                if last_attr(node.func) != mname:
                    continue
                found_call = True
                arg: Optional[ast.AST] = None
                if pos < len(node.args):
                    arg = node.args[pos]
                for kw in node.keywords:
                    if kw.arg == type_expr.id:
                        arg = kw.value
                if arg is None:
                    continue
                c = self.pkg.resolve_const(cmod, arg, caliases)
                if c is None:
                    resolved.append((None, cname))
                elif (cname, c.key) not in seen_vals:
                    seen_vals.add((cname, c.key))
                    resolved.append((c, cname))
        if not found_call:
            return [(None, mname)]
        return resolved

    # -- reads -------------------------------------------------------------
    _READ_ATTRS = {"get": False, "require": True, "get_required": True}

    def extract_reads(self) -> Dict[str, Dict[str, bool]]:
        """method -> {key: required} reads of the method's first (message)
        parameter, with one-level propagation into helpers the message is
        passed to."""
        out: Dict[str, Dict[str, bool]] = {}
        for mname, (mod, fn) in sorted(self.methods.items()):
            params = _fn_param_names(fn)
            if not params:
                continue
            out[mname] = self._reads_of(mod, fn, params[0], depth=2)
        return out

    def _reads_of(self, mod: PModule, fn: ast.FunctionDef, pname: str,
                  depth: int) -> Dict[str, bool]:
        reads: Dict[str, bool] = {}
        aliases = _method_aliases(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = last_attr(node.func)
            if f in self._READ_ATTRS and isinstance(node.func,
                                                    ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == pname and node.args:
                key = self.pkg.resolve_const(mod, node.args[0], aliases)
                if key is None:
                    self._warn(mod, node, f"{self.name}: msg params key "
                               "read did not resolve")
                    continue
                required = self._READ_ATTRS[f] or (
                    len(node.args) == 1 and not node.keywords)
                k = str(key.value)
                reads[k] = reads.get(k, False) or required
            elif depth > 0 and isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                # one-level helper propagation: self.helper(..., msg, ...)
                helper = self.methods.get(node.func.attr)
                if helper is None:
                    continue
                hmod, hfn = helper
                hparams = _fn_param_names(hfn)
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and a.id == pname and \
                            i < len(hparams):
                        sub = self._reads_of(hmod, hfn, hparams[i],
                                             depth - 1)
                        for k, req in sub.items():
                            reads[k] = reads.get(k, False) or req
        return reads

    # -- call graph / finish -----------------------------------------------
    def extract_callgraph(self) -> Tuple[Dict[str, Set[str]],
                                         Dict[str, bool]]:
        direct: Dict[str, Set[str]] = {}
        finishing: Dict[str, bool] = {}
        for mname, (_mod, fn) in self.methods.items():
            calls: Set[str] = set()
            fin = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = last_attr(node.func)
                if f == "finish":
                    fin = True
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in self.methods:
                    calls.add(node.func.attr)
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in self.methods:
                    calls.add(node.func.id)
                # callback registration edges: threading.Timer(delay,
                # self._cb) — the armed timeout path sends too
                if f in ("Timer", "Thread"):
                    cb = None
                    if f == "Timer" and len(node.args) >= 2:
                        cb = node.args[1]
                    for kw in node.keywords:
                        if kw.arg == "target":
                            cb = kw.value
                    if isinstance(cb, ast.Attribute) and \
                            isinstance(cb.value, ast.Name) and \
                            cb.value.id == "self" and \
                            cb.attr in self.methods:
                        calls.add(cb.attr)
            direct[mname] = calls
            finishing[mname] = fin
        closures: Dict[str, Set[str]] = {}
        for mname in self.methods:
            seen: Set[str] = set()
            stack = [mname]
            while stack:
                cur = stack.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(direct.get(cur, ()))
            closures[mname] = seen
        return closures, finishing

    def extract(self) -> ScopeProtocol:
        closures, finishing = self.extract_callgraph()
        return ScopeProtocol(
            name=self.name, path=self.mod.path, line=self.node.lineno,
            handlers=self.extract_handlers(), sends=self.extract_sends(),
            closures=closures, finishing=finishing,
            reads=self.extract_reads(), warnings=self.warnings)


# --------------------------------------------------------------------------
# family assembly
# --------------------------------------------------------------------------

@dataclasses.dataclass
class FamilyProtocol:
    name: str
    config: Dict[str, Any]
    #: role -> [ScopeProtocol] (family-filtered views share scope objects)
    roles: Dict[str, List[ScopeProtocol]]
    shared: List[ScopeProtocol]
    warnings: List[Finding]

    @property
    def queue_style(self) -> bool:
        return bool(self.config.get("queue_style"))

    def source_ok(self, msg: MsgConst) -> bool:
        if msg.is_connection_ready:
            return True
        norm = os.path.normpath(msg.source)
        return any(norm.endswith(os.path.normpath(s))
                   for s in self.config["sources"])

    def counterpart(self, role: str) -> str:
        if "peer" in self.roles:
            return "peer"
        return "client" if role == "server" else "server"

    def dst_role(self, send: SendSite, sender_role: str) -> str:
        if "peer" in self.roles:
            return "peer"
        if send.dst_is_server is True:
            return "server"
        if send.dst_is_server is False:
            return "client"
        return self.counterpart(sender_role)

    # -- family-filtered views --------------------------------------------
    def role_handlers(self, role: str) -> List[Tuple[ScopeProtocol,
                                                     HandlerReg]]:
        out = []
        scopes = list(self.roles.get(role, ()))
        for sp in scopes + self.shared:
            for reg in sp.handlers:
                if self.source_ok(reg.msg):
                    out.append((sp, reg))
        return out

    def role_sends(self, role: str) -> List[Tuple[ScopeProtocol, SendSite]]:
        out = []
        for sp in self.roles.get(role, ()):
            for s in sp.sends:
                if self.source_ok(s.msg):
                    out.append((sp, s))
        return out


def _scope_index(pkg: PackageView) -> Dict[Tuple[str, str],
                                           Tuple[PModule, ast.AST]]:
    """(name, normalized path) -> definition node, for classes at any
    nesting depth plus top-level functions."""
    out: Dict[Tuple[str, str], Tuple[PModule, ast.AST]] = {}
    for mod in pkg.modules:
        norm = os.path.normpath(mod.path)
        for name, node in mod.class_defs.items():
            out[(name, norm)] = (mod, node)
        for name, node in mod.func_defs.items():
            out.setdefault((name, norm), (mod, node))
    return out


def extract_protocols(paths: Iterable[str],
                      families: Optional[Dict[str, Dict[str, Any]]] = None
                      ) -> Tuple[Dict[str, FamilyProtocol], List[Finding]]:
    """Index every .py under ``paths`` and assemble each protocol family's
    extracted surface.  Returns ``(families, warnings)`` — warnings cover
    unresolvable call sites and missing members."""
    families = families if families is not None else PROTOCOL_FAMILIES
    modules: List[PModule] = []
    for path in iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        mod = index_module(path, src)
        if mod is not None:
            modules.append(mod)
    pkg = PackageView(modules)
    scopes = _scope_index(pkg)

    def find_scope(name: str, suffix: str):
        suffix = os.path.normpath(suffix)
        for (n, p), hit in scopes.items():
            if n == name and p.endswith(suffix):
                return hit
        return None

    out: Dict[str, FamilyProtocol] = {}
    warnings: List[Finding] = []
    extracted_cache: Dict[Tuple[str, str], ScopeProtocol] = {}

    def extract_scope(name: str, suffix: str) -> Optional[ScopeProtocol]:
        hit = find_scope(name, suffix)
        if hit is None:
            return None
        mod, node = hit
        key = (name, os.path.normpath(mod.path))
        if key not in extracted_cache:
            extracted_cache[key] = ScopeExtractor(pkg, mod, name,
                                                  node).extract()
        return extracted_cache[key]

    for fname, cfg in families.items():
        roles: Dict[str, List[ScopeProtocol]] = {}
        fwarn: List[Finding] = []
        any_member = False
        for member, (role, suffix) in cfg["members"].items():
            sp = extract_scope(member, suffix)
            if sp is None:
                continue
            any_member = True
            roles.setdefault(role, []).append(sp)
            fwarn.extend(sp.warnings)
        shared: List[ScopeProtocol] = []
        for member, suffix in cfg.get("shared_members", {}).items():
            sp = extract_scope(member, suffix)
            if sp is not None:
                shared.append(sp)
                fwarn.extend(sp.warnings)
        if not any_member:
            continue  # family's modules not under the analyzed paths
        missing = [m for m, (r, sfx) in cfg["members"].items()
                   if extract_scope(m, sfx) is None]
        for m in missing:
            fwarn.append(Finding(
                "unresolved-protocol",
                PROTO_RULES["unresolved-protocol"].severity,
                cfg["members"][m][1], 1, 0,
                f"family {fname}: member {m} not found under the analyzed "
                "paths"))
        fam = FamilyProtocol(fname, cfg, roles, shared, fwarn)
        warnings.extend(fwarn)
        out[fname] = fam
    # de-dup warnings (same scope shared by several families)
    seen: Set[Tuple] = set()
    deduped = []
    for w in warnings:
        if w.key() not in seen:
            seen.add(w.key())
            deduped.append(w)
    return out, deduped


# --------------------------------------------------------------------------
# the four static check families
# --------------------------------------------------------------------------

def _mk(rule: str, path: str, line: int, msg: str) -> Finding:
    return Finding(rule, PROTO_RULES[rule].severity, path, line, 0, msg)


def check_coverage(fam: FamilyProtocol, out: List[Finding]):
    for role in fam.roles:
        handled_by: Dict[str, Set[str]] = {}
        for r2 in fam.roles:
            handled_by[r2] = {reg.msg.key
                              for _sp, reg in fam.role_handlers(r2)}
        for sp, send in fam.role_sends(role):
            dst = fam.dst_role(send, role)
            if send.msg.key not in handled_by.get(dst, set()):
                out.append(_mk(
                    "unhandled-send", send.path, send.line,
                    f"[{fam.name}] {sp.name}.{send.method} sends "
                    f"{send.msg.name or send.msg.key} (type "
                    f"{send.msg.key}) to role '{dst}' which registers no "
                    "handler for it — delivered messages are logged and "
                    "dropped"))
    # orphan handlers: registered types nobody in the family sends
    sent_all = {s.msg.key for role in fam.roles
                for _sp, s in fam.role_sends(role)}
    for role in fam.roles:
        for sp, reg in fam.role_handlers(role):
            if reg.msg.is_connection_ready:
                continue  # runtime-emitted on channel startup
            if reg.msg.key not in sent_all:
                out.append(_mk(
                    "orphan-handler", reg.path, reg.line,
                    f"[{fam.name}] {sp.name} registers "
                    f"'{reg.handler}' for "
                    f"{reg.msg.name or reg.msg.key} (type {reg.msg.key}) "
                    "but no family member ever sends it"))


def handler_required_reads(sp: ScopeProtocol, reg: HandlerReg
                           ) -> Dict[str, bool]:
    if reg.lambda_node is not None:
        return {}
    return sp.reads.get(reg.handler, {})


def check_param_contract(fam: FamilyProtocol, out: List[Finding]):
    if fam.queue_style:
        return  # driver-loop reads aren't attributable per msg type
    for role in fam.roles:
        for sp, reg in fam.role_handlers(role):
            reads = handler_required_reads(sp, reg)
            required = {k for k, req in reads.items()
                        if req and k not in IMPLICIT_KEYS
                        and not k.startswith(CONTEXT_KEY_PREFIX)}
            if not required:
                continue
            for r2 in fam.roles:
                for sp2, send in fam.role_sends(r2):
                    if send.msg.key != reg.msg.key:
                        continue
                    if fam.dst_role(send, r2) != role:
                        continue
                    if send.unresolved_params:
                        continue  # can't prove the key set — skip site
                    missing = sorted(required - set(send.params))
                    for key in missing:
                        out.append(_mk(
                            "missing-param", send.path, send.line,
                            f"[{fam.name}] handler {sp.name}."
                            f"{reg.handler} requires params key "
                            f"{key!r} of {send.msg.name or send.msg.key}, "
                            f"but sender {sp2.name}.{send.method} never "
                            "add_params-sets it — the read returns None"))


def check_liveness(fam: FamilyProtocol, out: List[Finding]):
    if fam.queue_style:
        # bounded driver loops, not handler FSMs: liveness is the loop
        # bound + the FINISH drain, checked by the runtime conformance pass
        return
    # nodes: (role, type); node data: handler regs
    nodes: Dict[Tuple[str, str], List[Tuple[ScopeProtocol, HandlerReg]]] = {}
    for role in fam.roles:
        for sp, reg in fam.role_handlers(role):
            nodes.setdefault((role, reg.msg.key), []).append((sp, reg))
    edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {
        n: set() for n in nodes}
    entry_nodes: Set[Tuple[str, str]] = set()
    finish_nodes: Set[Tuple[str, str]] = set()
    for (role, key), regs in nodes.items():
        for sp, reg in regs:
            if reg.msg.is_connection_ready:
                entry_nodes.add((role, key))
            if sp.handler_finishes(reg):
                finish_nodes.add((role, key))
            for send in sp.handler_sends(reg):
                if not fam.source_ok(send.msg):
                    continue
                dst = fam.dst_role(send, role)
                tgt = (dst, send.msg.key)
                if tgt in nodes:
                    edges[(role, key)].add(tgt)
    # entry sends: family-typed sends from (a) methods outside every
    # handler closure — run(), __init__ — and (b) methods inside the
    # closure of a handler registered for ANOTHER protocol plane (the
    # cross_cloud bridge: the regional upload handler's round close sends
    # the first global-plane partial)
    for role in fam.roles:
        handler_methods: Set[str] = set()
        other_plane_methods: Set[str] = set()
        for sp in fam.roles.get(role, []):
            for reg in sp.handlers:
                if reg.lambda_node is not None:
                    continue
                if fam.source_ok(reg.msg):
                    handler_methods |= sp.closure_of(reg.handler)
                else:
                    other_plane_methods |= sp.closure_of(reg.handler)
        for sp, send in fam.role_sends(role):
            if send.method in handler_methods and \
                    send.method not in other_plane_methods:
                continue
            tgt = (fam.dst_role(send, role), send.msg.key)
            if tgt in nodes:
                entry_nodes.add(tgt)

    def reachable_from(starts: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
        seen: Set[Tuple[str, str]] = set()
        stack = list(starts)
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(edges.get(cur, ()))
        return seen

    if not nodes:
        return
    anchor_sp = next(iter(fam.roles.values()))[0]
    live = reachable_from(entry_nodes)
    if not (live & finish_nodes):
        out.append(_mk(
            "no-finish-path", anchor_sp.path, anchor_sp.line,
            f"[{fam.name}] no finish()-bearing handler is reachable from "
            f"the protocol entry (entries: {sorted(entry_nodes)}; finish "
            f"nodes: {sorted(finish_nodes)}) — the federation cannot "
            "terminate cleanly"))
    # cycle check: any node in a cycle that cannot reach a finish node
    can_finish: Set[Tuple[str, str]] = set()
    rev: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {n: set()
                                                        for n in nodes}
    for n, tgts in edges.items():
        for t in tgts:
            rev[t].add(n)
    stack = list(finish_nodes)
    while stack:
        cur = stack.pop()
        if cur in can_finish:
            continue
        can_finish.add(cur)
        stack.extend(rev.get(cur, ()))
    for n in sorted(nodes):
        in_cycle = n in edges.get(n, set()) or any(
            n in reachable_from({t}) for t in edges.get(n, ()))
        if in_cycle and n not in can_finish:
            sp, reg = nodes[n][0]
            out.append(_mk(
                "no-finish-path", reg.path, reg.line,
                f"[{fam.name}] handler cycle through ({n[0]}, type "
                f"{n[1]}, {sp.name}.{reg.handler}) has no exit edge to "
                "any finish()-bearing handler — a hang once entered"))


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------

DEFAULT_MANIFEST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "data", "fedproto",
    "protocols.json")


def family_to_manifest(fam: FamilyProtocol) -> Dict[str, Any]:
    roles_out: Dict[str, List[str]] = {
        role: sorted(sp.name for sp in sps)
        for role, sps in fam.roles.items()}
    handlers: Dict[str, Dict[str, str]] = {}
    requires: Dict[str, Dict[str, List[str]]] = {}
    finish_roles: List[str] = []
    for role in sorted(fam.roles):
        h: Dict[str, str] = {}
        req: Dict[str, List[str]] = {}
        fin = False
        for sp, reg in fam.role_handlers(role):
            h[reg.msg.key] = reg.handler
            reads = handler_required_reads(sp, reg)
            keys = sorted(k for k, r in reads.items()
                          if r and k not in IMPLICIT_KEYS
                          and not k.startswith(CONTEXT_KEY_PREFIX)
                          and not k.startswith(TRANSPORT_KEY_PREFIX)
                          and not k.startswith(WIRE_KEY_PREFIX))
            if keys:
                req[reg.msg.key] = keys
            fin = fin or sp.handler_finishes(reg)
        handlers[role] = dict(sorted(h.items()))
        if req:
            requires[role] = dict(sorted(req.items()))
        if fin:
            finish_roles.append(role)
    sends: Dict[str, Dict[str, Any]] = {}
    for role in sorted(fam.roles):
        srow: Dict[str, Any] = {}
        for sp, s in fam.role_sends(role):
            entry = srow.setdefault(s.msg.key, {
                "dst": fam.dst_role(s, role), "name": s.msg.name,
                "sites": []})
            method = s.method if sp.name == s.method else \
                f"{sp.name}.{s.method}"
            site = {"method": method,
                    "params": [p for p in s.params
                               if not p.startswith(TRANSPORT_KEY_PREFIX)
                               and not p.startswith(WIRE_KEY_PREFIX)]}
            if site not in entry["sites"]:
                entry["sites"].append(site)
        for entry in srow.values():
            entry["sites"].sort(key=lambda x: x["method"])
        sends[role] = dict(sorted(srow.items()))
    out = {"roles": roles_out, "handlers": handlers, "sends": sends,
           "requires": requires, "finish_roles": sorted(finish_roles),
           "queue_style": fam.queue_style}
    if fam.config.get("transport"):
        # fedguard ack/heartbeat + fedwire chunk frames ride below this
        # family's FSM — pin the transport types so check-trace knows
        # them (both directions), and the wire codec contract next to
        # them (docs/WIRE.md)
        out["transport"] = dict(TRANSPORT_TYPES)
        out["wire"] = {k: list(v) if isinstance(v, list) else v
                       for k, v in WIRE_CODEC_PARAMS.items()}
    return out


def protocols_to_manifest(fams: Dict[str, FamilyProtocol]
                          ) -> Dict[str, Any]:
    return {"version": 1,
            "families": {n: family_to_manifest(f)
                         for n, f in sorted(fams.items())},
            "suppressions": []}


def load_manifest(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    path = path or DEFAULT_MANIFEST
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return json.load(fh)


def update_manifest(fams: Dict[str, FamilyProtocol],
                    path: Optional[str] = None) -> Dict[str, Any]:
    """Write the extracted protocols, PRESERVING the policy half (the
    suppressions list) of any existing manifest — the diff of the measured
    half is the review surface (the fedverify pattern)."""
    path = path or DEFAULT_MANIFEST
    old = load_manifest(path)
    fresh = protocols_to_manifest(fams)
    if old is not None:
        fresh["suppressions"] = old.get("suppressions", [])
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(fresh, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return fresh


def _diff_paths(a: Any, b: Any, prefix: str = "") -> List[str]:
    if isinstance(a, dict) and isinstance(b, dict):
        out: List[str] = []
        for k in sorted(set(a) | set(b)):
            p = f"{prefix}.{k}" if prefix else str(k)
            if k not in a:
                out.append(f"+{p}")
            elif k not in b:
                out.append(f"-{p}")
            else:
                out.extend(_diff_paths(a[k], b[k], p))
        return out
    if a != b:
        return [f"~{prefix}: {json.dumps(b)} -> {json.dumps(a)}"]
    return []


def check_manifest(fams: Dict[str, FamilyProtocol],
                   manifest: Optional[Dict[str, Any]],
                   out: List[Finding]):
    if manifest is None:
        for fam in fams.values():
            sp = next(iter(fam.roles.values()))[0]
            out.append(_mk("manifest-missing", sp.path, sp.line,
                           f"[{fam.name}] no manifest pinned yet — run "
                           "tools/fedproto.py --update-manifest"))
        return
    pinned = manifest.get("families", {})
    for name, fam in fams.items():
        sp = next(iter(fam.roles.values()))[0]
        if name not in pinned:
            out.append(_mk("manifest-missing", sp.path, sp.line,
                           f"[{name}] family has no manifest entry — run "
                           "tools/fedproto.py --update-manifest"))
            continue
        got = family_to_manifest(fam)
        if got != pinned[name]:
            diffs = _diff_paths(got, pinned[name])
            shown = "; ".join(diffs[:6])
            more = f" (+{len(diffs) - 6} more)" if len(diffs) > 6 else ""
            out.append(_mk(
                "manifest-drift", sp.path, sp.line,
                f"[{name}] extracted protocol drifted from the pinned "
                f"manifest: {shown}{more} — review and refresh with "
                "--update-manifest"))


# --------------------------------------------------------------------------
# suppression + driver
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*fedproto:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_,\-]+|all)")


def _line_suppressions(path: str) -> Dict[int, Set[str]]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return {}
    supp: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        which, rules = m.groups()
        names = {r.strip() for r in rules.split(",") if r.strip()}
        target = i + 1 if which == "disable-next-line" else i
        supp.setdefault(target, set()).update(names)
    return supp


_FAMILY_TAG_RE = re.compile(r"^\[([A-Za-z0-9_\-]+)\]")


def apply_suppressions(findings: List[Finding],
                       manifest: Optional[Dict[str, Any]]) -> List[Finding]:
    """Source-comment suppressions by (path, line); manifest-level
    ``{"family", "rule", "reason"}`` suppressions match the family tag
    every fedproto message leads with."""
    by_path: Dict[str, Dict[int, Set[str]]] = {}
    man_sup = (manifest or {}).get("suppressions", [])
    for f in findings:
        if f.path not in by_path:
            by_path[f.path] = _line_suppressions(f.path)
        marked = by_path[f.path].get(f.line, set())
        if "all" in marked or f.rule in marked:
            f.suppressed = True
            continue
        m = _FAMILY_TAG_RE.match(f.message)
        fam = m.group(1) if m else None
        for sup in man_sup:
            if sup.get("rule") == f.rule and \
                    sup.get("family") in (fam, "*"):
                f.suppressed = True
                break
    return findings


def check_protocols(fams: Dict[str, FamilyProtocol],
                    manifest: Optional[Dict[str, Any]] = None,
                    warnings: Optional[List[Finding]] = None,
                    rules: Optional[Set[str]] = None) -> List[Finding]:
    out: List[Finding] = list(warnings or [])
    for fam in fams.values():
        check_coverage(fam, out)
        check_param_contract(fam, out)
        check_liveness(fam, out)
    check_manifest(fams, manifest, out)
    if rules is not None:
        out = [f for f in out if f.rule in rules]
    seen: Set[Tuple] = set()
    deduped: List[Finding] = []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.rule,
                                        f.message)):
        k = (f.path, f.line, f.rule, f.message)
        if k in seen:
            continue
        seen.add(k)
        deduped.append(f)
    return apply_suppressions(deduped, manifest)


# --------------------------------------------------------------------------
# runtime conformance — replay a fedscope capture against the protocol
# --------------------------------------------------------------------------

def _trace_events(trace: Any) -> List[dict]:
    if isinstance(trace, dict):
        return trace.get("traceEvents", [])
    return list(trace)


def check_trace(traces: Sequence[Any], family: str,
                manifest: Optional[Dict[str, Any]] = None,
                fams: Optional[Dict[str, FamilyProtocol]] = None
                ) -> List[Finding]:
    """Validate observed ``comm.send`` / ``comm.recv`` / ``comm.drop``
    spans (one or more fedscope captures, raw or merged) against the
    pinned protocol of ``family``.

    The static pass proves the protocol CAN run; this proves a given run
    DID follow it: every send delivered exactly once (matching by the
    propagated span link, falling back to the stamped ``fedscope.msg_id``
    so duplicated deliveries don't read as losses), every observed type
    known to the protocol, every fault-injection drop surfaced.

    fedwire chunked framing (docs/WIRE.md): one logical message may ride
    the wire as N type-692 chunk frames sharing one ``fedwire.parent`` —
    the logical ``fedscope.msg_id``.  Frames self-account (per-frame
    ``comm.send``/``comm.recv`` under derived ids), the logical message
    has a ``comm.recv`` but no backend ``comm.send``; this checker groups
    observed frames by parent and requires the parent's logical delivery
    instead — a torn stream (frames seen, parent never reassembled) is a
    loss of the LOGICAL message."""
    if manifest is not None:
        entry = manifest.get("families", {}).get(family)
    elif fams is not None and family in fams:
        entry = family_to_manifest(fams[family])
    else:
        entry = None
    if entry is None:
        return [_mk("manifest-missing", f"<trace:{family}>", 1,
                    f"[{family}] no pinned protocol to replay the trace "
                    "against — run --update-manifest first")]
    known_handled: Set[str] = set()
    for row in entry.get("handlers", {}).values():
        known_handled |= set(row)
    known_sent: Set[str] = set()
    for row in entry.get("sends", {}).values():
        known_sent |= set(row)
    # fedguard transport types (ack/heartbeat) ride below the FSM in
    # both directions — known senders AND receivers on every role
    transport_types = {str(v) for v in
                       (entry.get("transport") or {}).values()}
    known_handled |= transport_types
    known_sent |= transport_types

    chunk_type = str((entry.get("transport") or {}).get("chunk", "692"))

    sends: List[dict] = []
    recvs: List[dict] = []
    drops: List[dict] = []
    retries: List[dict] = []
    chunk_parents: Dict[str, str] = {}   # parent msg_id -> original type
    for trace in traces:
        for e in _trace_events(trace):
            if e.get("ph") != "B":
                continue
            args = e.get("args") or {}
            rec = {"span_id": args.get("span_id"),
                   "parent_span": args.get("parent_span"),
                   "msg_type": args.get("msg_type"),
                   "msg_id": args.get("msg_id"),
                   "ts": e.get("ts", 0.0)}
            if e.get("name") == "comm.send":
                sends.append(rec)
            elif e.get("name") == "comm.recv":
                recvs.append(rec)
                if str(rec.get("msg_type")) == chunk_type and \
                        args.get("parent"):
                    chunk_parents.setdefault(str(args["parent"]), "?")
            elif e.get("name") == "comm.drop":
                drops.append(rec)
            elif e.get("name") == "comm.retry":
                retries.append(rec)
            elif e.get("name") == "comm.chunk" and args.get("parent"):
                # sender-side frame evidence: the logical message behind
                # these frames must reassemble into a comm.recv under
                # the parent msg_id
                chunk_parents[str(args["parent"])] = \
                    str(args.get("msg_type", "?"))

    out: List[Finding] = []
    tpath = f"<trace:{family}>"

    def maybe_type(rec) -> Optional[str]:
        t = rec.get("msg_type")
        return str(t) if t is not None else None

    # unknown types
    for rec in recvs:
        t = maybe_type(rec)
        if t is not None and t not in known_handled:
            out.append(_mk(
                "trace-unknown-type", tpath, 1,
                f"[{family}] observed comm.recv of msg_type {t} which the "
                "pinned protocol registers no handler for"))
    for rec in sends:
        t = maybe_type(rec)
        if t is not None and t not in known_sent:
            out.append(_mk(
                "trace-unknown-type", tpath, 1,
                f"[{family}] observed comm.send of msg_type {t} which the "
                "pinned protocol never sends"))
    # delivery: every send matched by span link or msg_id
    recv_parents = {r["parent_span"] for r in recvs
                    if r.get("parent_span")}
    recv_msg_ids = [r["msg_id"] for r in recvs if r.get("msg_id")]
    recv_id_set = set(recv_msg_ids)
    for rec in sends:
        delivered = (rec.get("span_id") in recv_parents or
                     (rec.get("msg_id") and rec["msg_id"] in recv_id_set))
        if not delivered:
            t = maybe_type(rec) or "?"
            out.append(_mk(
                "trace-message-loss", tpath, 1,
                f"[{family}] comm.send of msg_type {t} (span "
                f"{rec.get('span_id')}) has no matching comm.recv on any "
                "captured process — lost in transit or delivered to a "
                "rank with no handler"))
    # duplicates: one msg_id delivered more often than its DELIBERATE
    # wire attempts.  fedguard retransmissions (docs/FAULT_TOLERANCE.md)
    # share the logical msg_id and mark every re-send with a
    # ``comm.retry`` span, so a message retried N times may legally
    # produce up to 1+N deliveries — a retry surviving loss, not a
    # duplicate-delivery fault.  Anything beyond that budget (broker
    # QoS-1 re-delivery, chaos duplication) is flagged as before.
    retry_counts: Dict[str, int] = {}
    for rec in retries:
        mid = rec.get("msg_id")
        if mid:
            retry_counts[mid] = retry_counts.get(mid, 0) + 1
    counts: Dict[str, int] = {}
    for mid in recv_msg_ids:
        counts[mid] = counts.get(mid, 0) + 1
    dup_types = {}
    for rec in recvs:
        mid = rec.get("msg_id")
        if mid and counts.get(mid, 0) > 1 + retry_counts.get(mid, 0):
            dup_types.setdefault(mid, maybe_type(rec))
    for mid, t in sorted(dup_types.items()):
        out.append(_mk(
            "trace-duplicate-delivery", tpath, 1,
            f"[{family}] message {mid} (msg_type {t}) was delivered "
            f"{counts[mid]} times against a budget of "
            f"{1 + retry_counts.get(mid, 0)} deliberate send(s) — "
            "re-delivery the FSM must tolerate (fedguard "
            "retransmissions sharing the msg_id are not flagged)"))
    # fedwire chunk-stream completeness: every parent whose frames were
    # observed must have reassembled into the parent's logical comm.recv
    # (one logical partial = N chunk frames under one fedscope.msg_id)
    for parent, orig_t in sorted(chunk_parents.items()):
        if parent not in recv_id_set:
            out.append(_mk(
                "trace-message-loss", tpath, 1,
                f"[{family}] chunk frames of logical message {parent} "
                f"(msg_type {orig_t}) were observed but the message never "
                "reassembled into a comm.recv — torn chunk stream"))
    # observed fault-injection drops
    for rec in drops:
        t = maybe_type(rec) or "?"
        out.append(_mk(
            "trace-observed-drop", tpath, 1,
            f"[{family}] fault injection dropped a message of msg_type "
            f"{t} (msg {rec.get('msg_id')}) — never delivered"))
    return apply_suppressions(out, manifest)
