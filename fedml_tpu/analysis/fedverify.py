"""fedverify — AOT lowering-level contract checker (ISSUE 10 tentpole).

fedlint (``fedlint.py``) checks what the *source* says and JaxRuntimeAudit
(``runtime.py``) checks what *happened at runtime*; nothing verified what
XLA actually *compiles*.  Two real failure classes motivated closing that
gap: GSPMD silently re-replicated the model-sharded server state on round
exit (the PR 6 bug — caught only because a TPU ran out of HBM), and the
ObsCarry ``collective_bytes`` model is hand-maintained with no check
against the collectives XLA really emits.

Because every registered program is a pure function of ``(state, cohort,
hparams)`` (the PR 7 round algebra, arXiv:2403.07128), the whole training
and serving surface AOT-lowers on abstract shapes — ``jit(...).lower()``
over ``ShapeDtypeStruct`` avals runs NO step and needs NO accelerator —
so the contracts that matter at pod scale (arXiv:2204.06514) verify
statically, in CI, on a CPU host.  Five contract families:

1. **sharding** — every ServerState / client-table leaf of a program's
   output must land on its declared resting placement
   (``MeshLayout.state_sharding``), with a dedicated *silent
   re-replication* detector (expected-sharded leaf compiled to a fully
   replicated output = the PR 6 bug class).
2. **collective census** — count/classify ``all-reduce`` /
   ``reduce-scatter`` / ``all-gather`` / ``all-to-all`` /
   ``collective-permute`` ops per mesh axis in the *compiled* module,
   total their payload bytes, and cross-check against the ObsCarry
   ``collective_bytes_{client,model}`` model — drift is a failure.
3. **donation** — every buffer the engine declares donated must appear in
   the module's ``input_output_alias`` map (a missed donation silently
   doubles peak HBM for that buffer).
4. **HBM fit** — reconcile the compiled module's per-chip argument+temp
   footprint with ``core/memory_estimate.py``: the estimator must upper
   bound the lowering, and a config the estimator admits under a budget
   must actually fit it.
5. **recompile surface** — fingerprint the staged-input signature set a
   config family presents to the jit cache and fail when it exceeds the
   declared budget (homo cohorts = 1 program; hetero = pow2 step
   classes).

Findings ride fedlint's machinery (:class:`~.fedlint.Finding`, severity,
JSON, exit codes) so one reporting plane serves both analyzers;
suppressions live in the verify manifest
(``tests/data/fedverify/contracts.json``) as ``{program, rule, reason}``
records, and the manifest pins the expected census per canonical config
so contract changes are reviewed diffs, not silent drift
(``tools/fedverify.py --update-manifest`` regenerates the measured
fields, preserving budgets/bands/suppressions).

Layering: the HLO/StableHLO parsing and check half of this module is pure
stdlib (unit-testable without jax); the program registry half imports the
engines lazily and lowers the exact jitted callables the drivers run,
exposed by the ``round_program`` / ``block_program`` /
``step_programs`` hooks (docs/FEDVERIFY.md, "How to add a program").
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import programs as registry
from .fedlint import (ERROR, WARNING, Finding, Rule, exit_code,  # noqa: F401
                      findings_to_json, render_findings)

# --------------------------------------------------------------------------
# rule registry (one reporting plane with fedlint)
# --------------------------------------------------------------------------

VERIFY_RULES: Dict[str, Rule] = {
    r.name: r
    for r in [
        Rule("sharding-contract", ERROR,
             "a program output leaf's compiled sharding differs from the "
             "layout's declared resting placement"),
        Rule("silent-rereplication", ERROR,
             "a leaf the layout declares SHARDED compiled to a fully "
             "replicated output — GSPMD silently forfeited the 1/(c*m) "
             "per-chip ownership on program exit (the PR 6 bug class)"),
        Rule("collective-census", ERROR,
             "the compiled module's collective ops (count/kind/axis or "
             "payload bytes) differ from the manifest-pinned census"),
        Rule("byte-model-drift", ERROR,
             "the ObsCarry collective_bytes model drifted outside the "
             "pinned band of the bytes the compiled collectives move"),
        Rule("donation-aliasing", ERROR,
             "a buffer declared donated is missing from the compiled "
             "module's input_output_alias map — peak HBM doubles for it"),
        Rule("hbm-fit", ERROR,
             "per-chip argument+temp footprint of the compiled module "
             "exceeds the memory estimator or the declared HBM budget "
             "the estimator admitted"),
        Rule("recompile-surface", ERROR,
             "a config family presents more distinct staged-input "
             "signatures to the jit cache than its declared budget"),
        Rule("manifest-missing", WARNING,
             "a registered program has no manifest entry pinning its "
             "census — run tools/fedverify.py --update-manifest and "
             "review the diff"),
    ]
}

#: mesh-axis buckets census ops classify into
AXES = ("client", "stage", "model", "world", "none")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

#: collective op kinds the census tracks (order = report order)
COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "collective-permute")


@dataclasses.dataclass
class CollectiveOp:
    """One collective in a compiled module."""
    kind: str
    axis: str            # client | model | world | none
    nbytes: int          # payload bytes (operand for reductions/permutes,
    #                      result for gathers — the bytes one chip moves)
    result_shape: str
    operand_bytes: int
    result_bytes: int
    groups: Tuple[Tuple[int, ...], ...]


# --------------------------------------------------------------------------
# HLO text parsing (pure stdlib)
# --------------------------------------------------------------------------

_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*(?:e[0-9]+m[0-9]+(?:fn)?)?)"
                       r"\[([0-9,]*)\]")


def _shape_nbytes(segment: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape token in ``segment``."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in _DTYPE_BYTES:
            continue
        elems = 1
        for d in dims.split(","):
            if d.strip():
                elems *= int(d)
        total += elems * _DTYPE_BYTES[dtype]
    return total


_IOTA_RE = re.compile(r"\[([0-9,]+)\]<=\[([0-9,]+)\]"
                      r"(?:T\(([0-9,]+)\))?")


def _parse_replica_groups(text: str) -> List[List[int]]:
    """``replica_groups={{0,1},{2,3}}`` or the iota form
    ``[2,4]<=[4,2]T(1,0)`` -> explicit device-id groups."""
    text = text.strip()
    m = _IOTA_RE.match(text)
    if m:
        out_dims = [int(d) for d in m.group(1).split(",")]
        src_dims = [int(d) for d in m.group(2).split(",")]
        n = 1
        for d in src_dims:
            n *= d
        ids = list(range(n))
        if m.group(3):
            perm = [int(p) for p in m.group(3).split(",")]
            # reshape ids to src_dims, transpose by perm, flatten
            strides = [0] * len(src_dims)
            acc = 1
            for i in range(len(src_dims) - 1, -1, -1):
                strides[i] = acc
                acc *= src_dims[i]
            tdims = [src_dims[p] for p in perm]
            tstrides = [strides[p] for p in perm]
            flat = []

            def rec(depth, off):
                if depth == len(tdims):
                    flat.append(off)
                    return
                for i in range(tdims[depth]):
                    rec(depth + 1, off + i * tstrides[depth])

            rec(0, 0)
            ids = flat
        group = out_dims[-1] if out_dims else n
        return [ids[i:i + group] for i in range(0, len(ids), group)]
    groups: List[List[int]] = []
    for g in re.findall(r"\{([0-9,\s]+)\}", text):
        groups.append([int(d) for d in g.split(",") if d.strip()])
    return groups


def classify_groups(groups: Sequence[Sequence[int]],
                    mesh_shape: Tuple[int, ...]) -> str:
    """Which mesh axis a collective's device groups span.

    Device ids follow the canonical mesh layout (``core.mesh.make_mesh``)
    with data/seq pinned to 1: on the 2-D ``(c, m)`` layout
    ``id = client_coord * m + model_coord``; on the 3-D pipeline layout
    ``(c, s, m)`` it is ``(client_coord * s + stage_coord) * m +
    model_coord`` (docs/PIPELINE.md) — so a stage-ring
    ``collective-permute``'s pairs vary only the middle coordinate."""
    dims = tuple(int(d) for d in mesh_shape)
    names = (("client", "model") if len(dims) == 2
             else ("client", "stage", "model"))
    axes: Set[str] = set()
    for g in groups:
        if len(g) <= 1:
            continue
        varying: Set[str] = set()
        inner = 1
        for i in range(len(dims) - 1, -1, -1):
            coords = {(d // inner) % dims[i] for d in g}
            if len(coords) > 1:
                varying.add(names[i])
            inner *= dims[i]
        if len(varying) > 1:
            axes.add("world")
        elif varying:
            axes.add(varying.pop())
    if not axes:
        return "none"
    if len(axes) == 1:
        return axes.pop()
    return "world"


_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<result>[^=]*?)\s*"
    r"(?P<kind>all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)(?:-start)?\(")


def parse_collectives(hlo: str,
                      mesh_shape: Tuple[int, int]) -> List[CollectiveOp]:
    """Census of every collective op in a compiled (post-SPMD) HLO
    module.  Payload-byte convention: reductions/permutes/all-to-all
    count operand bytes (what enters the wire), gathers count result
    bytes (what one chip assembles) — consistent with the ObsCarry model
    (docs/COLLECTIVE_PRECISION.md)."""
    ops: List[CollectiveOp] = []
    for line in hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        result_seg = m.group("result")
        operand_seg = line[m.end():]
        # strip trailing attribute clauses from the operand segment so
        # attribute shapes (none today) can't pollute the byte count
        operand_seg = operand_seg.split("), ")[0]
        rg = re.search(r"replica_groups=("
                       r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?"
                       r"|\{[0-9,{}\s]*\})", line)
        if rg:
            groups = _parse_replica_groups(rg.group(1))
        else:
            pairs = re.search(r"source_target_pairs=\{(.*?)\}\}", line)
            if pairs:
                # permute pairs: classify by the coordinate that moves
                ids = re.findall(r"\{(\d+),(\d+)\}", pairs.group(0))
                groups = [[int(a), int(b)] for a, b in ids]
            else:
                groups = []
        axis = classify_groups(groups, mesh_shape)
        operand_bytes = _shape_nbytes(operand_seg)
        result_bytes = _shape_nbytes(result_seg)
        nbytes = result_bytes if kind == "all-gather" else operand_bytes
        ops.append(CollectiveOp(
            kind=kind, axis=axis, nbytes=nbytes,
            result_shape=result_seg.strip(),
            operand_bytes=operand_bytes, result_bytes=result_bytes,
            groups=tuple(tuple(g) for g in groups)))
    return ops


def parse_io_aliases(hlo: str) -> Set[int]:
    """Flat parameter indices of the module's ``input_output_alias`` map
    (the donations XLA actually honored).  The map nests braces
    (``{1}: (1, {}, may-alias)``), so scan balanced rather than regex to
    the first ``}``."""
    start = hlo.find("input_output_alias={")
    if start < 0:
        return set()
    i = hlo.index("{", start)
    depth, j = 0, i
    for j in range(i, min(len(hlo), i + 100_000)):
        if hlo[j] == "{":
            depth += 1
        elif hlo[j] == "}":
            depth -= 1
            if depth == 0:
                break
    body = hlo[i:j + 1]
    return {int(p) for p in re.findall(r":\s*\((\d+)", body)}


def parse_num_partitions(hlo: str) -> int:
    m = re.search(r"num_partitions=(\d+)", hlo)
    return int(m.group(1)) if m else 1


_MLIR_DTYPES = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint64": "ui64", "uint32": "ui32", "uint16": "ui16",
    "uint8": "ui8", "bool": "i1",
}


def parse_stablehlo_args(stablehlo: str) -> List[Tuple[Tuple[int, ...],
                                                       str, bool]]:
    """``(shape, mlir dtype, is_buffer_donor)`` per ``@main`` argument of
    a lowered module.  Argument numbering here matches the compiled
    module's parameter numbering (jit prunes dead args BEFORE emitting
    StableHLO, and the SPMD partitioner preserves parameter order)."""
    start = stablehlo.find("@main")
    if start < 0:
        return []
    sig = stablehlo[start:]
    cut = sig.find("->")
    sig = sig[:cut if cut > 0 else len(sig)]
    out = []
    for m in re.finditer(
            r"%arg\d+:\s*tensor<([^>]*)>\s*(\{[^}]*\})?", sig):
        parts = m.group(1).split("x")
        dtype = parts[-1]
        dims = tuple(int(d) for d in parts[:-1])
        attrs = m.group(2) or ""
        donor = ("jax.buffer_donor" in attrs
                 or "tf.aliasing_output" in attrs)
        out.append((dims, dtype, donor))
    return out


def align_donated_args(leaves: Sequence[Tuple[Tuple[int, ...], str]],
                       donated_flat: Set[int],
                       module_args: Sequence[Tuple[Tuple[int, ...], str,
                                                   bool]]
                       ) -> Tuple[Set[int], Set[int]]:
    """Map engine-declared donated flat leaves onto the lowered module's
    (pruned) argument numbering.

    jit silently drops arguments nothing consumes (e.g. the RNG key
    stack of a dropout-free fp32 config), renumbering every later
    parameter — so donated indices must be re-derived against the module
    by aligning the flat (shape, dtype) sequence greedily (order is
    preserved; a leaf that doesn't match the next kept argument was
    pruned).  Returns ``(kept_donated, undonated)``: module arg indices
    of the donated leaves that survived, and the subset of those the
    module does NOT mark ``jax.buffer_donor`` (a donation lost at trace
    level)."""
    kept: Set[int] = set()
    undonated: Set[int] = set()
    j = 0
    for i, (shape, dtype) in enumerate(leaves):
        if j >= len(module_args):
            break
        mshape, mdtype, donor = module_args[j]
        if mshape == tuple(shape) and mdtype == dtype:
            if i in donated_flat:
                kept.add(j)
                if not donor:
                    undonated.add(j)
            j += 1
        # else: leaf i was pruned from the module; stay on arg j
    return kept, undonated


def leaf_sig(leaf) -> Tuple[Tuple[int, ...], str]:
    """(shape, mlir dtype) of one abstract arg leaf."""
    import numpy as np
    name = np.dtype(leaf.dtype).name
    return tuple(leaf.shape), _MLIR_DTYPES.get(name, name)


def count_stablehlo_collectives(stablehlo: str) -> Dict[str, int]:
    """Pre-partitioning view: explicit ``stablehlo.*`` collective ops
    (the shard_map-manual collectives the *program* asked for, before
    GSPMD adds the ones sharding propagation needs)."""
    out = {}
    for op in ("all_reduce", "reduce_scatter", "all_gather", "all_to_all",
               "collective_permute"):
        n = len(re.findall(r"stablehlo\." + op + r"\b", stablehlo))
        if n:
            out[op.replace("_", "-")] = n
    return out


# --------------------------------------------------------------------------
# program report + checks (pure once the report exists)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramReport:
    """Everything the contract checks need about one lowered program."""
    name: str
    mesh_shape: Tuple[int, int]
    num_partitions: int
    collectives: List[CollectiveOp]
    requested_collectives: Dict[str, int]     # stablehlo (pre-SPMD) view
    donated_params: Set[int]                  # declared (module arg idx)
    undonated_params: Set[int]                # declared but not donor-marked
    aliased_params: Set[int]                  # honored by the module
    #: [(leaf path, expected spec, actual spec)] where expected != actual
    sharding_violations: List[Tuple[str, str, str]]
    #: leaf paths expected sharded that compiled fully replicated
    rereplicated: List[str]
    n_sharding_leaves: int                    # leaves actually compared
    modeled_bytes: Dict[str, float]           # ObsCarry model, per axis
    memory: Dict[str, float]                  # per-chip module footprint
    estimate_bytes: float                     # memory_estimate upper bound
    signatures: List[str]
    signature_budget: int

    # -- census views ------------------------------------------------------
    def collective_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.collectives:
            key = f"{op.kind}.{op.axis}"
            out[key] = out.get(key, 0) + 1
        return dict(sorted(out.items()))

    def census_bytes(self) -> Dict[str, float]:
        out = {a: 0.0 for a in AXES}
        for op in self.collectives:
            out[op.axis] += float(op.nbytes)
        return {a: b for a, b in out.items() if b}

    def per_chip_total(self) -> float:
        m = self.memory
        return (m.get("argument", 0.0) + m.get("temp", 0.0)
                + m.get("output", 0.0) - m.get("alias", 0.0))

    def to_manifest_entry(self) -> Dict[str, Any]:
        """Measured census fields of a manifest entry (budgets/bands are
        policy, added/kept by the manifest writer)."""
        return {
            "mesh_shape": list(self.mesh_shape),
            "num_partitions": self.num_partitions,
            "collectives": self.collective_counts(),
            "requested_collectives": dict(sorted(
                self.requested_collectives.items())),
            "census_bytes": {k: round(v) for k, v in
                             self.census_bytes().items()},
            "modeled_bytes": {k: round(v) for k, v in
                              self.modeled_bytes.items() if v},
            "donated": sorted(self.donated_params),
            "hbm": {k: round(v) for k, v in self.memory.items()},
            "per_chip_total": round(self.per_chip_total()),
            "estimate_bytes": round(self.estimate_bytes),
            "distinct_signatures": len(set(self.signatures)),
        }


#: default policy fields stamped into fresh manifest entries
DEFAULT_BYTES_TOL = 0.10
#: census/model ratio band: the ObsCarry model prices the intended hot-
#: path wire traffic; GSPMD's fp32 staging (flat-view gathers) legally
#: rides on top, so the band admits up to 4x before calling drift
DEFAULT_RATIO_BAND = (0.25, 4.0)
DEFAULT_HBM_BUDGET = 256 * 1024 * 1024
#: census bytes on an axis the model prices at zero below this are noise
#: (scalar psums, key permutes), not drift
DRIFT_FLOOR_BYTES = 4096


def _find(rule: str, program: str, msg: str) -> Finding:
    return Finding(rule=rule, severity=VERIFY_RULES[rule].severity,
                   path=f"fedverify:{program}", line=0, col=0, message=msg)


def run_checks(report: ProgramReport, entry: Optional[Dict[str, Any]],
               suppressions: Iterable[Dict[str, str]] = ()) -> List[Finding]:
    """The five contract families over one program report + its manifest
    entry.  Returns findings with manifest suppressions applied."""
    p = report.name
    out: List[Finding] = []

    # 1. sharding contracts --------------------------------------------------
    for path, exp, act in report.sharding_violations:
        out.append(_find("sharding-contract", p,
                         f"output leaf {path}: compiled sharding {act} != "
                         f"declared resting placement {exp}"))
    for path in report.rereplicated:
        out.append(_find(
            "silent-rereplication", p,
            f"output leaf {path} is declared SHARDED but compiled fully "
            f"replicated — each chip now holds the whole buffer "
            f"(docs/MESH_2D.md resting-placement contract)"))

    # 2. collective census ---------------------------------------------------
    if entry is None:
        out.append(_find("manifest-missing", p,
                         "no contracts.json entry pins this program's "
                         "census"))
    else:
        counts = report.collective_counts()
        want = dict(entry.get("collectives", {}))
        if counts != want:
            diff = []
            for k in sorted(set(counts) | set(want)):
                a, b = counts.get(k, 0), want.get(k, 0)
                if a != b:
                    diff.append(f"{k}: compiled {a} != pinned {b}")
            out.append(_find("collective-census", p,
                             "collective census drifted from the "
                             "manifest: " + "; ".join(diff)))
        tol = float(entry.get("bytes_tolerance", DEFAULT_BYTES_TOL))
        got_b = report.census_bytes()
        want_b = {k: float(v)
                  for k, v in entry.get("census_bytes", {}).items()}
        for axis in sorted(set(got_b) | set(want_b)):
            a, b = got_b.get(axis, 0.0), want_b.get(axis, 0.0)
            if b == 0.0 and a > DRIFT_FLOOR_BYTES:
                out.append(_find("collective-census", p,
                                 f"{axis}-axis collectives move {a:.0f} "
                                 f"bytes; manifest pins none"))
            elif b > 0.0 and abs(a - b) > tol * b:
                out.append(_find(
                    "collective-census", p,
                    f"{axis}-axis collective bytes {a:.0f} drifted past "
                    f"±{tol:.0%} of the pinned {b:.0f}"))

        # 2b. ObsCarry byte-model cross-check ------------------------------
        band = entry.get("model_ratio_band", list(DEFAULT_RATIO_BAND))
        lo, hi = float(band[0]), float(band[1])
        for axis in ("client", "stage", "model"):
            modeled = float(report.modeled_bytes.get(axis, 0.0))
            actual = got_b.get(axis, 0.0)
            if modeled <= 0.0:
                if actual > DRIFT_FLOOR_BYTES:
                    out.append(_find(
                        "byte-model-drift", p,
                        f"ObsCarry models zero {axis}-axis bytes but the "
                        f"compiled collectives move {actual:.0f}"))
                continue
            ratio = actual / modeled
            if not (lo <= ratio <= hi):
                out.append(_find(
                    "byte-model-drift", p,
                    f"compiled {axis}-axis bytes {actual:.0f} are "
                    f"{ratio:.2f}x the ObsCarry model's {modeled:.0f} — "
                    f"outside the pinned band [{lo}, {hi}] "
                    f"(docs/COLLECTIVE_PRECISION.md wire model)"))

    # 3. donation ------------------------------------------------------------
    undonated = sorted(report.undonated_params)
    if undonated:
        out.append(_find(
            "donation-aliasing", p,
            f"input leaves {undonated} the engine declares donated carry "
            f"no jax.buffer_donor mark in the lowered module — the "
            f"donation was lost at the jit boundary (dropped donation)"))
    missing = sorted(report.donated_params - report.undonated_params
                     - report.aliased_params)
    if missing:
        out.append(_find(
            "donation-aliasing", p,
            f"declared-donated input leaves {missing} are absent from "
            f"the compiled module's input_output_alias map — XLA will "
            f"keep both copies live (dropped donation)"))

    # 4. HBM fit -------------------------------------------------------------
    measured = report.per_chip_total()
    budget = float((entry or {}).get("hbm_budget_bytes",
                                     DEFAULT_HBM_BUDGET))
    est = float(report.estimate_bytes)
    if est > 0.0 and measured > est:
        out.append(_find(
            "hbm-fit", p,
            f"per-chip lowered footprint {measured:.0f} B exceeds the "
            f"memory estimator's {est:.0f} B — the estimator no longer "
            f"upper-bounds the lowering, so its 'fits' verdicts are "
            f"unsound (core/memory_estimate.py)"))
    if est <= budget < measured:
        out.append(_find(
            "hbm-fit", p,
            f"estimator admits this config under the "
            f"{budget:.0f} B budget ({est:.0f} B) but the compiled "
            f"module needs {measured:.0f} B/chip — it would OOM on the "
            f"hardware the estimate approved"))

    # 5. recompile surface ---------------------------------------------------
    distinct = len(set(report.signatures))
    budget_n = int((entry or {}).get("signature_budget",
                                     report.signature_budget))
    if distinct > budget_n:
        out.append(_find(
            "recompile-surface", p,
            f"config family presents {distinct} distinct staged-input "
            f"signatures to the jit cache (budget {budget_n}) — every "
            f"extra signature is a full recompile at run time"))

    # manifest suppressions ---------------------------------------------------
    for f in out:
        for s in suppressions:
            if s.get("rule") == f.rule and \
                    s.get("program") in (p, "*"):
                f.suppressed = True
                reason = s.get("reason", "")
                if reason:
                    f.message += f" [suppressed: {reason}]"
    return out


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------

def default_manifest_path() -> str:
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, "tests", "data", "fedverify",
                        "contracts.json")


def load_manifest(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or default_manifest_path()
    if not os.path.exists(path):
        return {"version": 1, "programs": {}, "suppressions": []}
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def update_manifest(reports: Sequence[ProgramReport],
                    path: Optional[str] = None) -> Dict[str, Any]:
    """Refresh the measured census fields, preserving policy fields
    (budgets, tolerance bands) and suppressions — the diff is the review
    surface."""
    path = path or default_manifest_path()
    manifest = load_manifest(path)
    progs = manifest.setdefault("programs", {})
    for rep in reports:
        old = progs.get(rep.name, {})
        entry = rep.to_manifest_entry()
        entry["bytes_tolerance"] = old.get("bytes_tolerance",
                                           DEFAULT_BYTES_TOL)
        entry["model_ratio_band"] = old.get("model_ratio_band",
                                            list(DEFAULT_RATIO_BAND))
        entry["hbm_budget_bytes"] = old.get("hbm_budget_bytes",
                                            DEFAULT_HBM_BUDGET)
        entry["signature_budget"] = old.get("signature_budget",
                                            rep.signature_budget)
        progs[rep.name] = entry
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return manifest


# --------------------------------------------------------------------------
# lowering (jax from here down; imported lazily so the parsing half stays
# stdlib-importable)
# --------------------------------------------------------------------------

def _abstract(tree):
    """Concrete staged args -> ShapeDtypeStruct avals carrying the staged
    shardings, so ``.lower`` sees exactly what the driver's call would
    present — without touching (or needing) the data."""
    import jax
    from jax.sharding import NamedSharding

    def leaf(l):
        sh = getattr(l, "sharding", None)
        if not isinstance(sh, NamedSharding):
            sh = None
        return jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh)

    return jax.tree_util.tree_map(leaf, tree)


def donated_leaf_indices(args: Sequence[Any],
                         donate_argnums: Sequence[int]) -> Set[int]:
    """Flat module-parameter indices of the donated positional args (jit
    flattens args in order; None subtrees contribute no leaves)."""
    import jax
    idx, out = 0, set()
    donate = set(donate_argnums)
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in donate:
            out.update(range(idx, idx + n))
        idx += n
    return out


def _leaf_path_items(tree) -> List[Tuple[str, Any]]:
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def compare_shardings(actual_tree, expected_tree, out_struct_tree,
                      prefix: str = ""):
    """(violations, rereplicated, n_compared) between a compiled output
    subtree's shardings and the layout's declared resting placement."""
    import jax
    violations: List[Tuple[str, str, str]] = []
    rerepl: List[str] = []
    act = _leaf_path_items(actual_tree)
    exp = _leaf_path_items(expected_tree)
    structs = _leaf_path_items(out_struct_tree)
    if len(act) != len(exp) or len(act) != len(structs):
        violations.append((prefix or "<tree>",
                           f"{len(exp)} leaves", f"{len(act)} leaves"))
        return violations, rerepl, 0
    n = 0
    for (path, a), (_, e), (_, st) in zip(act, exp, structs):
        if e is None:
            continue
        n += 1
        shape = tuple(getattr(st, "shape", ()))
        try:
            same = a.is_equivalent_to(e, len(shape))
        except Exception:
            same = str(a) == str(e)
        if same:
            continue
        name = prefix + path
        # the PR 6 class: the compiled output spreads the leaf over FEWER
        # devices than declared — some mesh factor (e.g. ``model`` under
        # a partial-auto shard_map) silently re-replicated, so each chip
        # holds more of the buffer than the layout budgeted
        if _shard_count(a, shape) < _shard_count(e, shape):
            rerepl.append(name)
        else:
            violations.append((name, _spec_str(e), _spec_str(a)))
    return violations, rerepl, n


def _shard_count(sharding, shape) -> int:
    """How many distinct shards a sharding splits ``shape`` into (1 =
    fully replicated)."""
    try:
        local = sharding.shard_shape(tuple(shape))
    except Exception:
        return 1
    total = math.prod(shape) or 1
    per = math.prod(local) or 1
    return max(1, total // per)


def _spec_str(sharding) -> str:
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else str(sharding)


def lower_program(name: str, jit_fn, args: Sequence[Any],
                  donate_argnums: Sequence[int],
                  mesh_shape: Tuple[int, int] = (1, 1),
                  expected_out: Optional[Dict[int, Any]] = None,
                  modeled_bytes: Optional[Dict[str, float]] = None,
                  estimate_bytes: float = 0.0,
                  signatures: Sequence[str] = ("static",),
                  signature_budget: int = 1) -> ProgramReport:
    """AOT-lower ``jit_fn`` on ``args``' abstract avals, compile on the
    host platform, and assemble the :class:`ProgramReport` the contract
    checks consume.  ``expected_out`` maps output tuple indices to
    expected-sharding pytrees (``None`` leaves are unchecked)."""
    import jax

    absargs = _abstract(tuple(args))
    lowered = jit_fn.lower(*absargs)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    hlo = compiled.as_text()

    num_partitions = parse_num_partitions(hlo)
    collectives = parse_collectives(hlo, mesh_shape)
    aliased = parse_io_aliases(hlo)
    flat_sigs = [leaf_sig(l)
                 for l in jax.tree_util.tree_leaves(absargs)]
    donated, undonated = align_donated_args(
        flat_sigs, donated_leaf_indices(args, donate_argnums),
        parse_stablehlo_args(stablehlo))

    violations: List[Tuple[str, str, str]] = []
    rerepl: List[str] = []
    n_cmp = 0
    if expected_out:
        out_struct = jax.eval_shape(jit_fn, *absargs)
        out_shardings = compiled.output_shardings
        for idx, expected in expected_out.items():
            if expected is None:
                continue
            v, r, n = compare_shardings(out_shardings[idx], expected,
                                        out_struct[idx],
                                        prefix=f"out[{idx}]")
            violations += v
            rerepl += r
            n_cmp += n

    mem: Dict[str, float] = {}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for key, attr in (("argument", "argument_size_in_bytes"),
                          ("output", "output_size_in_bytes"),
                          ("temp", "temp_size_in_bytes"),
                          ("alias", "alias_size_in_bytes")):
            mem[key] = float(getattr(ma, attr, 0) or 0)

    return ProgramReport(
        name=name, mesh_shape=tuple(mesh_shape),
        num_partitions=num_partitions, collectives=collectives,
        requested_collectives=count_stablehlo_collectives(stablehlo),
        donated_params=donated, undonated_params=undonated,
        aliased_params=aliased,
        sharding_violations=violations, rereplicated=rerepl,
        n_sharding_leaves=n_cmp,
        modeled_bytes=dict(modeled_bytes or {}),
        memory=mem, estimate_bytes=float(estimate_bytes),
        signatures=list(signatures),
        signature_budget=int(signature_budget))


# --------------------------------------------------------------------------
# canonical program registry
# --------------------------------------------------------------------------

#: rounds enumerated when fingerprinting a program's recompile surface
SIGNATURE_ROUNDS = 4


def _canonical_args(**over):
    """One tiny, fast, deterministic config family every canonical
    program derives from (mirrors tests/test_mesh.py::args_for)."""
    import fedml_tpu
    from ..arguments import load_arguments
    args = load_arguments()
    args.update(
        dataset="synthetic", num_classes=10, input_shape=(28, 28, 1),
        train_size=256, test_size=64, model="lr",
        client_num_in_total=16, client_num_per_round=8, comm_round=8,
        epochs=1, batch_size=16, learning_rate=0.1, random_seed=7,
        frequency_of_the_test=100,
        # homo partition => every cohort pads to ONE pow2 step class, so
        # the canonical recompile budget is exactly 1 program (the hetero
        # pow2-class budget is exercised by the mutation tests)
        partition_method="homo",
    )
    args.update(**over)
    return fedml_tpu.init(args)


def _make_api(args):
    from .. import data as data_mod, device as device_mod, model as model_mod
    dev = device_mod.get_device(args)
    dataset, out_dim = data_mod.load(args)
    model = model_mod.create(args, out_dim)
    if getattr(args, "backend", "sp") == "mesh":
        from ..simulation.mesh.engine import MeshFedAvgAPI
        return MeshFedAvgAPI(args, dev, dataset, model)
    from ..simulation.sp.fedavg_api import FedAvgAPI
    return FedAvgAPI(args, dev, dataset, model)


def _data_plane_bytes(args_tuple, state) -> float:
    """Per-chip bytes of the non-state inputs of a staged round call —
    exact, from each leaf's shape/sharding (the lowering's data plane the
    state estimator doesn't price)."""
    import jax
    import numpy as np

    def per_chip(leaf) -> float:
        shape = tuple(leaf.shape)
        nbytes = float(np.dtype(leaf.dtype).itemsize) * float(
            math.prod(shape) or 1)
        sh = getattr(leaf, "sharding", None)
        if sh is None or not shape:
            return nbytes
        try:
            local = sh.shard_shape(shape)
        except Exception:
            return nbytes
        frac = math.prod(local) / max(1, math.prod(shape))
        return nbytes * frac

    state_ids = {id(l) for l in jax.tree_util.tree_leaves(state)}
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tuple(args_tuple)):
        if id(leaf) in state_ids:
            continue
        total += per_chip(leaf)
    return total


def _stage_fraction(api) -> float:
    """Fraction of the params living in the staged leaves (the layer-
    stacked chunks that shard over ``stage`` — docs/PIPELINE.md)."""
    import jax
    params = api.state.global_params
    staged = set(api.trainer.pipe.stage_leaves)
    total = sta = 0
    for name, sub in params.items():
        n = sum(int(l.size) for l in jax.tree_util.tree_leaves(sub))
        total += n
        if name in staged:
            sta += n
    return sta / max(1, total)


def _mesh_round_estimate(api, args_tuple, members: int = 1,
                         steps: int = 1, rounds_fused: int = 1) -> float:
    """Upper-bound per-chip footprint from core/memory_estimate.py plus
    the exact data plane of this staged call."""
    from ..core import tree as tree_util
    from ..core.memory_estimate import (MeshStateLayout,
                                        estimate_round_footprint)
    c = int(getattr(api, "n_shards", 1))
    m = int(getattr(api, "n_model_shards", 1))
    s = int(getattr(api, "n_stage_shards", 1))
    n_params = tree_util.num_params(
        api.state.global_params) // max(1, members)
    shape = (c, s, m) if s > 1 else (c, m)
    lo = MeshStateLayout(
        n_params=n_params, mesh_shape=shape,
        clients_per_round=api.clients_per_round,
        algorithm=api.server_opt.algorithm,
        collective_precision=api.collective_precision,
        stage_fraction=_stage_fraction(api) if s > 1 else 1.0)
    cohort_bytes = _cohort_work_bytes(api, steps)
    data_bytes = _data_plane_bytes(args_tuple, api.state)
    return estimate_round_footprint(
        lo, data_bytes=data_bytes, cohort_bytes=cohort_bytes,
        members=members, rounds_fused=rounds_fused)["total"]


def _cohort_work_bytes(api, steps: int) -> float:
    """Gathered cohort tensors per chip (x + y at f32) at the staged
    pow2-padded step count — the term the round's temps scale with."""
    clients_local = -(-api.clients_per_round
                      // int(getattr(api, "n_shards", 1)))
    shape = tuple(api.dataset.train_x.shape[1:])
    feat = math.prod(shape) or 1
    return float(clients_local * max(1, steps) * api.batch_size
                 * (feat + 1) * 4)


def _modeled_round_bytes(api, steps: int = 1) -> Dict[str, float]:
    """The ObsCarry collective_bytes model for one mesh round — computed
    exactly the way ``mesh/engine.py::_bytes_model`` does."""
    from ..core import tree as tree_util
    from ..simulation.mesh import collectives as coll
    scatter = api.update_sharding == "scatter"
    if scatter:
        n_flat = api.layout.flat_spec_of(
            api.state.global_params).padded_size
    else:
        n_flat = tree_util.num_params(api.state.global_params)
    mode = "scatter" if scatter else "replicated"
    m = api.n_model_shards
    s = int(getattr(api, "n_stage_shards", 1))
    n_payload = n_flat if scatter else -(-n_flat // (m * s))
    cbytes = coll.client_axis_bytes(n_payload, api.n_shards,
                                    api.collective_precision,
                                    api.quant_block, mode)
    mbytes = coll.model_axis_bytes(n_flat, m, mode=mode)
    out = {"client": float(cbytes), "model": float(mbytes)}
    if s > 1:
        tr = api.trainer
        out["stage"] = float(coll.stage_axis_bytes(
            n_flat, s, mode=mode, hidden=tr.hidden,
            microbatch=api.batch_size // tr.n_micro,
            n_micro=tr.n_micro, steps=steps))
    return out


def _build_sp(name: str, **over) -> ProgramReport:
    api = _make_api(_canonical_args(backend="sp", **over))
    progs = {kind: (fn, args, donate)
             for kind, fn, args, donate in api.lowerable_programs()}
    fn, args, donate = progs["round"]
    sigs = [api.round_signature(r) for r in range(SIGNATURE_ROUNDS)]
    members = api.population.size if api.population else 1
    est = _mesh_round_estimate(api, args, members=members,
                               steps=int(args[1].shape[1]))
    return lower_program(name, fn, args, donate, mesh_shape=(1, 1),
                         estimate_bytes=est, signatures=sigs)


@registry.register("sp_round", "sp", "round", quick=True)
def build_sp_round() -> ProgramReport:
    """Single-process round: the reference program every mesh layout must
    match (vmap clients, gather cohort)."""
    return _build_sp("sp_round")


@registry.register("population_p4", "sp", "round")
def build_population_p4() -> ProgramReport:
    """P=4 experiment population vmapped over the sp round — one
    dispatch, member-stacked state (docs/PRIMITIVES.md)."""
    return _build_sp("population_p4", population=4)


def _make_async_api():
    from ..simulation.async_engine import FedBuffAPI
    args = _canonical_args(backend="sp", federated_optimizer="fedbuff")
    from .. import data as data_mod, device as device_mod, model as model_mod
    dev = device_mod.get_device(args)
    dataset, out_dim = data_mod.load(args)
    model = model_mod.create(args, out_dim)
    return FedBuffAPI(args, dev, dataset, model)


@registry.register("async_dispatch", "async", "dispatch")
def build_async_dispatch() -> ProgramReport:
    """The buffered-async engine's generation dispatch (docs/ASYNC.md):
    client phase + per-client unreduced aggregate rows, staged exactly
    like the sync round."""
    api = _make_async_api()
    fn, args, donate = api.dispatch_program(0)
    sigs = [api.dispatch_signature(g) for g in range(SIGNATURE_ROUNDS)]
    est = _mesh_round_estimate(api, args, steps=int(args[1].shape[1]))
    return lower_program("async_dispatch", fn, args, donate,
                         mesh_shape=(1, 1), estimate_bytes=est,
                         signatures=sigs)


@registry.register("async_buffer_apply", "async", "buffer")
def build_async_apply() -> ProgramReport:
    """The buffered-async engine's buffer apply: finish the size-K row
    buffer (occupancy/staleness as traced data) + server transition,
    with the buffer donated for the in-place reset."""
    api = _make_async_api()
    fn, args, donate = api.buffer_program()
    est = _mesh_round_estimate(api, args, steps=1)
    return lower_program("async_buffer_apply", fn, args, donate,
                         mesh_shape=(1, 1), estimate_bytes=est)


def _build_mesh(name: str, mesh_shape: str, update_sharding: str,
                alg: str = "FedAvg", block: int = 1,
                precision: str = "fp32", **over) -> ProgramReport:
    api = _make_api(_canonical_args(
        backend="mesh", mesh_shape=mesh_shape,
        update_sharding=update_sharding, federated_optimizer=alg,
        collective_precision=precision, round_block=block, **over))
    scatter = api.update_sharding == "scatter"
    quantized = api.collective_precision != "fp32"
    progs = {kind: (fn, args, donate)
             for kind, fn, args, donate in api.lowerable_programs()}
    expected = {0: api.layout.state_sharding(api.state, scatter,
                                             quantized)}
    if block > 1:
        fn, args, donate = progs["block"]
        if api.client_table is not None:
            expected[2] = api.layout.table_sharding(api.client_table)
        sigs = [api.block_signature(s)
                for s in range(0, api.comm_rounds, block)]
        steps = int(args[1].shape[2])
    else:
        fn, args, donate = progs["round"]
        sigs = [api.round_signature(r) for r in range(SIGNATURE_ROUNDS)]
        steps = int(args[1].shape[1])
    est = _mesh_round_estimate(api, args, steps=steps,
                               rounds_fused=max(1, block))
    # a fused block's census covers K rounds' collectives; scale the
    # per-round ObsCarry model to match
    modeled = {k: v * max(1, block)
               for k, v in _modeled_round_bytes(api, steps=steps).items()}
    s = int(getattr(api, "n_stage_shards", 1))
    shape = ((api.n_shards, s, api.n_model_shards) if s > 1
             else (api.n_shards, api.n_model_shards))
    return lower_program(
        name, fn, args, donate, mesh_shape=shape,
        expected_out=expected, modeled_bytes=modeled,
        estimate_bytes=est, signatures=sigs)


@registry.register("mesh1d_replicated", "mesh", "round")
def build_mesh1d_replicated() -> ProgramReport:
    """8-shard 1-D mesh, replicated merge (per-leaf psum all-reduce)."""
    return _build_mesh("mesh1d_replicated", "8,1", "replicated")


@registry.register("mesh1d_scatter", "mesh", "round", quick=True)
def build_mesh1d_scatter() -> ProgramReport:
    """8-shard 1-D mesh, reduce-scatter merge + shard-resident FedOpt
    moments (the arXiv:2004.13336 cross-replica layout)."""
    return _build_mesh("mesh1d_scatter", "8,1", "scatter", alg="FedOpt")


@registry.register("mesh2d_replicated", "mesh", "round")
def build_mesh2d_replicated() -> ProgramReport:
    """(4,2) client x model mesh, replicated merge — the GSPMD partial-
    auto shard_map layout (docs/MESH_2D.md)."""
    return _build_mesh("mesh2d_replicated", "4,2", "replicated")


@registry.register("mesh2d_scatter", "mesh", "round")
def build_mesh2d_scatter() -> ProgramReport:
    """(4,2) client x model mesh, scatter merge: flat server state over
    BOTH axes — the layout the PR 6 re-replication bug hit."""
    return _build_mesh("mesh2d_scatter", "4,2", "scatter", alg="FedOpt")


@registry.register("mesh_block8", "mesh", "block")
def build_mesh_block8() -> ProgramReport:
    """Fused round_block=8 scan on the 8-shard scatter mesh with the
    SCAFFOLD client table threading the donated carry."""
    return _build_mesh("mesh_block8", "8,1", "scatter", alg="SCAFFOLD",
                       block=8)


#: 3-D pipeline canonical config (docs/PIPELINE.md): pipe_mlp's stacked
#: blocks split 4 layers over s=2 stages, rows over m=2; microbatches=2
_PIPE_OVER = dict(model="pipe_mlp", model_dim=16, model_layers=4,
                  microbatches=2)


@registry.register("mesh3d_scatter", "mesh", "round")
def build_mesh3d_scatter() -> ProgramReport:
    """(2,2,2) client x stage x model pipeline mesh, scatter merge +
    FedOpt moments over c*s*m: the microbatched-pipeline train phase
    (stage-ring collective-permutes) feeding the byte-identical client
    merge (docs/PIPELINE.md)."""
    return _build_mesh("mesh3d_scatter", "2,2,2", "scatter", alg="FedOpt",
                       **_PIPE_OVER)


@registry.register("mesh3d_block8", "mesh", "block")
def build_mesh3d_block8() -> ProgramReport:
    """Fused round_block=8 scan on the (2,2,2) pipeline mesh with the
    SCAFFOLD client table — the fully-manual pipeline shard_map under the
    fused scan (docs/PIPELINE.md, docs/ROUND_FUSION.md)."""
    return _build_mesh("mesh3d_block8", "2,2,2", "scatter", alg="SCAFFOLD",
                       block=8, **_PIPE_OVER)


def _serving_engine():
    import jax
    import jax.numpy as jnp
    from ..llm.model import LlamaConfig, LlamaLM
    from ..serving.batching import ContinuousBatchingEngine
    cfg = LlamaConfig(vocab_size=97, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_dim=64, max_seq_len=48,
                      dtype=jnp.float32, attn_impl="blockwise")
    model = LlamaLM(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    eng = ContinuousBatchingEngine(model, variables["params"], slots=4,
                                   buf_len=48)
    return eng


def _serving_estimate(eng) -> float:
    import jax
    from ..core.memory_estimate import estimate_serving_memory
    from ..core import tree as tree_util
    cache_bytes = sum(l.nbytes for l in
                      jax.tree_util.tree_leaves(eng._caches))
    n_params = tree_util.num_params(eng.raw_params)
    return estimate_serving_memory(
        n_params=n_params, param_bytes=4, n_slots=eng.n_slots,
        cache_bytes=cache_bytes, vocab_size=97,
        horizon=eng.horizon)["total"]


def _build_serving(which: str) -> ProgramReport:
    eng = _serving_engine()
    try:
        est = _serving_estimate(eng)
        progs = {n: (fn, args, donate)
                 for n, fn, args, donate in eng.step_programs()}
        fn, args, donate = progs[which]
        return lower_program(f"serving_{which}", fn, args, donate,
                             mesh_shape=(1, 1), estimate_bytes=est)
    finally:
        eng.stop()


@registry.register("serving_decode_step", "serving", "step")
def build_serving_step() -> ProgramReport:
    """The continuous-batching engine's batched decode step (vmapped
    KV-cache decode over all slots, horizon-scanned)."""
    return _build_serving("decode_step")


@registry.register("serving_insert_cache", "serving", "step", quick=True)
def build_serving_insert() -> ProgramReport:
    """The engine's donated cache-insert (admission writes one slot's KV
    into the stacked cache in place)."""
    return _build_serving("insert_cache")


def _serving_paged_engine():
    import jax
    import jax.numpy as jnp
    from ..llm.model import LlamaConfig, LlamaLM
    from ..serving.batching import ContinuousBatchingEngine
    cfg = LlamaConfig(vocab_size=97, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_dim=64, max_seq_len=48,
                      dtype=jnp.float32, attn_impl="blockwise")
    model = LlamaLM(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))
    return ContinuousBatchingEngine(model, variables["params"], slots=4,
                                    buf_len=48, kv_page_tokens=8,
                                    prefill_chunk_tokens=16)


def _serving_paged_estimate(eng) -> float:
    import jax
    from ..core.memory_estimate import estimate_paged_serving_memory
    from ..core import tree as tree_util
    pool_leaves = jax.tree_util.tree_leaves(eng._pool)
    pool_bytes = sum(l.nbytes for l in pool_leaves)
    # transient gather window: pool[block_tables] per layer — price K+V
    # for ~2 live layers at the full per-slot window width
    per_page = max(l.nbytes / l.shape[0] for l in pool_leaves)
    window = 2 * 2 * eng.n_slots * eng.max_blocks * per_page
    return estimate_paged_serving_memory(
        n_params=tree_util.num_params(eng.raw_params), param_bytes=4,
        n_slots=eng.n_slots, pool_bytes=pool_bytes,
        block_table_bytes=float(eng._btabs.nbytes), window_bytes=window,
        vocab_size=97, horizon=eng.horizon)["total"]


def _build_serving_paged(which: str) -> ProgramReport:
    eng = _serving_paged_engine()
    try:
        est = _serving_paged_estimate(eng)
        progs = {n: (fn, args, donate)
                 for n, fn, args, donate in eng.step_programs()}
        fn, args, donate = progs[which]
        return lower_program(f"serving_paged_{which}", fn, args, donate,
                             mesh_shape=(1, 1), estimate_bytes=est)
    finally:
        eng.stop()


@registry.register("serving_paged_decode_step", "serving", "step")
def build_serving_paged_step() -> ProgramReport:
    """The paged engine's batched decode step: one shared page pool
    (DONATED — page moves are block-table data, never copies) addressed
    through traced per-slot block tables, horizon-scanned.  Pins the
    zero-steady-state-recompile memory plane of docs/SERVING.md."""
    return _build_serving_paged("decode_step")


@registry.register("serving_paged_prefill_chunk", "serving", "step",
                   quick=True)
def build_serving_paged_chunk() -> ProgramReport:
    """The paged engine's fixed-shape prefill chunk (donated pool,
    traced sample index): ONE program serves every chunk of every
    prompt — intermediate and final alike."""
    return _build_serving_paged("prefill_chunk")


#: name -> builder; the canonical verification surface, derived from the
#: first-class Program registry (``analysis/programs.py``, ISSUE 18) —
#: registration order is the report order everywhere (CLI, manifest,
#: bench --verify).
PROGRAMS = {p.name: p.build for p in registry.registered()}


def verify_programs(names: Optional[Sequence[str]] = None,
                    manifest_path: Optional[str] = None,
                    update: bool = False
                    ) -> Tuple[List[Finding], List[ProgramReport]]:
    """Build + lower + check the named programs (all by default).

    ``update=True`` rewrites the manifest's measured fields from these
    reports before checking, so a fresh manifest verifies clean and the
    git diff carries the contract change."""
    names = list(names) if names else list(PROGRAMS)
    unknown = [n for n in names if n not in PROGRAMS]
    if unknown:
        raise KeyError(f"unknown program(s) {unknown}; "
                       f"have {list(PROGRAMS)}")
    reports = [PROGRAMS[n]() for n in names]
    if update:
        update_manifest(reports, manifest_path)
    manifest = load_manifest(manifest_path)
    suppressions = manifest.get("suppressions", [])
    findings: List[Finding] = []
    for rep in reports:
        entry = manifest.get("programs", {}).get(rep.name)
        findings.extend(run_checks(rep, entry, suppressions))
    return findings, reports
