"""programs — the first-class registry of lowerable staged programs.

Before ISSUE 18, the canonical verification surface was an ad-hoc dict of
11 builder functions private to ``fedverify``, ``bench.py --verify``
hard-coded its quick subset, and each engine exposed
``round_program``/``block_program`` hooks that every caller had to know
about individually.  This module is the ONE list all three iterate:

- **fedverify** registers each canonical builder here (the
  ``@register`` decorator) and derives its ``PROGRAMS`` mapping from
  :func:`registered` — adding a program (e.g. the 3-D pipeline round) is
  a registration, not a 12th parallel edit.
- **bench.py --verify** asks the registry for names (all, or the
  ``quick`` subset flagged at registration).
- **engines** (``FedAvgAPI`` / ``MeshFedAvgAPI``) expose their lowerable
  surface through :func:`lowerable`, which walks :data:`ENGINE_HOOKS` —
  one list of hook names instead of per-caller knowledge of which
  methods exist (docs/FEDVERIFY.md, "How to add a program").

The registry holds NAMES and metadata only; builders import jax/engines
lazily when called, so importing this module (or fedverify's pure-stdlib
parsing half) stays dependency-free.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Program:
    """One registered lowerable program: a named, canonical
    ``(jit_fn, staged args, donate_argnums)`` family that AOT-lowers on
    abstract shapes.  ``build()`` returns the
    :class:`~.fedverify.ProgramReport` the contract checks consume."""
    name: str
    family: str                  # "sp" | "mesh" | "async" | "serving"
    kind: str                    # "round" | "block" | "dispatch" | "step"
    description: str
    build: Callable[[], Any]
    quick: bool = False          # part of the FEDML_VERIFY_QUICK subset


_REGISTRY: Dict[str, Program] = {}


def register(name: str, family: str, kind: str, quick: bool = False):
    """Decorator: register a ProgramReport builder under ``name``.
    Registration order is the canonical report order everywhere (CLI,
    manifest, ``bench --verify``)."""
    def deco(fn):
        _REGISTRY[name] = Program(
            name=name, family=family, kind=kind,
            description=" ".join((fn.__doc__ or "").split()),
            build=fn, quick=quick)
        return fn
    return deco


def registered() -> Tuple[Program, ...]:
    """Every registered program, in registration order."""
    return tuple(_REGISTRY.values())


def names(quick: bool = False) -> List[str]:
    return [p.name for p in _REGISTRY.values() if p.quick or not quick]


def get(name: str) -> Program:
    return _REGISTRY[name]


#: engine methods producing a lowerable ``(fn, args, donate)`` triple —
#: the single list :func:`lowerable` walks.  ``block_program`` only
#: applies when the config actually fuses rounds.
ENGINE_HOOKS: Tuple[Tuple[str, str], ...] = (
    ("round", "round_program"),
    ("block", "block_program"),
    ("dispatch", "dispatch_program"),
    ("buffer", "buffer_program"),
)


def lowerable(api) -> List[Tuple[str, Any, tuple, tuple]]:
    """The engine side of the registry: every ``(kind, fn, args,
    donate)`` this engine instance can stage at its current config.
    Engines expose it as ``lowerable_programs()``; fedverify's builders
    and any future driver iterate THIS instead of knowing hook names."""
    out = []
    for kind, hook in ENGINE_HOOKS:
        if not hasattr(api, hook):
            continue
        if kind == "block" and int(
                getattr(api, "_round_block", None)
                or getattr(api, "round_block", 1) or 1) <= 1:
            continue
        try:
            fn, args, donate = getattr(api, hook)()
        except (NotImplementedError, AttributeError):
            # the hook exists (e.g. inherited) but this config can't
            # stage it — bucketed cohorts, host-resident data, or an
            # async engine that round-trips through dispatch instead
            continue
        out.append((kind, fn, args, donate))
    return out


__all__ = ["Program", "register", "registered", "names", "get",
           "lowerable", "ENGINE_HOOKS"]
