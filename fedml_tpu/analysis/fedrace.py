"""fedrace — lock-discipline & deadlock checker for the host concurrency
plane (docs/FEDRACE.md).

The fifth static-analysis layer (after fedlint, fedverify, fedproto and the
runtime audit): the planes those checkers cover all run *under threads* —
async staging pools, pager write-back executors, fedguard retransmit and
heartbeat beacons, metricsd scrape handlers, the serving engine loop — and
nothing checked the host locking discipline that keeps them coherent.

Pure stdlib like fedlint/fedproto: loaded by file path from
``tools/fedrace.py`` so no jax install is needed.  The extraction half
builds, per class ("scope"):

- **thread roots** — methods spawned via ``threading.Thread(target=)``,
  ``threading.Timer``, ``executor.submit``, ``atexit.register``, nested
  ``BaseHTTPRequestHandler`` ``do_*`` methods, plus the implicit
  ``<caller>`` root (public API called from the driver thread),
- **locks** — ``Lock``/``RLock``/``Condition`` attributes, with
  ``Condition(self._lock)`` aliased to the lock it wraps,
- **accesses** — reads/writes of shared mutable attributes together with
  the set of locks held (lexical ``with self._lock:`` regions plus a
  fixpoint over intra-class call sites: a helper only ever called under a
  lock inherits that lock),
- **acquisition edges** — nested lock acquisitions, including cross-class
  edges through attributes whose type is another package class,
- **spawn sites** — thread/timer/executor construction and their
  join/cancel/daemon/shutdown cleanup paths.

Four rule families check that surface (see RACE_RULES); the witnessed
concurrency surface pins into ``tests/data/fedrace/concurrency.json`` with
``--update-manifest`` preserving suppressions (the fedproto/fedverify
workflow), and the runtime half (:class:`fedml_tpu.analysis.runtime.
LockOrderAudit`) replays live acquisition order against the same pin.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

try:  # package import (tests); tools/fedrace.py loads by file path instead
    from .fedlint import (
        ERROR,
        WARNING,
        Rule,
        Finding,
        dotted_name,
        last_attr,
        build_parents,
        iter_py_files,
        render_findings,
        findings_to_json,
        exit_code,
    )
except ImportError:  # pragma: no cover - exercised via tools/fedrace.py
    from fedlint import (  # type: ignore
        ERROR,
        WARNING,
        Rule,
        Finding,
        dotted_name,
        last_attr,
        build_parents,
        iter_py_files,
        render_findings,
        findings_to_json,
        exit_code,
    )


# --------------------------------------------------------------------------
# rule registry
# --------------------------------------------------------------------------

RACE_RULES: Dict[str, Rule] = {
    r.name: r
    for r in [
        Rule("unguarded-shared-write", ERROR,
             "an attribute written on one thread root and read/written on "
             "another with no common guarding lock — a torn read or lost "
             "update under the live federation's thread mix"),
        Rule("lock-order-cycle", ERROR,
             "the package-wide nested-acquisition graph has a cycle "
             "(including cross-class edges through typed attributes) — two "
             "threads taking the locks in opposite order deadlock"),
        Rule("blocking-under-lock", ERROR,
             "a blocking call (thread/future join, device sync, fsync, "
             "sleep, comm send, queue.get without timeout, executor "
             "shutdown) inside a held lock region — stalls every thread "
             "contending for the lock and invites deadlock"),
        Rule("leaked-thread", ERROR,
             "a thread/timer/executor created with no join/cancel/daemon/"
             "shutdown path — the fedproto finish-liveness analogue for "
             "host threads: shutdown never converges"),
        Rule("unresolved-concurrency", WARNING,
             "a thread target / timer callback the extractor cannot "
             "resolve to a method — the scope's root set is incomplete"),
        Rule("manifest-drift", ERROR,
             "the extracted concurrency surface drifted from the pinned "
             "manifest — review and refresh with --update-manifest"),
        Rule("manifest-missing", WARNING,
             "a concurrency scope has no manifest entry yet — run "
             "tools/fedrace.py check --update-manifest"),
    ]
}


# --------------------------------------------------------------------------
# extraction data model
# --------------------------------------------------------------------------

CALLER_ROOT = "<caller>"

# attribute types that are internally synchronized (or are thread handles,
# which the leaked-thread rule owns) — excluded from shared-write analysis
_SYNCED_TYPES = {
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event",
    "Semaphore", "BoundedSemaphore", "Barrier", "local",
    "Thread", "Timer", "ThreadPoolExecutor", "ProcessPoolExecutor",
}

_LOCK_TYPES = {"Lock", "RLock"}

# container / dict-like constructors whose method calls can mutate
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "move_to_end",
}

# blocking calls flagged under a held lock: attr-name -> description
_BLOCKING_ATTRS = {
    "block_until_ready": "device sync",
    "device_get": "device transfer",
    "fsync": "fsync",
    "sleep": "sleep",
    "send_message": "comm send",
    "serve_forever": "serve loop",
    "recv": "socket recv",
}


@dataclasses.dataclass
class Access:
    attr: str
    kind: str                    # "read" | "write"
    method: str
    line: int
    col: int
    locks: FrozenSet[str]        # canonical lock names held lexically


@dataclasses.dataclass
class Spawn:
    kind: str                    # "thread" | "timer" | "executor"
    target: Optional[str]        # resolved root method name (threads/timers)
    handle: Optional[str]        # "self.X" attr or local var the handle binds to
    method: str
    line: int
    col: int
    cleanup: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class AcqEdge:
    src: str                     # canonical "Scope.lock"
    dst: str
    method: str
    line: int


@dataclasses.dataclass
class BlockSite:
    lock: str                    # canonical lock name held
    call: str                    # rendered call, e.g. "self._t.join"
    why: str
    method: str
    line: int
    col: int


@dataclasses.dataclass
class Scope:
    """Concurrency surface of one class (or a module's top level)."""

    name: str                    # "module.ClassName" or "module.<module>"
    path: str
    line: int
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    lock_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    roots: Dict[str, str] = dataclasses.field(default_factory=dict)
    root_closure: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    spawns: List[Spawn] = dataclasses.field(default_factory=list)
    edges: List[AcqEdge] = dataclasses.field(default_factory=list)
    blocking: List[BlockSite] = dataclasses.field(default_factory=list)
    entry_locks: Dict[str, FrozenSet[str]] = dataclasses.field(
        default_factory=dict)

    def canonical_lock(self, attr: str) -> Optional[str]:
        attr = self.lock_aliases.get(attr, attr)
        return attr if attr in self.locks else None

    def qualified(self, lock: str) -> str:
        return f"{self.name.rsplit('.', 1)[-1]}.{lock}"

    def interesting(self) -> bool:
        """Scopes with any concurrency surface enter the manifest."""
        return bool(self.locks or self.spawns
                    or any(k != "caller" for k in self.roots.values()))


# --------------------------------------------------------------------------
# small helpers
# --------------------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for a ``self.X`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _call_type(call: ast.Call) -> Optional[str]:
    """Constructor class name for ``threading.Lock()`` / ``dict()`` etc."""
    return last_attr(call.func)


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_str_receiver(node: ast.AST) -> bool:
    """True for ``"".join`` / ``b",".join`` / ``os.path.join`` receivers."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (str, bytes)):
        return True
    d = dotted_name(node)
    return bool(d) and (d == "os.path" or d.endswith(".path") or d == "os")


class _FuncScopes:
    """Maps every node in a method to the function whose body owns it,
    without descending into nested ClassDefs (nested handler classes are
    extracted separately)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn


def _assign_calls(value: ast.AST) -> List[ast.Call]:
    """Constructor call(s) on the RHS of an assignment, looking through
    conditional expressions (``TPE(...) if enabled else None``)."""
    if isinstance(value, ast.Call):
        return [value]
    if isinstance(value, ast.IfExp):
        return _assign_calls(value.body) + _assign_calls(value.orelse)
    if isinstance(value, (ast.BoolOp,)):
        out: List[ast.Call] = []
        for v in value.values:
            out.extend(_assign_calls(v))
        return out
    return []


def _iter_body(fn: ast.AST):
    """Walk a function body without entering nested ClassDef bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# per-scope extractor
# --------------------------------------------------------------------------

class ScopeExtractor:
    """One class (or one module top level) -> a :class:`Scope`."""

    def __init__(self, name: str, path: str, node: ast.AST,
                 class_names: Dict[str, str]):
        self.scope = Scope(name=name, path=path,
                           line=getattr(node, "lineno", 1))
        self.node = node
        self.class_names = class_names  # ClassName -> scope name (package)
        self.warnings: List[Finding] = []
        # method name -> set of method names it calls via self.M(...)
        self.calls: Dict[str, Set[str]] = {}
        # method name -> list of (callee, locks-held-at-site)
        self.call_sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        # method -> list of (attr, callee-method-or-None, locks) for
        # cross-class edges through typed attributes
        self.xcalls: Dict[str, List[Tuple[str, Optional[str],
                                          FrozenSet[str]]]] = {}

    # -- pass 1: methods, locks, attribute types ---------------------------

    def collect_methods(self):
        body = self.node.body if hasattr(self.node, "body") else []
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.scope.methods[stmt.name] = stmt

    def collect_types(self):
        """Classify ``self.X = <ctor>()`` assignments (any method, so
        lazily-built locks/pools are seen too)."""
        assigns: List[Tuple[ast.Assign, ast.Call]] = []
        for fn in self.scope.methods.values():
            for node in _iter_body(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for value in _assign_calls(node.value):
                    assigns.append((node, value))
        # plain locks first so `Condition(self._lock)` aliases resolve
        # regardless of statement visit order
        for node, value in assigns:
            if _call_type(value) in _LOCK_TYPES:
                self._classify_assign(node, value)
        for node, value in assigns:
            if _call_type(value) not in _LOCK_TYPES:
                self._classify_assign(node, value)

    def _classify_assign(self, node: ast.Assign, value: ast.Call):
        ctor = _call_type(value)
        if ctor is None:
            return
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            if ctor in _LOCK_TYPES:
                self.scope.locks.setdefault(attr, ctor)
            elif ctor == "Condition":
                wrapped = None
                if value.args:
                    wrapped = _self_attr(value.args[0])
                if wrapped and wrapped in self.scope.locks:
                    self.scope.lock_aliases[attr] = wrapped
                else:
                    self.scope.locks.setdefault(attr, "Condition")
            self.scope.attr_types.setdefault(attr, ctor)

    # -- pass 2: thread roots + spawn sites --------------------------------

    def _resolve_target(self, node: ast.AST, method: str,
                        line: int) -> Optional[str]:
        if isinstance(node, ast.Call) and \
                last_attr(node.func) == "partial" and node.args:
            return self._resolve_target(node.args[0], method, line)
        attr = _self_attr(node)
        if attr is not None and attr in self.scope.methods:
            return attr
        if isinstance(node, ast.Name) and node.id in self.scope.methods:
            return node.id
        if isinstance(node, ast.Attribute):
            # dotted target (`self._httpd.serve_forever`, `conn.run`):
            # the body runs in another scope — spawn hygiene still applies
            # through the handle, so no warning
            return None
        if isinstance(node, ast.Name):
            # local closure defined in the same method: treat the closure
            # as a pseudo-method so its body is analyzed under a root
            fn = self.scope.methods.get(method)
            if fn is not None:
                for sub in _iter_body(fn):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) and \
                            sub.name == node.id:
                        pseudo = f"{method}.{node.id}"
                        self.scope.methods.setdefault(pseudo, sub)
                        return pseudo
        self.warnings.append(Finding(
            "unresolved-concurrency", WARNING, self.scope.path, line, 0,
            f"[{self.scope.name}] cannot resolve thread target "
            f"{ast.dump(node)[:60]} to a method"))
        return None

    def collect_roots(self):
        for mname, fn in list(self.scope.methods.items()):
            for node in _iter_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                ctor = _call_type(node)
                dn = dotted_name(node.func) or ""
                if ctor == "Thread":
                    tgt = _kw(node, "target")
                    root = tgt is not None and self._resolve_target(
                        tgt, mname, node.lineno) or None
                    if root:
                        self.scope.roots.setdefault(root, "thread")
                    self._record_spawn("thread", root, node, mname)
                elif ctor == "Timer":
                    cb = node.args[1] if len(node.args) > 1 else \
                        _kw(node, "function")
                    root = cb is not None and self._resolve_target(
                        cb, mname, node.lineno) or None
                    if root:
                        self.scope.roots.setdefault(root, "timer")
                    self._record_spawn("timer", root, node, mname)
                elif ctor in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
                    self._record_spawn("executor", None, node, mname)
                elif last_attr(node.func) == "submit" and node.args:
                    root = self._resolve_submit(node.args[0], mname)
                    if root:
                        self.scope.roots.setdefault(root, "executor")
                elif dn.endswith("atexit.register") or dn == "register" and \
                        dotted_name(node.func) == "atexit.register":
                    if node.args:
                        root = _self_attr(node.args[0])
                        if root and root in self.scope.methods:
                            self.scope.roots.setdefault(root, "atexit")
        # nested HTTP handler classes: their do_* methods run on server
        # threads; outer methods they call become http-root reachable
        self._collect_http_roots()

    def _resolve_submit(self, node: ast.AST, method: str) -> Optional[str]:
        attr = _self_attr(node)
        if attr is not None and attr in self.scope.methods:
            return attr
        if isinstance(node, ast.Name) and node.id in self.scope.methods:
            return node.id
        return None

    def _record_spawn(self, kind: str, target: Optional[str],
                      call: ast.Call, method: str):
        handle = None
        parent = self._assign_parent.get(call)
        if parent is not None:
            tgt = parent.targets[0] if isinstance(parent, ast.Assign) and \
                parent.targets else None
            attr = _self_attr(tgt) if tgt is not None else None
            if attr is not None:
                handle = f"self.{attr}"
            elif isinstance(tgt, ast.Name):
                handle = tgt.id
        sp = Spawn(kind=kind, target=target, handle=handle, method=method,
                   line=call.lineno, col=call.col_offset)
        daemon = _kw(call, "daemon")
        if isinstance(daemon, ast.Constant) and daemon.value is True:
            sp.cleanup.add("daemon")
        if self._withitem_calls.get(call):
            sp.cleanup.add("context")    # `with ThreadPoolExecutor() as ..`
        self.scope.spawns.append(sp)

    def _collect_http_roots(self):
        body = getattr(self.node, "body", [])
        nested: List[ast.ClassDef] = []
        for fn in self.scope.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.ClassDef):
                    nested.append(node)
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                nested.append(stmt)
        for cls in nested:
            bases = {last_attr(b) or "" for b in cls.bases}
            if not bases & {"BaseHTTPRequestHandler",
                            "SimpleHTTPRequestHandler"}:
                continue
            # any outer-scope method the handler body names is reachable
            # from an HTTP root
            for node in ast.walk(cls):
                if isinstance(node, ast.Call):
                    callee = last_attr(node.func)
                    if callee in self.scope.methods:
                        self.scope.roots.setdefault(callee, "http")

    # -- pass 3: guarded regions, accesses, edges, blocking ---------------

    def _prepass(self):
        """Index Assign parents and with-items for spawn handle binding."""
        self._assign_parent: Dict[ast.AST, ast.Assign] = {}
        self._withitem_calls: Dict[ast.AST, bool] = {}
        for fn in self.scope.methods.values():
            for node in _iter_body(fn):
                if isinstance(node, ast.Assign):
                    for call in _assign_calls(node.value):
                        self._assign_parent[call] = node
                if isinstance(node, ast.With):
                    for item in node.items:
                        if isinstance(item.context_expr, ast.Call):
                            self._withitem_calls[item.context_expr] = True

    def walk_method(self, mname: str, fn: ast.AST):
        held: List[str] = list(self.scope.entry_locks.get(mname, ()))
        self._walk_stmts(getattr(fn, "body", []), mname, held,
                         local_types=self._local_types(fn))

    def _local_types(self, fn: ast.AST) -> Dict[str, str]:
        """Local var -> ctor type, for join/result receiver typing."""
        out: Dict[str, str] = {}
        for node in _iter_body(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                ctor = _call_type(node.value)
                callee = last_attr(node.value.func)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if ctor in ("Thread", "Timer",
                                    "ThreadPoolExecutor"):
                            out[tgt.id] = ctor
                        elif callee == "submit":
                            out[tgt.id] = "Future"
        return out

    def _walk_stmts(self, stmts: Sequence[ast.stmt], mname: str,
                    held: List[str], local_types: Dict[str, str]):
        for stmt in stmts:
            self._walk_stmt(stmt, mname, held, local_types)

    def _walk_stmt(self, stmt: ast.stmt, mname: str, held: List[str],
                   local_types: Dict[str, str]):
        if isinstance(stmt, ast.With):
            acquired: List[str] = []
            for item in stmt.items:
                lk = self._lock_of(item.context_expr)
                if lk is not None:
                    if held:
                        self.scope.edges.append(AcqEdge(
                            src=self.scope.qualified(held[-1]),
                            dst=self.scope.qualified(lk),
                            method=mname, line=stmt.lineno))
                    held.append(lk)
                    acquired.append(lk)
                else:
                    self._visit_expr(item.context_expr, mname, held,
                                     local_types)
            self._walk_stmts(stmt.body, mname, held, local_types)
            for _ in acquired:
                held.pop()
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return          # nested defs analyzed only if they are roots
        if isinstance(stmt, ast.ClassDef):
            return
        # acquire()/release() outside `with` — conservative region
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            callee = last_attr(call.func)
            if callee in ("acquire", "release") and \
                    isinstance(call.func, ast.Attribute):
                lk = self._lock_of_node(call.func.value)
                if lk is not None:
                    if callee == "acquire":
                        if held:
                            self.scope.edges.append(AcqEdge(
                                src=self.scope.qualified(held[-1]),
                                dst=self.scope.qualified(lk),
                                method=mname, line=stmt.lineno))
                        held.append(lk)
                    elif lk in held:
                        held.remove(lk)
                    return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._walk_stmt(node, mname, held, local_types)
            elif isinstance(node, ast.excepthandler):
                # not an ast.stmt — without this branch an except body
                # would fall to the expression visitor and lose the held
                # stack, mis-flagging `with lock:` regions inside handlers
                self._walk_stmts(node.body, mname, held, local_types)
            else:
                self._visit_expr(node, mname, held, local_types,
                                 store_ctx=self._store_target(stmt))

    def _store_target(self, stmt: ast.stmt) -> Set[ast.AST]:
        """Expression nodes that are *written* by this statement."""
        out: Set[ast.AST] = set()
        if isinstance(stmt, ast.Assign):
            out.update(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            out.add(stmt.target)
        elif isinstance(stmt, ast.Delete):
            out.update(stmt.targets)
        return out

    def _lock_of(self, node: ast.AST) -> Optional[str]:
        """Canonical lock for `self._lock` / `self._cv` context exprs."""
        return self._lock_of_node(node)

    def _lock_of_node(self, node: ast.AST) -> Optional[str]:
        attr = _self_attr(node)
        if attr is None:
            return None
        return self.scope.canonical_lock(attr)

    def _visit_expr(self, node: ast.AST, mname: str, held: List[str],
                    local_types: Dict[str, str],
                    store_ctx: Optional[Set[ast.AST]] = None):
        store_ctx = store_ctx or set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._visit_call(sub, mname, held, local_types)
            attr = _self_attr(sub)
            if attr is None:
                continue
            if self.scope.canonical_lock(attr) is not None:
                continue
            kind = "write" if (
                sub in store_ctx or
                isinstance(getattr(sub, "ctx", None),
                           (ast.Store, ast.Del))) else "read"
            self.scope.accesses.append(Access(
                attr=attr, kind=kind, method=mname,
                line=sub.lineno, col=sub.col_offset,
                locks=frozenset(held)))
        # subscript stores: self.X[k] = v writes the container X
        for sub in ast.walk(node):
            if isinstance(sub, ast.Subscript) and \
                    isinstance(getattr(sub, "ctx", None),
                               (ast.Store, ast.Del)):
                attr = _self_attr(sub.value)
                if attr is not None and \
                        self.scope.canonical_lock(attr) is None:
                    self.scope.accesses.append(Access(
                        attr=attr, kind="write", method=mname,
                        line=sub.lineno, col=sub.col_offset,
                        locks=frozenset(held)))

    def _visit_call(self, call: ast.Call, mname: str, held: List[str],
                    local_types: Dict[str, str]):
        callee = last_attr(call.func)
        recv = call.func.value if isinstance(call.func, ast.Attribute) \
            else None
        # self.M(...) intra-class call graph (+ held locks at the site)
        if recv is not None and isinstance(recv, ast.Name) and \
                recv.id == "self" and callee in self.scope.methods:
            self.calls.setdefault(mname, set()).add(callee)
            self.call_sites.setdefault(callee, []).append(
                (mname, frozenset(held)))
        # self.attr.M(...) cross-class call through a typed attribute
        if recv is not None:
            rattr = _self_attr(recv)
            if rattr is not None and rattr in self.scope.attr_types:
                rtype = self.scope.attr_types[rattr]
                if rtype in self.class_names and held:
                    self.xcalls.setdefault(mname, []).append(
                        (rattr, callee, frozenset(held)))
            # mutating container method = write access on the attribute
            if rattr is not None and callee in _MUTATOR_METHODS and \
                    self.scope.canonical_lock(rattr) is None:
                self.scope.accesses.append(Access(
                    attr=rattr, kind="write", method=mname,
                    line=call.lineno, col=call.col_offset,
                    locks=frozenset(held)))
        if held:
            self._check_blocking(call, callee, recv, mname, held,
                                 local_types)

    def _check_blocking(self, call: ast.Call, callee: Optional[str],
                        recv: Optional[ast.AST], mname: str,
                        held: List[str], local_types: Dict[str, str]):
        why = None
        rendered = dotted_name(call.func) or callee or "<call>"
        if callee in _BLOCKING_ATTRS:
            why = _BLOCKING_ATTRS[callee]
        elif callee == "join" and recv is not None and \
                not _is_str_receiver(recv):
            rattr = _self_attr(recv)
            rtype = None
            if rattr is not None:
                rtype = self.scope.attr_types.get(rattr)
            elif isinstance(recv, ast.Name):
                rtype = local_types.get(recv.id)
            if rtype in ("Thread", "Timer"):
                why = "thread join"
        elif callee == "result":
            rattr = _self_attr(recv) if recv is not None else None
            rtype = None
            if rattr is not None:
                rtype = self.scope.attr_types.get(rattr)
            elif isinstance(recv, ast.Name):
                rtype = local_types.get(recv.id)
            if rtype == "Future":
                why = "future wait"
        elif callee == "shutdown" and recv is not None:
            rattr = _self_attr(recv)
            rtype = self.scope.attr_types.get(rattr) if rattr else None
            if isinstance(recv, ast.Name):
                rtype = local_types.get(recv.id)
            wait = _kw(call, "wait")
            if rtype in ("ThreadPoolExecutor", "ProcessPoolExecutor") and \
                    not (isinstance(wait, ast.Constant)
                         and wait.value is False):
                why = "executor shutdown"
        elif callee == "get" and recv is not None:
            rattr = _self_attr(recv)
            rtype = self.scope.attr_types.get(rattr) if rattr else None
            if rtype in ("Queue", "LifoQueue", "PriorityQueue",
                         "SimpleQueue"):
                timeout = _kw(call, "timeout")
                blocking = _kw(call, "block")
                untimed = timeout is None or (
                    isinstance(timeout, ast.Constant)
                    and timeout.value is None)
                nonblock = isinstance(blocking, ast.Constant) and \
                    blocking.value is False
                if untimed and not nonblock and not call.args:
                    why = "queue get without timeout"
        if why is not None:
            self.scope.blocking.append(BlockSite(
                lock=held[-1], call=rendered, why=why, method=mname,
                line=call.lineno, col=call.col_offset))

    # -- pass 4: closures + guard fixpoint --------------------------------

    def _closure(self, starts: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [s for s in starts if s in self.scope.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(self.calls.get(m, ()))
        return seen

    def build_closures(self):
        spawn_targets = set(self.scope.roots)
        for root in list(self.scope.roots):
            self.scope.root_closure[root] = self._closure([root])
        # implicit caller root: public surface + dunders, minus methods
        # that exist only as spawn targets
        public = [m for m in self.scope.methods
                  if (not m.startswith("_") or
                      (m.startswith("__") and m.endswith("__")))
                  and m not in ("__init__",)
                  and m not in spawn_targets and "." not in m]
        caller = self._closure(public)
        caller -= {"__init__"}
        if caller:
            self.scope.roots[CALLER_ROOT] = "caller"
            self.scope.root_closure[CALLER_ROOT] = caller

    def guard_fixpoint(self):
        """A method only ever called with lock L held inherits L; iterate
        so the guarantee flows through helper chains."""
        entry: Dict[str, FrozenSet[str]] = {}
        for _ in range(4):
            changed = False
            for m in self.scope.methods:
                sites = self.call_sites.get(m, [])
                if not sites:
                    continue
                # entry guard = intersection over every in-class call site
                # (caller's own entry guard unions with locks at the site)
                acc: Optional[Set[str]] = None
                for caller, locks in sites:
                    eff = set(locks) | set(entry.get(caller, ()))
                    acc = eff if acc is None else (acc & eff)
                # publicly reachable methods can also be called bare
                if m in self.scope.root_closure.get(CALLER_ROOT, set()) and \
                        not m.startswith("_"):
                    acc = set()
                if m in self.scope.roots:
                    acc = set()
                new = frozenset(acc or ())
                if entry.get(m, frozenset()) != new:
                    entry[m] = new
                    changed = True
            if not changed:
                break
        self.scope.entry_locks = entry

    # -- driver ------------------------------------------------------------

    def run(self) -> Scope:
        self.collect_methods()
        self.collect_types()
        self._prepass()
        self.collect_roots()
        # first pass: accesses with lexical locks + call graph
        for mname, fn in list(self.scope.methods.items()):
            self.walk_method(mname, fn)
        self.build_closures()
        self.guard_fixpoint()
        if any(self.scope.entry_locks.values()):
            # re-walk with entry guards seeding the held stack so helper
            # accesses/edges/blocking reflect the inherited lock
            self.scope.accesses = []
            self.scope.edges = []
            self.scope.blocking = []
            self.calls = {}
            self.call_sites = {}
            self.xcalls = {}
            for mname, fn in list(self.scope.methods.items()):
                self.walk_method(mname, fn)
        return self.scope


# --------------------------------------------------------------------------
# package extraction
# --------------------------------------------------------------------------

def _scope_name(path: str, cls: Optional[str]) -> str:
    base = os.path.splitext(os.path.basename(path))[0]
    return f"{base}.{cls}" if cls else f"{base}.<module>"


def extract_concurrency(paths: Iterable[str]
                        ) -> Tuple[Dict[str, Scope], List[Finding],
                                   Dict[str, "ScopeExtractor"]]:
    """Extract every class scope (plus per-module top-level pseudo-scopes
    for spawn hygiene) under `paths`."""
    files = iter_py_files(paths)
    class_names: Dict[str, str] = {}
    trees: List[Tuple[str, ast.Module]] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        trees.append((path, tree))
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                class_names.setdefault(stmt.name,
                                       _scope_name(path, stmt.name))
    scopes: Dict[str, Scope] = {}
    extractors: Dict[str, ScopeExtractor] = {}
    warnings: List[Finding] = []
    for path, tree in trees:
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                ex = ScopeExtractor(_scope_name(path, stmt.name), path,
                                    stmt, class_names)
                sc = ex.run()
                if sc.interesting():
                    scopes[sc.name] = sc
                    extractors[sc.name] = ex
                    warnings.extend(ex.warnings)
        # module top level: wrap top-level functions in a pseudo-scope so
        # leaked threads spawned outside classes are still seen
        mod_fns = [s for s in tree.body
                   if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
        if mod_fns:
            pseudo = ast.ClassDef(name="<module>", bases=[], keywords=[],
                                  body=list(mod_fns), decorator_list=[])
            pseudo.lineno = 1
            pseudo.col_offset = 0
            ex = ScopeExtractor(_scope_name(path, None), path, pseudo,
                                class_names)
            sc = ex.run()
            if sc.spawns or any(k != "caller" for k in sc.roots.values()):
                scopes[sc.name] = sc
                extractors[sc.name] = ex
                warnings.extend(ex.warnings)
    # resolve spawn cleanup paths NOW, so the extracted surface is fully
    # determined before any consumer runs — a manifest written before the
    # leaked-thread check must serialize the same cleanup sets the check
    # later sees (otherwise --update-manifest self-reports drift)
    for name, sc in scopes.items():
        for sp in sc.spawns:
            sp.cleanup = _spawn_cleanup(sc, extractors[name], sp)
    return scopes, warnings, extractors


# --------------------------------------------------------------------------
# rule checks
# --------------------------------------------------------------------------

def _mk(rule: str, path: str, line: int, msg: str,
        col: int = 0) -> Finding:
    return Finding(rule, RACE_RULES[rule].severity, path, line, col, msg)


def _shared_attrs(sc: Scope) -> Dict[str, List[Access]]:
    """Attrs with >=1 write outside __init__ (config assigned once in
    __init__ is happens-before thread start and exempt), excluding locks,
    synced types, and pure bool/None publishes."""
    by_attr: Dict[str, List[Access]] = {}
    for a in sc.accesses:
        if a.method == "__init__":
            continue
        if sc.attr_types.get(a.attr) in _SYNCED_TYPES:
            continue
        by_attr.setdefault(a.attr, []).append(a)
    out: Dict[str, List[Access]] = {}
    for attr, accs in by_attr.items():
        if any(a.kind == "write" for a in accs):
            out[attr] = accs
    return out


def _roots_of(sc: Scope, method: str) -> Set[str]:
    return {root for root, clo in sc.root_closure.items() if method in clo}


def _is_publish_only(sc: Scope, attr: str, extractor: "ScopeExtractor"
                     ) -> bool:
    """True when every non-init write of `attr` stores a bare constant —
    an atomic publish under the GIL (e.g. ``self._closed = True``)."""
    for mname, fn in extractor.scope.methods.items():
        if mname == "__init__":
            continue
        for node in _iter_body(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if _self_attr(tgt) == attr and \
                            not isinstance(node.value, ast.Constant):
                        return False
                    if isinstance(tgt, (ast.Subscript, ast.Attribute)) \
                            and _self_attr(getattr(tgt, "value", None)) \
                            == attr:
                        return False
            elif isinstance(node, ast.AugAssign):
                tgt = node.target
                if _self_attr(tgt) == attr:
                    return False
                if isinstance(tgt, (ast.Subscript, ast.Attribute)) and \
                        _self_attr(getattr(tgt, "value", None)) == attr:
                    return False
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS and \
                    _self_attr(node.func.value) == attr:
                return False
    return True


def check_shared_writes(sc: Scope, extractor: "ScopeExtractor",
                        out: List[Finding]):
    # accesses in a spawning method BEFORE its first spawn site are
    # sequenced happens-before the thread start (`self._server = ...;
    # Thread(target=self._accept).start()`) and carry no race
    first_spawn: Dict[str, int] = {}
    for sp in sc.spawns:
        if sp.target is not None or sp.handle is not None:
            cur = first_spawn.get(sp.method)
            first_spawn[sp.method] = sp.line if cur is None else \
                min(cur, sp.line)
    for attr, accs in _shared_attrs(sc).items():
        accs = [a for a in accs
                if not (a.method in first_spawn
                        and a.line < first_spawn[a.method])]
        if not any(a.kind == "write" for a in accs):
            continue
        roots: Set[str] = set()
        for a in accs:
            roots |= _roots_of(sc, a.method)
        if len(roots) < 2:
            continue
        common = None
        for a in accs:
            eff = set(a.locks) | set(sc.entry_locks.get(a.method, ()))
            common = eff if common is None else (common & eff)
        if common:
            continue
        if _is_publish_only(sc, attr, extractor):
            continue
        writes = [a for a in accs if a.kind == "write"]
        bare = [a for a in writes if not a.locks] or writes
        first = min(bare, key=lambda a: (a.line, a.col))
        others = sorted(roots - _roots_of(sc, first.method)) or \
            sorted(roots)
        out.append(_mk(
            "unguarded-shared-write", sc.path, first.line,
            f"[{sc.name}] attribute '{attr}' written in "
            f"{first.method}() on root(s) "
            f"{'/'.join(sorted(_roots_of(sc, first.method)))} and "
            f"accessed from root(s) {'/'.join(others)} with no common "
            f"lock", col=first.col))


def global_lock_edges(scopes: Dict[str, Scope],
                      extractors: Dict[str, "ScopeExtractor"]
                      ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """Package-wide acquisition edges "Cls.lock" -> "Cls.lock", including
    cross-class edges through typed attributes: holding A and calling a
    method of an attribute of class C that acquires C.L adds A -> C.L."""
    # which locks does each (scope, method closure) acquire?
    acquires: Dict[str, Dict[str, Set[str]]] = {}
    by_class: Dict[str, Scope] = {}
    for sc in scopes.values():
        by_class[sc.name.rsplit(".", 1)[-1]] = sc
        per: Dict[str, Set[str]] = {}
        ex = extractors[sc.name]
        for mname, fn in sc.methods.items():
            lks: Set[str] = set(sc.entry_locks.get(mname, ()))
            for node in _iter_body(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lk = ex._lock_of(item.context_expr)
                        if lk is not None:
                            lks.add(lk)
            per[mname] = lks
        # close over intra-class calls
        for _ in range(3):
            for mname in per:
                for callee in ex.calls.get(mname, ()):
                    per[mname] |= per.get(callee, set())
        acquires[sc.name] = per
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for sc in scopes.values():
        for e in sc.edges:
            edges.setdefault((e.src, e.dst), (sc.path, e.line, e.method))
        ex = extractors[sc.name]
        for mname, sites in ex.xcalls.items():
            for rattr, callee, held in sites:
                rtype = sc.attr_types.get(rattr)
                tgt_sc = by_class.get(rtype or "")
                if tgt_sc is None or not held:
                    continue
                tgt_ac = acquires.get(tgt_sc.name, {})
                callee_locks: Set[str] = set()
                if callee in tgt_ac:
                    callee_locks = tgt_ac[callee]
                for hl in held:
                    for tl in callee_locks:
                        src = sc.qualified(hl)
                        dst = tgt_sc.qualified(tl)
                        if src != dst:
                            edges.setdefault(
                                (src, dst),
                                (sc.path, sc.methods[mname].lineno
                                 if mname in sc.methods else sc.line,
                                 mname))
    return edges


def _find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str):
        color[n] = 1
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = stack[stack.index(m):] + [m]
                key = tuple(sorted(set(cyc)))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
        stack.pop()
        color[n] = 2

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            dfs(n)
    return cycles


def check_lock_order(scopes: Dict[str, Scope],
                     extractors: Dict[str, "ScopeExtractor"],
                     out: List[Finding]):
    edges = global_lock_edges(scopes, extractors)
    # a self-edge on a plain (non-reentrant) Lock deadlocks immediately
    for (a, b), (path, line, method) in sorted(edges.items()):
        if a == b:
            cls, lk = a.rsplit(".", 1)
            kind = None
            for sc in scopes.values():
                if sc.name.rsplit(".", 1)[-1] == cls:
                    kind = sc.locks.get(lk)
            if kind == "Lock":
                out.append(_mk(
                    "lock-order-cycle", path, line,
                    f"[{cls}] non-reentrant Lock '{lk}' re-acquired while "
                    f"already held in {method}() — immediate deadlock"))
    for cyc in _find_cycles((a, b) for (a, b) in edges if a != b):
        first = cyc[0]
        path, line, method = edges.get(
            (cyc[0], cyc[1]), ("<package>", 1, "?"))
        cls = first.rsplit(".", 1)[0]
        out.append(_mk(
            "lock-order-cycle", path, line,
            f"[{cls}] acquisition-order cycle "
            f"{' -> '.join(cyc)} — threads taking these locks in "
            f"opposite order deadlock"))


def check_blocking(sc: Scope, out: List[Finding]):
    for b in sc.blocking:
        out.append(_mk(
            "blocking-under-lock", sc.path, b.line,
            f"[{sc.name}] {b.why} ({b.call}) in {b.method}() while "
            f"holding '{b.lock}' — stalls every thread contending for "
            f"the lock", col=b.col))


def _spawn_cleanup(sc: Scope, extractor: "ScopeExtractor",
                   sp: Spawn) -> Set[str]:
    """Cleanup paths for a spawn handle: daemon flag (constructor or
    later attribute store), join, cancel, shutdown, context manager, or
    escape (returned / yielded handles are the caller's to manage)."""
    paths = set(sp.cleanup)
    if sp.handle is None:
        return paths
    is_attr = sp.handle.startswith("self.")
    name = sp.handle.split(".", 1)[1] if is_attr else sp.handle
    methods = sc.methods.items() if is_attr else \
        [(sp.method, sc.methods.get(sp.method))]
    for mname, fn in methods:
        if fn is None:
            continue
        for node in _iter_body(fn):
            recv_name = None
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon":
                        base = tgt.value
                        if (is_attr and _self_attr(base) == name) or \
                                (not is_attr and
                                 isinstance(base, ast.Name) and
                                 base.id == name):
                            if isinstance(node.value, ast.Constant) and \
                                    node.value.value is True:
                                paths.add("daemon")
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                base = node.func.value
                if is_attr and _self_attr(base) == name:
                    recv_name = name
                elif not is_attr and isinstance(base, ast.Name) and \
                        base.id == name:
                    recv_name = name
                if recv_name is not None and node.func.attr in (
                        "join", "cancel", "shutdown"):
                    paths.add(node.func.attr)
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if (is_attr and _self_attr(v) == name) or \
                        (not is_attr and isinstance(v, ast.Name)
                         and v.id == name):
                    paths.add("escape")
    return paths


def check_leaked_threads(sc: Scope, extractor: "ScopeExtractor",
                         out: List[Finding]):
    for sp in sc.spawns:
        paths = _spawn_cleanup(sc, extractor, sp)
        if paths:
            sp.cleanup = paths
            continue
        what = sp.target or sp.handle or sp.kind
        out.append(_mk(
            "leaked-thread", sc.path, sp.line,
            f"[{sc.name}] {sp.kind} '{what}' created in {sp.method}() "
            f"with no join/cancel/daemon/shutdown path — it outlives "
            f"close()", col=sp.col))


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------

DEFAULT_MANIFEST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "data", "fedrace",
    "concurrency.json")


def scope_to_manifest(sc: Scope) -> Dict[str, Any]:
    guards: Dict[str, List[str]] = {}
    for attr, accs in _shared_attrs(sc).items():
        common = None
        for a in accs:
            eff = set(a.locks) | set(sc.entry_locks.get(a.method, ()))
            common = eff if common is None else (common & eff)
        if common:
            guards[attr] = sorted(common)
    spawns = []
    for sp in sorted(sc.spawns, key=lambda s: (s.line, s.col)):
        spawns.append({
            "kind": sp.kind,
            "target": sp.target,
            "cleanup": sorted(sp.cleanup)})
    return {
        "locks": dict(sorted(sc.locks.items())),
        "aliases": dict(sorted(sc.lock_aliases.items())),
        "roots": {k: v for k, v in sorted(sc.roots.items())},
        "guards": dict(sorted(guards.items())),
        "order": sorted({(e.src, e.dst) for e in sc.edges}),
        "spawns": spawns,
    }


def scopes_to_manifest(scopes: Dict[str, Scope],
                       extractors: Dict[str, "ScopeExtractor"]
                       ) -> Dict[str, Any]:
    man_scopes = {}
    for name, sc in sorted(scopes.items()):
        entry = scope_to_manifest(sc)
        entry["order"] = [list(e) for e in entry["order"]]
        man_scopes[name] = entry
    edges = global_lock_edges(scopes, extractors)
    return {
        "version": 1,
        "scopes": man_scopes,
        "lock_order": sorted([list(e) for e in edges]),
        "suppressions": [],
    }


def load_manifest(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    path = path or DEFAULT_MANIFEST
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return json.load(fh)


def update_manifest(scopes: Dict[str, Scope],
                    extractors: Dict[str, "ScopeExtractor"],
                    path: Optional[str] = None) -> Dict[str, Any]:
    """Write the extracted surface, PRESERVING the policy half (the
    suppressions list) of any existing manifest — the measured half's git
    diff is the review surface (the fedproto/fedverify pattern)."""
    path = path or DEFAULT_MANIFEST
    old = load_manifest(path)
    fresh = scopes_to_manifest(scopes, extractors)
    if old is not None:
        fresh["suppressions"] = old.get("suppressions", [])
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(fresh, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return fresh


def _diff_paths(a: Any, b: Any, prefix: str = "") -> List[str]:
    if isinstance(a, dict) and isinstance(b, dict):
        out: List[str] = []
        for k in sorted(set(a) | set(b)):
            p = f"{prefix}.{k}" if prefix else str(k)
            if k not in a:
                out.append(f"+{p}")
            elif k not in b:
                out.append(f"-{p}")
            else:
                out.extend(_diff_paths(a[k], b[k], p))
        return out
    if a != b:
        return [f"~{prefix}: {json.dumps(b)} -> {json.dumps(a)}"]
    return []


def check_manifest(scopes: Dict[str, Scope],
                   extractors: Dict[str, "ScopeExtractor"],
                   manifest: Optional[Dict[str, Any]],
                   out: List[Finding]):
    if manifest is None:
        for sc in scopes.values():
            out.append(_mk("manifest-missing", sc.path, sc.line,
                           f"[{sc.name}] no concurrency manifest pinned "
                           "yet — run tools/fedrace.py check "
                           "--update-manifest"))
            return   # one finding is enough signal
        return
    pinned = manifest.get("scopes", {})
    for name, sc in sorted(scopes.items()):
        got = scope_to_manifest(sc)
        got["order"] = [list(e) for e in got["order"]]
        if name not in pinned:
            out.append(_mk("manifest-missing", sc.path, sc.line,
                           f"[{name}] scope has no manifest entry — run "
                           "tools/fedrace.py check --update-manifest"))
            continue
        if got != pinned[name]:
            diffs = _diff_paths(got, pinned[name])
            shown = "; ".join(diffs[:6])
            more = f" (+{len(diffs) - 6} more)" if len(diffs) > 6 else ""
            out.append(_mk(
                "manifest-drift", sc.path, sc.line,
                f"[{name}] concurrency surface drifted from the pinned "
                f"manifest: {shown}{more} — review and refresh with "
                "--update-manifest"))
    for name in sorted(set(pinned) - set(scopes)):
        out.append(_mk(
            "manifest-drift", "<manifest>", 1,
            f"[{name}] pinned scope no longer extracted — review and "
            "refresh with --update-manifest"))


# --------------------------------------------------------------------------
# suppression + driver
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*fedrace:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_,\-]+|all)")


def _line_suppressions(path: str) -> Dict[int, Set[str]]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return {}
    supp: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        which, rules = m.groups()
        names = {r.strip() for r in rules.split(",") if r.strip()}
        target = i + 1 if which == "disable-next-line" else i
        supp.setdefault(target, set()).update(names)
    return supp


_SCOPE_TAG_RE = re.compile(r"^\[([A-Za-z0-9_.<>\-]+)\]")


def apply_suppressions(findings: List[Finding],
                       manifest: Optional[Dict[str, Any]]) -> List[Finding]:
    """Source-comment suppressions by (path, line); manifest-level
    ``{"scope", "rule", "reason"}`` entries match the scope tag every
    fedrace message leads with (scope "*" matches all; a scope value
    ending in '*' is a prefix match, for whole legacy subtrees)."""
    by_path: Dict[str, Dict[int, Set[str]]] = {}
    man_sup = (manifest or {}).get("suppressions", [])
    for f in findings:
        if f.path not in by_path:
            by_path[f.path] = _line_suppressions(f.path)
        marked = by_path[f.path].get(f.line, set())
        if "all" in marked or f.rule in marked:
            f.suppressed = True
            continue
        m = _SCOPE_TAG_RE.match(f.message)
        scope = m.group(1) if m else None
        for sup in man_sup:
            if sup.get("rule") not in (f.rule, "*"):
                continue
            pat = sup.get("scope", "")
            if pat == "*" or pat == scope or (
                    pat.endswith("*") and scope is not None
                    and scope.startswith(pat[:-1])):
                f.suppressed = True
                break
    return findings


def check_concurrency(scopes: Dict[str, Scope],
                      extractors: Dict[str, "ScopeExtractor"],
                      manifest: Optional[Dict[str, Any]] = None,
                      warnings: Optional[List[Finding]] = None,
                      rules: Optional[Set[str]] = None) -> List[Finding]:
    out: List[Finding] = list(warnings or [])
    for sc in scopes.values():
        check_shared_writes(sc, extractors[sc.name], out)
        check_blocking(sc, out)
        check_leaked_threads(sc, extractors[sc.name], out)
    check_lock_order(scopes, extractors, out)
    if rules is None or "manifest-drift" in rules or \
            "manifest-missing" in rules:
        check_manifest(scopes, extractors, manifest, out)
    if rules is not None:
        out = [f for f in out if f.rule in rules]
    seen: Set[Tuple] = set()
    deduped: List[Finding] = []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.rule, f.message)):
        k = (f.path, f.line, f.rule, f.message)
        if k in seen:
            continue
        seen.add(k)
        deduped.append(f)
    return apply_suppressions(deduped, manifest)


def analyze_paths(paths: Iterable[str],
                  manifest: Optional[Dict[str, Any]] = None,
                  rules: Optional[Set[str]] = None) -> List[Finding]:
    scopes, warnings, extractors = extract_concurrency(paths)
    return check_concurrency(scopes, extractors, manifest, warnings, rules)


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Set[str]] = None) -> List[Finding]:
    """Single-source entry point for fixture tests — no manifest rules."""
    import tempfile
    if rules is None:
        rules = set(RACE_RULES) - {"manifest-drift", "manifest-missing"}
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, os.path.basename(path) if path != "<string>"
                         else "fixture.py")
        with open(p, "w", encoding="utf-8") as fh:
            fh.write(source)
        findings = analyze_paths([p], manifest=None, rules=rules)
    for f in findings:
        f.path = path
    return findings
