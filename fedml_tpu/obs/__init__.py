"""fedtrace — the sync-free round-telemetry plane (ISSUE 4).

Three layers, one overhead contract (ZERO extra host syncs, ZERO extra
steady-state compiles on the round hot path — pinned by the
``JaxRuntimeAudit``-based tests in ``tests/test_fedtrace.py``):

1. **Device-carry metrics** (:mod:`.carry`): a fixed-shape
   :class:`ObsCarry` pytree (per-phase FLOP weights, cohort counters,
   update norm) computed INSIDE the compiled round and returned through
   the existing metrics pytree, so it rides the same ``jit``/``lax.scan``
   outputs the loss does and materializes only on the driver's existing
   eval/log-round syncs.
2. **Host spans + counters** (:mod:`.tracer`): a thread-safe
   :class:`Tracer` recording staging spans + queue depth, XLA compile
   events with durations (through the shared :mod:`.jaxhooks` monitoring
   hub the runtime auditor also uses), ``device_put``/``device_get``
   byte counters, and comm-manager RTT spans — exported as Chrome
   trace-event JSON (loadable in Perfetto / ``chrome://tracing``) plus a
   Prometheus-style aggregate text dump.
3. **Analysis** (``tools/fedtrace.py``): ``summarize`` turns a trace
   into a per-phase (staging / gather / client steps / merge / server
   update) time breakdown; ``diff`` compares two traces.

See ``docs/OBSERVABILITY.md`` for the attribution model and the Perfetto
how-to.
"""

from __future__ import annotations

from . import context  # noqa: F401  (fedscope trace-context propagation)
from .tracer import (  # noqa: F401
    DEVICE_PHASES,
    PHASES,
    Tracer,
    configure,
    get_tracer,
    trace_enabled,
)

#: symbols resolved lazily so importing :mod:`fedml_tpu.obs` (e.g. from a
#: comm manager that never touches jax) stays stdlib-light; :mod:`.carry`
#: pulls in jax + flax.
_CARRY_EXPORTS = ("ObsCarry", "OPT_FLOPS", "obs_host", "obs_host_rows",
                  "param_count", "round_obs")

__all__ = ["DEVICE_PHASES", "PHASES", "Tracer", "configure", "context",
           "get_tracer", "trace_enabled", *_CARRY_EXPORTS]


def __getattr__(name):
    if name in _CARRY_EXPORTS:
        from . import carry
        return getattr(carry, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
