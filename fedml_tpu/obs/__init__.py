"""fedtrace — the sync-free round-telemetry plane (ISSUE 4).

Three layers, one overhead contract (ZERO extra host syncs, ZERO extra
steady-state compiles on the round hot path — pinned by the
``JaxRuntimeAudit``-based tests in ``tests/test_fedtrace.py``):

1. **Device-carry metrics** (:mod:`.carry`): a fixed-shape
   :class:`ObsCarry` pytree (per-phase FLOP weights, cohort counters,
   update norm) computed INSIDE the compiled round and returned through
   the existing metrics pytree, so it rides the same ``jit``/``lax.scan``
   outputs the loss does and materializes only on the driver's existing
   eval/log-round syncs.
2. **Host spans + counters** (:mod:`.tracer`): a thread-safe
   :class:`Tracer` recording staging spans + queue depth, XLA compile
   events with durations (through the shared :mod:`.jaxhooks` monitoring
   hub the runtime auditor also uses), ``device_put``/``device_get``
   byte counters, and comm-manager RTT spans — exported as Chrome
   trace-event JSON (loadable in Perfetto / ``chrome://tracing``) plus a
   Prometheus-style aggregate text dump.
3. **Analysis** (``tools/fedtrace.py``): ``summarize`` turns a trace
   into a per-phase (staging / gather / client steps / merge / server
   update) time breakdown; ``diff`` compares two traces.

fedmon (ISSUE 14) extends the plane with federation-health observability:
:mod:`.health` (robust per-client anomaly / drift detection + declarative
SLO rules over the per-client stat rows the engines compute in-trace) and
:mod:`.metricsd` (the threaded ``/metrics`` · ``/healthz`` ·
``/debug/health`` endpoint behind ``args.metrics_port``).

See ``docs/OBSERVABILITY.md`` for the attribution model and the Perfetto
how-to.
"""

from __future__ import annotations

from . import context  # noqa: F401  (fedscope trace-context propagation)
from .health import (  # noqa: F401  (stdlib-only, like the tracer)
    DEFAULT_SLO_RULES,
    HealthConfig,
    HealthMonitor,
    evaluate_slos,
    load_slo_rules,
)
from .tracer import (  # noqa: F401
    DEVICE_PHASES,
    PHASES,
    Tracer,
    configure,
    escape_label_value,
    get_tracer,
    sanitize_metric_name,
    trace_enabled,
)

#: symbols resolved lazily so importing :mod:`fedml_tpu.obs` (e.g. from a
#: comm manager that never touches jax) stays stdlib-light; :mod:`.carry`
#: pulls in jax + flax.
_CARRY_EXPORTS = ("ObsCarry", "OPT_FLOPS", "obs_host", "obs_host_rows",
                  "param_count", "round_obs")
#: :mod:`.metricsd` exports, lazy for the same reason (http.server)
_METRICSD_EXPORTS = ("MetricsServer", "parse_prometheus_text",
                     "prom_value", "start_from_args")
#: fedslo exports (:mod:`.histogram` / :mod:`.slo` / :mod:`.canary`) —
#: stdlib-only, lazy so disabled-telemetry imports stay featherweight
_FEDSLO_EXPORTS = {
    "BoundedLabels": "histogram", "Histogram": "histogram",
    "ServeHistograms": "histogram",
    "buckets_from_samples": "histogram",
    "merge_bucket_entries": "histogram",
    "quantile_from_buckets": "histogram",
    "BURN_WINDOWS": "slo", "ObjectiveWindow": "slo",
    "evaluate_objective_rules": "slo", "windows_for_rules": "slo",
    "CanaryJudge": "canary", "validate_audit_log": "canary",
}

__all__ = ["DEVICE_PHASES", "PHASES", "DEFAULT_SLO_RULES", "HealthConfig",
           "HealthMonitor", "Tracer", "configure", "context",
           "escape_label_value", "evaluate_slos", "get_tracer",
           "load_slo_rules", "sanitize_metric_name", "trace_enabled",
           *_CARRY_EXPORTS, *_METRICSD_EXPORTS, *_FEDSLO_EXPORTS]


def __getattr__(name):
    if name in _CARRY_EXPORTS:
        from . import carry
        return getattr(carry, name)
    if name in _METRICSD_EXPORTS:
        from . import metricsd
        return getattr(metricsd, name)
    if name in _FEDSLO_EXPORTS:
        import importlib
        mod = importlib.import_module(
            f".{_FEDSLO_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
