"""fedmon — host-side federation-health plane (anomaly / drift / SLOs).

The engines compute fixed-shape per-client stat rows IN-TRACE (update L2
norm, cosine-to-cohort-mean, per-client loss delta, async staleness —
``core/federated.py::client_health_stats``) and return them through the
same metrics pytree the loss rides, so the PR 4 zero-overhead contract
holds unchanged: tracing/health on adds ZERO host syncs, explicit
transfers, or steady-state compiles.  The driver materializes the rows at
its EXISTING log-round flush and feeds them here.

This module is the pure host half — stdlib math only (no jax, no numpy
required; any float sequence works), so ``tools/fedtrace.py health`` can
reason about the same quantities offline:

- **Robust per-round z-scores** (median / MAD, with absolute MAD floors
  so a perfectly homogeneous cohort cannot manufacture infinite z) over
  the per-client stat stream.  Directionality encodes the attack
  signatures: a *scaled update* is an update-norm outlier ABOVE the
  cohort median (scored in log space, so "10x" means the same thing at
  every scale); a *label flip* points AWAY from the cohort-mean update
  (cosine far BELOW the median) and carries an elevated local loss.
- **Per-client EWM baselines** keyed by registered client id (a dict
  over OBSERVED ids, so 1M-registered fedstore runs cost memory
  proportional to the touched cohort set, not the id space).
- **Cohort-level drift**: EWM baselines of the round medians; a round
  whose median walks many floors away from its own baseline raises the
  drift score (the whole cohort moved — not an individual outlier).
- **Declarative SLO rules** (YAML or dicts) evaluated over the merged
  gauge set (tracer counters + fedmon gauges) into the ok / degraded /
  unhealthy verdict ``obs/metricsd.py`` serves on ``/healthz``.

Every per-round verdict is emitted as a ``health.verdict`` span plus
``health.*`` counters on the global tracer (host floats only — the
fedlint jit-host-sync rule flags ``health.observe/flag`` sinks fed a
traced value inside jit-reachable code, exactly like the tracer sinks).
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence

from .tracer import get_tracer

#: stat fields every engine's in-trace rows carry (async adds staleness)
HEALTH_STAT_FIELDS = ("update_norm", "cosine", "loss_delta", "weight")


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_z(vals: Sequence[float], floor: float) -> List[float]:
    """Per-element robust z-scores: ``(x - median) / (1.4826 * MAD)``
    with an absolute floor on the MAD scale.  The floor is the knob that
    keeps a *homogeneous* cohort honest — when every client agrees to
    within ``floor``, nobody is an outlier no matter how tight the
    spread."""
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    scale = max(1.4826 * mad, float(floor))
    return [(v - med) / scale for v in vals]


@dataclass
class HealthConfig:
    """Detector knobs (``args.health_*`` override the defaults).

    ``z_flag`` is the per-round robust-z magnitude that counts as full
    anomaly evidence; a client flags when its evidence EWM crosses 1.0
    after ``min_obs`` observations, or immediately at ``hard_z``.  The
    per-stat floors are ABSOLUTE robust-scale floors (log-norm units /
    cosine units / loss units)."""
    z_flag: float = 5.5
    hard_z: float = 20.0
    ewm_alpha: float = 0.6
    min_obs: int = 2
    clear_score: float = 0.25      # evidence EWM below this unflags
    norm_floor: float = 0.25       # log-space: ~= "within 1.28x is normal"
    cosine_floor: float = 0.08
    loss_floor: float = 0.25
    drift_alpha: float = 0.25
    drift_flag: float = 8.0
    drift_warmup: int = 3          # rounds before drift can fire
    recent: int = 256              # flag events kept for /debug/health


@dataclass
class _ClientBaseline:
    """Per-registered-client EWM state (small and dict-packed: the
    1M-registered case stores one of these per OBSERVED client)."""
    evidence: float = 0.0          # EWM of score / z_flag (1.0 == flag)
    score_last: float = 0.0
    obs: int = 0
    rounds: List[int] = field(default_factory=list)


class HealthMonitor:
    """Streaming anomaly + drift detector over per-client stat rows.

    Thread-safe: the driver observes from the train loop while
    ``obs/metricsd.py`` reads gauges from its HTTP threads."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 slo_rules: Optional[List[Dict[str, Any]]] = None):
        self.config = config or HealthConfig()
        self.slo_rules = (DEFAULT_SLO_RULES if slo_rules is None
                          else slo_rules)
        self._lock = threading.Lock()
        self._clients: Dict[int, _ClientBaseline] = {}
        self._flagged: Dict[int, Dict[str, Any]] = {}
        self._flag_events: List[Dict[str, Any]] = []
        self._drift_base: Dict[str, float] = {}
        self._drift_score = 0.0
        self._drift_rounds = 0
        self._gauges: Dict[str, float] = {}
        self.rounds_observed = 0

    @classmethod
    def from_args(cls, args) -> "HealthMonitor":
        cfg = HealthConfig(
            z_flag=float(getattr(args, "health_z", 0.0) or
                         HealthConfig.z_flag),
            ewm_alpha=float(getattr(args, "health_ewm_alpha", 0.0) or
                            HealthConfig.ewm_alpha),
            min_obs=int(getattr(args, "health_min_obs", 0) or
                        HealthConfig.min_obs))
        rules = None
        slo_path = getattr(args, "health_slo_path", None)
        if slo_path:
            rules = load_slo_rules(slo_path)
        return cls(cfg, rules)

    # -- ingest -------------------------------------------------------------
    def observe_round(self, round_idx: int, client_ids: Sequence[int],
                      stats: Dict[str, Sequence[float]],
                      round_time_s: float = 0.0) -> Dict[str, Any]:
        """One round's materialized per-client stat rows.

        ``client_ids`` are the sampled REGISTERED ids (host ints — the
        driver's own sampling, never a device readback); ``stats`` maps
        :data:`HEALTH_STAT_FIELDS` (+ optional ``staleness``) to
        sequences at least ``len(client_ids)`` long (mesh engines pad the
        cohort axis — pad rows carry weight 0 and are dropped here).
        Returns the per-round verdict dict (also traced as the
        ``health.verdict`` span + ``health.*`` counters)."""
        tracer = get_tracer()
        with tracer.span("health.verdict", cat="health", round=round_idx):
            verdict = self._observe(round_idx, client_ids, stats,
                                    round_time_s)
        if tracer.enabled:
            tracer.counter("health.anomaly_rate", verdict["anomaly_rate"])
            tracer.counter("health.flagged_total",
                           verdict["flagged_total"])
            tracer.counter("health.drift_score", verdict["drift_score"])
            tracer.counter("health.round_time_s", round_time_s)
            for fl in verdict["new_flags"]:
                tracer.counter("health.flag", fl["score"], **fl)
        return verdict

    def _observe(self, round_idx, client_ids, stats, round_time_s):
        cfg = self.config
        ids = [int(c) for c in client_ids]
        n = len(ids)

        def col(name, default=0.0):
            seq = stats.get(name)
            if seq is None:
                return [default] * n
            return [float(v) for v in list(seq)[:n]]

        weight = col("weight", 1.0)
        rows = [i for i in range(n) if weight[i] > 0.0]
        norm = col("update_norm")
        cos = col("cosine")
        loss_d = col("loss_delta")
        stale = col("staleness")
        log_norm = [math.log(max(norm[i], 1e-12)) for i in range(n)]

        z_norm = _scatter_z(log_norm, rows, cfg.norm_floor)
        z_cos = _scatter_z(cos, rows, cfg.cosine_floor)
        z_loss = _scatter_z(loss_d, rows, cfg.loss_floor)
        # direction evidence gate: once training converges a BENIGN
        # client's update is near-zero noise and its cosine to the cohort
        # mean is arbitrary — only a client pushing with at least
        # median force can testify about direction (a label-flip keeps
        # pushing hard away; noise does not)
        med_norm = _median([norm[i] for i in rows] or [0.0])
        norm_gate = [min(norm[i] / max(med_norm, 1e-12), 1.0)
                     for i in range(n)]

        new_flags: List[Dict[str, Any]] = []
        flagged_in_cohort = 0
        with self._lock:
            for i in rows:
                cid = ids[i]
                # directional evidence: big norm / opposed direction /
                # elevated loss (label-flip reads as the latter two, a
                # scaled update as the first)
                score, reason = max(
                    (z_norm[i], "scaled_update"),
                    (-z_cos[i] * norm_gate[i], "direction"),
                    (z_loss[i], "loss"))
                score = max(score, 0.0)
                b = self._clients.setdefault(cid, _ClientBaseline())
                a = cfg.ewm_alpha
                b.evidence = ((1.0 - a) * b.evidence
                              + a * min(score / cfg.z_flag, 4.0))
                b.score_last = score
                b.obs += 1
                b.rounds.append(int(round_idx))
                del b.rounds[:-8]
                # bias-corrected EWM (ewm / (1 - (1-a)^n)): without it a
                # client whose every observation sits AT the flag line
                # needs ~1/a observations before the zero-initialized EWM
                # catches up — exactly the slow-flag regime the by-round-10
                # recall bar exists to prevent
                corrected = b.evidence / (1.0 - (1.0 - a) ** b.obs)
                was = cid in self._flagged
                flag_now = (score >= cfg.hard_z
                            or (b.obs >= cfg.min_obs
                                and corrected >= 1.0))
                if flag_now:
                    info = {"client": cid, "round": int(round_idx),
                            "score": round(score, 3), "reason": reason,
                            "staleness": stale[i]}
                    self._flagged[cid] = info
                    if not was:
                        new_flags.append(info)
                        self._flag_events.append(info)
                        del self._flag_events[:-cfg.recent]
                elif was and corrected < cfg.clear_score:
                    del self._flagged[cid]
                if cid in self._flagged:
                    flagged_in_cohort += 1

            drift = self._update_drift(
                {"cosine": _median([cos[i] for i in rows] or [0.0]),
                 "log_norm": _median([log_norm[i] for i in rows] or [0.0]),
                 "loss_delta": _median([loss_d[i] for i in rows] or [0.0])})
            self.rounds_observed += 1
            anomaly_rate = flagged_in_cohort / max(len(rows), 1)
            stale_real = sorted(stale[i] for i in rows)
            verdict = {
                "round": int(round_idx),
                "clients": len(rows),
                "anomaly_rate": round(anomaly_rate, 6),
                "flagged_in_cohort": flagged_in_cohort,
                "flagged_total": len(self._flagged),
                "drift_score": round(drift, 6),
                "drifting": drift >= cfg.drift_flag,
                "new_flags": new_flags,
                "staleness_p99": (stale_real[
                    min(len(stale_real) - 1,
                        int(0.99 * len(stale_real)))]
                    if stale_real else 0.0),
            }
            self._gauges = {
                "health.anomaly_rate": verdict["anomaly_rate"],
                "health.flagged_total": float(len(self._flagged)),
                "health.drift_score": verdict["drift_score"],
                "health.rounds_observed": float(self.rounds_observed),
                "health.round_time_s": float(round_time_s),
                "health.staleness_p99": float(verdict["staleness_p99"]),
            }
        return verdict

    def _update_drift(self, medians: Dict[str, float]) -> float:
        """Cohort drift: every round median keeps an EWM baseline; the
        drift score is the worst |median − baseline| in floor units.
        Warmup rounds only seed the baseline."""
        cfg = self.config
        floors = {"cosine": cfg.cosine_floor, "log_norm": cfg.norm_floor,
                  "loss_delta": cfg.loss_floor}
        score = 0.0
        for k, v in medians.items():
            if k not in self._drift_base:
                self._drift_base[k] = v
                continue
            base = self._drift_base[k]
            if self._drift_rounds >= cfg.drift_warmup:
                score = max(score, abs(v - base) / floors[k])
            self._drift_base[k] = ((1.0 - cfg.drift_alpha) * base
                                   + cfg.drift_alpha * v)
        self._drift_rounds += 1
        self._drift_score = score
        return score

    # -- read side ----------------------------------------------------------
    def flagged(self) -> List[int]:
        with self._lock:
            return sorted(self._flagged)

    def flag_details(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(self._flagged[c]) for c in sorted(self._flagged)]

    def recent_flags(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(f) for f in self._flag_events]

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def verdict(self, extra_metrics: Optional[Dict[str, float]] = None
                ) -> Dict[str, Any]:
        """The /healthz body: SLO evaluation over fedmon gauges merged
        with any caller-provided metric set (tracer counters)."""
        metrics = dict(extra_metrics or {})
        metrics.update(self.gauges())
        return evaluate_slos(self.slo_rules, metrics)


# --------------------------------------------------------------------------
# SLO rules — declarative ok / degraded / unhealthy
# --------------------------------------------------------------------------

#: rules evaluated when no ``health_slo_path`` YAML is given; rules whose
#: metric is absent from the gauge set are skipped (a train-only run is
#: not "degraded" for lacking serving gauges)
DEFAULT_SLO_RULES: List[Dict[str, Any]] = [
    # fedguard reliability plane (docs/FAULT_TOLERANCE.md): a sustained
    # retry storm degrades; any rank missing from the round degrades
    # ("quorum below S"); a round that could not seat its quorum Q is
    # unhealthy; lease-dead ranks degrade until they heal or are
    # replaced
    {"name": "comm_retry_rate", "metric": "comm.retry_rate",
     "max": 0.25, "crit": 0.75},
    {"name": "quorum_full", "metric": "comm.quorum_missing_ranks",
     "max": 0.0},
    {"name": "quorum_met", "metric": "comm.quorum_deficit",
     "crit": 0.0},
    {"name": "dead_ranks", "metric": "comm.dead_ranks", "max": 0.0},
    {"name": "round_time", "metric": "health.round_time_s",
     "max": 60.0, "crit": 600.0},
    {"name": "anomaly_rate", "metric": "health.anomaly_rate",
     "max": 0.3, "crit": 0.6},
    {"name": "drift", "metric": "health.drift_score", "max": 8.0},
    {"name": "staleness_p99", "metric": "async.staleness_p99",
     "max": 10.0},
    {"name": "serve_queue_depth", "metric": "serve.queue_depth",
     "max": 16.0, "crit": 128.0},
    {"name": "serve_p99", "metric": "serve.latency_p99_ms",
     "max": 250.0},
    # fedslo objective rule (docs/OBSERVABILITY.md): "p99 TTFT < 200 ms
    # over 99% of requests", evaluated as multi-window burn-rate alerts
    # when the caller wires an ObjectiveWindow stream (obs/slo.py);
    # skipped, like any absent metric, on processes without one
    {"name": "serve_ttft_p99",
     "objective": {"metric": "serve_ttft_seconds", "threshold": 0.2,
                   "compliance": 0.99}},
    # paged serving memory plane (docs/SERVING.md): a drained page pool
    # means admissions are parking — degraded before it becomes queue
    # growth; an adapter-miss storm (most acquires paging in from the
    # store) means the HBM cache is thrashing — resize
    # adapter_cache_slots or shard the adapter population
    {"name": "kv_page_pool", "metric": "serve.kv_pages_free",
     "min": 1.0},
    {"name": "adapter_miss_storm", "metric": "serve.adapter_miss_rate",
     "max": 0.5},
]


def load_slo_rules(path: str) -> List[Dict[str, Any]]:
    """SLO rules from YAML (``{"slos": [...]}`` or a bare list).  Two
    rule shapes: point rules — ``name``, ``metric`` (a tracer-counter /
    fedmon gauge name), ``max`` and/or ``min`` warn bounds with optional
    ``crit`` / ``crit_min`` critical bounds — and fedslo objective rules
    — ``name`` plus an ``objective`` mapping (``metric``, ``threshold``,
    ``compliance``) evaluated as multi-window burn-rate alerts
    (:mod:`fedml_tpu.obs.slo`)."""
    import yaml
    with open(path) as fh:
        data = yaml.safe_load(fh) or {}
    rules = data.get("slos", data) if isinstance(data, dict) else data
    if not isinstance(rules, list):
        raise ValueError(f"{path}: expected a list or {{'slos': [...]}}")
    for r in rules:
        if "objective" in r:
            from .slo import validate_objective
            validate_objective(r["objective"],
                               where=f"{path}: {r.get('name', r)!r}")
        elif "metric" not in r:
            raise ValueError(f"{path}: SLO rule missing 'metric': {r!r}")
    return rules


def evaluate_slos(rules: Iterable[Dict[str, Any]],
                  metrics: Dict[str, float],
                  objectives: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """ok / degraded / unhealthy over the rule set.

    A point rule breaches *warn* when the metric exceeds ``max`` (or
    falls below ``min``), *crit* at ``crit`` / ``crit_min``.  Any crit
    breach ⇒ unhealthy; any warn breach ⇒ degraded; rules whose metric
    is absent are reported as skipped and do not affect the verdict.

    Objective rules (``rule["objective"]``) evaluate as multi-window
    burn-rate alerts against the matching
    :class:`~fedml_tpu.obs.slo.ObjectiveWindow` in ``objectives``
    (keyed by rule name or objective metric); with no stream wired they
    are skipped, same as an absent point metric."""
    checks: List[Dict[str, Any]] = []
    status = "ok"
    order = ("ok", "degraded", "unhealthy")
    rules = list(rules)
    # evaluate objective rules up front, then emit every row in the
    # caller's DECLARED rule order (checks[i] stays rule i)
    objective_rules = [r for r in rules if r.get("objective")]
    obj_rows: Dict[int, Dict[str, Any]] = {}
    if objective_rules:
        from .slo import evaluate_objective_rules
        obj_rows = {
            id(r): row for r, row in zip(
                objective_rules,
                evaluate_objective_rules(objective_rules,
                                         objectives or {}))}
    for rule in rules:
        if rule.get("objective"):
            row = obj_rows[id(rule)]
            checks.append(row)
            lvl = row.get("status", "skipped")
            if lvl in order and order.index(lvl) > order.index(status):
                status = lvl
            continue
        metric = rule["metric"]
        v = metrics.get(metric)
        row: Dict[str, Any] = {"name": rule.get("name", metric),
                               "metric": metric}
        if v is None:
            row["status"] = "skipped"
            checks.append(row)
            continue
        v = float(v)
        row["value"] = round(v, 6)
        level = "ok"
        if "crit" in rule and v > float(rule["crit"]):
            level = "unhealthy"
        elif "crit_min" in rule and v < float(rule["crit_min"]):
            level = "unhealthy"
        elif "max" in rule and v > float(rule["max"]):
            level = "degraded"
        elif "min" in rule and v < float(rule["min"]):
            level = "degraded"
        row["status"] = level
        for b in ("max", "min", "crit", "crit_min"):
            if b in rule:
                row[b] = float(rule[b])
        checks.append(row)
        order = ("ok", "degraded", "unhealthy")
        if order.index(level) > order.index(status):
            status = level
    return {"status": status, "checks": checks}


def _scatter_z(vals: List[float], rows: List[int], floor: float
               ) -> List[float]:
    """Robust z over the REAL rows only, scattered back to full cohort
    length (pad rows read 0)."""
    out = [0.0] * len(vals)
    if not rows:
        return out
    zs = robust_z([vals[i] for i in rows], floor)
    for i, z in zip(rows, zs):
        out[i] = z
    return out
