"""Measured per-phase device time — the out-of-band ``trace_device``
probe (fedscope, docs/OBSERVABILITY.md).

``fedtrace summarize``'s default device-phase breakdown apportions each
round's wall-clock by the FLOP weights the round carries on device
(:mod:`.carry`) — a *model*, chosen because the compiled round cannot be
host-timed per phase without breaking the zero-sync contract.  This
module adds the measured alternative: split the round into its four
phase sub-programs (gather / client_steps / merge / server_update) built
from the ENGINE'S OWN pieces (the same ``run_clients`` /
``build_aggregates`` / ``update_from_aggregates`` the fused round
composes), jit each, and time them with ``block_until_ready`` on the
real staged cohort.  The probe runs ONCE, out of band — behind
``args.trace_device``, never on the steady-state round path — so the
PR 4 overhead contract (zero extra syncs/compiles on traced rounds)
stands untouched; the audit-equality tests run with the probe off and
the probe's own compiles happen before the audited window.

Results land as ``device.<phase>_s`` counters in the trace;
``fedtrace summarize`` prefers them over the FLOP proxy when all four
are present and reports the measured-vs-modeled share deltas
(``bench.py --trace`` archives those into the BENCH json).

Optionally wraps the timed section in a ``jax.profiler`` capture
(``args.trace_profile_dir``) so an XLA-level timeline lands on disk next
to the fedtrace spans for offline inspection.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tracer import DEVICE_PHASES, get_tracer

log = logging.getLogger(__name__)


def _timed(fn, *args, repeats: int = 3) -> float:
    """min-of-N wall-clock of ``fn(*args)`` with a warmup call (the
    warmup pays the compile; min filters host scheduling noise)."""
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def measure_device_phases(api, round_idx: int = 0, repeats: int = 3,
                          profile_dir: Optional[str] = None
                          ) -> Optional[Dict[str, float]]:
    """Measure real per-phase device durations for one round of an SP
    engine (``FedAvgAPI`` on the device-gather path).

    Returns ``{phase: seconds}`` (and emits the ``device.<phase>_s``
    counters) or None when the engine shape isn't measurable this way
    (mesh backends, populations, quantized collectives — those keep the
    FLOP proxy)."""
    from ..core import federated
    from ..core import rng as rng_util
    from ..simulation.round_engine import make_run_clients

    if not hasattr(api, "_dev_x"):
        log.warning("trace_device: needs the device-gather cohort path "
                    "(device_data=True); keeping the FLOP proxy")
        return None
    if getattr(api, "population", None) or \
            getattr(api, "collective_precision", "fp32") != "fp32":
        log.warning("trace_device: population/quantized rounds keep the "
                    "FLOP proxy")
        return None

    trainer, server_opt = api.trainer, api.server_opt
    spec = server_opt.spec
    run_clients = make_run_clients(trainer, server_opt, api._client_mode)
    red = federated.StackedReducer()

    clients, idx, mask, w, _steps = api._stage_round_arrays(round_idx)
    cohort = np.asarray(clients, np.int32)
    c_stacked = api._gather_c(cohort, round_idx=round_idx)
    key = rng_util.round_key(rng_util.root_key(api.seed), round_idx)
    rngs = jax.random.split(key, len(clients))
    idx, mask, w = jnp.asarray(idx), jnp.asarray(mask), jnp.asarray(w)
    dev_x, dev_y = api._dev_x, api._dev_y

    gather_fn = jax.jit(lambda i: (jnp.take(dev_x, i, axis=0),
                                   jnp.take(dev_y, i, axis=0)))
    client_fn = jax.jit(lambda st, x, y, m, r, c:
                        run_clients(st, x, y, m, r, c))
    merge_fn = jax.jit(lambda st, outs, ww: federated.build_aggregates(
        spec, red, server_opt, st, outs, ww))
    update_fn = jax.jit(
        lambda st, agg: server_opt.update_from_aggregates(st, agg))

    prof = None
    if profile_dir:
        try:
            jax.profiler.start_trace(profile_dir)
            prof = profile_dir
        except Exception:   # profiler availability differs per backend
            log.warning("trace_device: jax.profiler capture unavailable",
                        exc_info=True)

    try:
        seconds: Dict[str, float] = {}
        seconds["gather"] = _timed(gather_fn, idx, repeats=repeats)
        x, y = gather_fn(idx)
        seconds["client_steps"] = _timed(
            client_fn, api.state, x, y, mask, rngs, c_stacked,
            repeats=repeats)
        outs = client_fn(api.state, x, y, mask, rngs, c_stacked)
        seconds["merge"] = _timed(merge_fn, api.state, outs, w,
                                  repeats=repeats)
        agg = merge_fn(api.state, outs, w)
        seconds["server_update"] = _timed(update_fn, api.state, agg,
                                          repeats=repeats)
    finally:
        if prof is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass

    tracer = get_tracer()
    for phase in DEVICE_PHASES:
        tracer.counter(f"device.{phase}_s", seconds[phase],
                       source="measured", round=round_idx)
    log.info("trace_device: measured phases %s",
             {p: round(s, 6) for p, s in seconds.items()})
    return seconds
