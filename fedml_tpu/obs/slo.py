"""fedslo objective rules — multi-window, multi-burn-rate SLO alerts.

The fedmon rule schema (:mod:`.health`) is point-in-time: ``metric >
max`` ⇒ degraded.  That is the wrong shape for latency objectives — "p99
TTFT < 200 ms" violated for one scrape interval is noise, violated
steadily for an hour is an incident — so this module adds
*objective-style* rules evaluated the way SRE burn-rate alerting does:

- An **objective** is ``{metric, threshold, compliance}``: "``metric``
  stays ≤ ``threshold`` for at least ``compliance`` of requests" (p99 <
  200 ms ⇔ compliance 0.99 at threshold 0.2 s).  The error *budget* is
  ``1 - compliance``.
- Each request is **good** (≤ threshold) or **bad**; the **burn rate**
  over a window is ``bad_fraction / budget`` — burn 1.0 spends the
  budget exactly at the compliance horizon, burn 14.4 spends a 30-day
  budget in 2 days.
- An alert fires only when BOTH windows of a pair burn (the long window
  proves it is sustained, the short window proves it is still
  happening, so recovered incidents stop alerting fast):
  **fast** = 5 m + 1 h at burn ≥ 14.4 (⇒ ``unhealthy``),
  **slow** = 30 m + 6 h at burn ≥ 6 (⇒ ``degraded``).

``time_scale`` compresses the wall-clock windows (benches and tests
replay hours of traffic in seconds); the *shape* of the policy is what
is under test, not the literal hour.

Rules load through :func:`~fedml_tpu.obs.health.load_slo_rules` (the
schema gains an ``objective`` key) and evaluate through
:func:`~fedml_tpu.obs.health.evaluate_slos` when the caller provides the
matching :class:`ObjectiveWindow` streams.  Pure stdlib, host floats
only — same contract as the tracer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .histogram import _le_key

#: the multi-window / multi-burn-rate alert policy (SRE workbook ch.5):
#: (name, short window s, long window s, burn threshold, verdict)
BURN_WINDOWS: Tuple[Tuple[str, float, float, float, str], ...] = (
    ("fast", 300.0, 3600.0, 14.4, "unhealthy"),
    ("slow", 1800.0, 21600.0, 6.0, "degraded"),
)


def validate_objective(obj: Dict[str, Any], where: str = "rule") -> None:
    """Schema check for an ``objective`` block: ``metric`` (histogram /
    stream name), ``threshold`` (good ≤ threshold), ``compliance`` in
    (0, 1) (or ``percentile`` — same number, either spelling)."""
    if not isinstance(obj, dict):
        raise ValueError(f"{where}: 'objective' must be a mapping, got "
                         f"{obj!r}")
    if "metric" not in obj:
        raise ValueError(f"{where}: objective missing 'metric': {obj!r}")
    if "threshold" not in obj:
        raise ValueError(f"{where}: objective missing 'threshold': "
                         f"{obj!r}")
    comp = obj.get("compliance", obj.get("percentile"))
    if comp is None:
        raise ValueError(f"{where}: objective needs 'compliance' (or "
                         f"'percentile'): {obj!r}")
    comp = float(comp)
    if not 0.0 < comp < 1.0:
        raise ValueError(f"{where}: compliance must be in (0, 1), got "
                         f"{comp}")


def objective_budget(obj: Dict[str, Any]) -> float:
    comp = float(obj.get("compliance", obj.get("percentile")))
    return 1.0 - comp


class ObjectiveWindow:
    """Good/bad event stream for ONE objective, answering burn-rate
    queries over arbitrary trailing windows.

    Events are ``(t, total, bad)`` batches appended by ``observe`` (one
    request) or ``ingest_counts`` (a scrape delta); windows scan the
    tail — request volumes here are per-engine host streams, thousands
    not billions, so a plain list beats a ring of pre-aggregated
    buckets.  A ``max_events`` cap bounds memory for soak runs."""

    def __init__(self, objective: Dict[str, Any],
                 time_scale: float = 1.0, max_events: int = 200_000,
                 clock=time.monotonic):
        validate_objective(objective)
        self.objective = dict(objective)
        self.metric = str(objective["metric"])
        self.threshold = float(objective["threshold"])
        self.budget = objective_budget(objective)
        self.time_scale = float(time_scale)
        self.max_events = int(max_events)
        self._clock = clock
        self._events: List[Tuple[float, int, int]] = []

    # -- ingest -------------------------------------------------------------
    def observe(self, value: float, t: Optional[float] = None) -> bool:
        """One request's measured value; returns True when good."""
        good = float(value) <= self.threshold
        self._append(t, 1, 0 if good else 1)
        return good

    def ingest_counts(self, total: int, bad: int,
                      t: Optional[float] = None) -> None:
        """A pre-counted batch (scrape-delta path)."""
        if total > 0:
            self._append(t, int(total), int(bad))

    def ingest_bucket_entry(self, entry: Dict[str, Any],
                            t: Optional[float] = None) -> None:
        """Count good/bad straight off a histogram snapshot entry
        (``{"buckets": [(le, cum)], "count": n}``): good = cumulative
        count at the smallest bound ≥ threshold — bucket-resolution
        evaluation, conservative by at most one bucket."""
        good = 0
        for le, cum in sorted(entry["buckets"],
                              key=lambda b: _le_key(b[0])):
            if _le_key(le) >= self.threshold:
                good = cum
                break
        total = int(entry["count"])
        self.ingest_counts(total, total - int(good), t=t)

    def _append(self, t: Optional[float], total: int, bad: int) -> None:
        t = self._clock() if t is None else float(t)
        self._events.append((t, total, bad))
        if len(self._events) > self.max_events:
            # drop the oldest half — windows only read the tail
            del self._events[: self.max_events // 2]

    # -- queries ------------------------------------------------------------
    def counts(self, window_s: float, now: Optional[float] = None
               ) -> Tuple[int, int]:
        now = self._clock() if now is None else float(now)
        lo = now - float(window_s) * self.time_scale
        total = bad = 0
        for t, n, b in reversed(self._events):
            if t < lo:
                break
            total += n
            bad += b
        return total, bad

    def burn_rate(self, window_s: float, now: Optional[float] = None
                  ) -> Optional[float]:
        """``bad_fraction / budget`` over the trailing window; ``None``
        with no traffic in the window (no data is not an alert)."""
        total, bad = self.counts(window_s, now=now)
        if total == 0:
            return None
        return (bad / total) / self.budget if self.budget > 0 \
            else float("inf") if bad else 0.0

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Multi-window verdict for this one objective: worst firing
        pair wins; a pair fires only when BOTH its windows burn."""
        now = self._clock() if now is None else float(now)
        rows: List[Dict[str, Any]] = []
        status = "ok"
        order = ("ok", "degraded", "unhealthy")
        for name, short_s, long_s, thresh, verdict in BURN_WINDOWS:
            b_short = self.burn_rate(short_s, now=now)
            b_long = self.burn_rate(long_s, now=now)
            firing = (b_short is not None and b_long is not None
                      and b_short >= thresh and b_long >= thresh)
            rows.append({"window": name, "short_s": short_s,
                         "long_s": long_s, "burn_threshold": thresh,
                         "burn_short": b_short, "burn_long": b_long,
                         "firing": firing})
            if firing and order.index(verdict) > order.index(status):
                status = verdict
        total, bad = self.counts(BURN_WINDOWS[-1][2], now=now)
        return {"metric": self.metric, "threshold": self.threshold,
                "budget": self.budget, "status": status,
                "windows": rows, "total": total, "bad": bad,
                "bad_fraction": (bad / total) if total else None}


def evaluate_objective_rules(rules: Iterable[Dict[str, Any]],
                             objectives: Dict[str, "ObjectiveWindow"],
                             now: Optional[float] = None
                             ) -> List[Dict[str, Any]]:
    """Burn-rate checks for every objective-style rule that has a live
    window stream; rules without one report ``skipped`` (a train-only
    process is not degraded for lacking serving streams)."""
    checks: List[Dict[str, Any]] = []
    for rule in rules:
        obj = rule.get("objective")
        if obj is None:
            continue
        name = rule.get("name", obj.get("metric", "objective"))
        win = objectives.get(name) or objectives.get(obj.get("metric"))
        row: Dict[str, Any] = {"name": name, "objective": dict(obj)}
        if win is None:
            row["status"] = "skipped"
        else:
            row.update(win.evaluate(now=now))
            row["name"] = name
        checks.append(row)
    return checks


def windows_for_rules(rules: Iterable[Dict[str, Any]],
                      time_scale: float = 1.0,
                      clock=time.monotonic
                      ) -> Dict[str, ObjectiveWindow]:
    """One :class:`ObjectiveWindow` per objective rule, keyed by rule
    name — the streams a serving driver feeds per finished request and
    hands to ``evaluate_slos(..., objectives=...)``."""
    out: Dict[str, ObjectiveWindow] = {}
    for rule in rules:
        obj = rule.get("objective")
        if obj is None:
            continue
        name = rule.get("name", obj.get("metric", "objective"))
        out[name] = ObjectiveWindow(obj, time_scale=time_scale,
                                    clock=clock)
    return out
