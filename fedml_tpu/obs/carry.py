"""Device-carry round telemetry: the fixed-shape :class:`ObsCarry` pytree.

The fused round path (``jit(lax.scan(round))``, docs/ROUND_FUSION.md)
syncs the host once per block, so nothing host-side can observe where
time goes INSIDE a round.  ObsCarry closes that gap without breaking the
sync contract: a handful of f32 scalars (plus one ``(4,)`` vector of
per-phase FLOP weights) computed in-trace from quantities the round
already has, returned through the same metrics pytree the loss rides —
stacked to ``(K,)`` by the block scan exactly like ``train_loss`` — and
materialized only on the driver's existing eval/log-round flush.

Cost on the hot path: a few scalar reductions plus one tree-sized
subtract-square-sum for the update norm (~2 FLOPs/param against the
round's ~6·examples FLOPs/param of client training) and ZERO extra host
syncs / compiles (pinned by ``tests/test_fedtrace.py``).

The phase FLOP weights are attribution weights, not exact counts:
``tools/fedtrace.py summarize`` apportions each round's measured
wall-clock across the device phases proportionally to them (see
docs/OBSERVABILITY.md for the model).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from .tracer import DEVICE_PHASES

#: server-update FLOPs/param attribution class per algorithm (stage-2
#: transition cost: plain wavg ≈ 2, Adam-family moments ≈ 18, control
#: variate / residual updates in between) — weights for time attribution,
#: not exact counts
OPT_FLOPS = {
    "fedavg": 2.0, "fedavg_seq": 2.0, "fedprox": 2.0, "fedsgd": 4.0,
    "fedopt": 18.0, "fedopt_seq": 18.0, "scaffold": 8.0, "feddyn": 10.0,
    "fednova": 6.0, "mime": 10.0,
}


@flax.struct.dataclass
class ObsCarry:
    """Fixed-shape per-round telemetry (all f32; ``phase_flops`` is a
    ``(4,)`` vector aligned with :data:`~fedml_tpu.obs.DEVICE_PHASES`:
    gather / client_steps / merge / server_update)."""

    steps: jnp.ndarray        # real (mask-weighted) local SGD steps, summed
    clients: jnp.ndarray      # sampled clients with weight > 0
    examples: jnp.ndarray     # real examples consumed (steps × batch)
    update_norm: jnp.ndarray  # ‖new_global − old_global‖₂ (f32)
    phase_flops: jnp.ndarray  # (4,) per-phase FLOP attribution weights
    # low-precision collective layer (docs/COLLECTIVE_PRECISION.md):
    # modeled interconnect payload bytes of merge+broadcast this round
    # (trace-time static — fp32 reports its dense payload so ratios work)
    # and the L2 norm of this round's quantization residual (0 at fp32)
    collective_bytes: jnp.ndarray
    quant_error_norm: jnp.ndarray
    # per-mesh-axis split of collective_bytes (docs/MESH_2D.md,
    # docs/PIPELINE.md): merge + broadcast payload crossing the ``client``
    # axis, the pipeline permute + flat-view traffic crossing ``stage``
    # (0 off the 3-D layout), and the model-parallel traffic crossing
    # ``model`` (0 on 1-D layouts).  client + stage + model ==
    # collective_bytes, pinned by tests/test_fedtrace.py
    collective_bytes_client: jnp.ndarray
    collective_bytes_stage: jnp.ndarray
    collective_bytes_model: jnp.ndarray


def param_count(tree: Any) -> int:
    """Static (trace-time) element count of a params pytree."""
    return sum(int(math.prod(l.shape))
               for l in jax.tree_util.tree_leaves(tree))


def round_obs(old_params: Any, new_params: Any, *, real_steps, real_clients,
              batch: int, feat: int, opt_flops_per_param: float,
              collective_bytes: float = 0.0,
              collective_bytes_client: float = None,
              collective_bytes_stage: float = 0.0,
              collective_bytes_model: float = 0.0,
              quant_error=None) -> ObsCarry:
    """Build the ObsCarry INSIDE the compiled round.

    ``real_steps``/``real_clients`` are traced scalars the round already
    computes; ``batch``/``feat`` (examples per step / elements per
    example) and the param count are trace-time statics, so every phase
    weight is a static × traced product — no extra reductions beyond the
    update norm.
    """
    f32 = jnp.float32
    p = float(param_count(old_params))
    steps = jnp.asarray(real_steps, f32)
    clients = jnp.asarray(real_clients, f32)
    examples = steps * float(batch)
    sq = jax.tree_util.tree_map(
        lambda n, o: jnp.sum((n.astype(f32) - o.astype(f32)) ** 2),
        new_params, old_params)
    update_norm = jnp.sqrt(sum(jax.tree_util.tree_leaves(sq)))
    phase_flops = jnp.stack([
        examples * float(max(int(feat), 1)),        # gather: elements moved
        (6.0 * p) * examples,                       # client steps: fwd+bwd
        (2.0 * p) * clients,                        # merge: weighted sums
        jnp.asarray(float(opt_flops_per_param) * p, f32),  # server update
    ])
    if collective_bytes_client is None:
        # single-axis engines (sp, 1-D mesh): all modeled bytes cross the
        # client axis
        collective_bytes_client = collective_bytes
    return ObsCarry(steps=steps, clients=clients, examples=examples,
                    update_norm=update_norm, phase_flops=phase_flops,
                    collective_bytes=jnp.asarray(float(collective_bytes),
                                                 f32),
                    quant_error_norm=(jnp.zeros((), f32) if quant_error
                                      is None
                                      else jnp.asarray(quant_error, f32)),
                    collective_bytes_client=jnp.asarray(
                        float(collective_bytes_client), f32),
                    collective_bytes_stage=jnp.asarray(
                        float(collective_bytes_stage), f32),
                    collective_bytes_model=jnp.asarray(
                        float(collective_bytes_model), f32))


# -- host-side materialization (called ONLY at the driver's existing
#    log-round sync points; the values are already computed on device) ------

def _row(steps, clients, examples, norm, pf, cbytes, qerr, cb_client,
         cb_stage, cb_model) -> Dict[str, float]:
    out = {"steps": float(steps), "clients": float(clients),
           "examples": float(examples), "update_norm": float(norm)}
    for i, phase in enumerate(DEVICE_PHASES):
        out[f"flops_{phase}"] = float(pf[i])
    out["collective_bytes"] = float(cbytes)
    out["quant_error_norm"] = float(qerr)
    out["collective_bytes_client"] = float(cb_client)
    out["collective_bytes_stage"] = float(cb_stage)
    out["collective_bytes_model"] = float(cb_model)
    return out


def obs_host(carry: ObsCarry) -> Dict[str, float]:
    """Materialize a scalar ObsCarry into plain host floats."""
    return _row(np.asarray(carry.steps), np.asarray(carry.clients),
                np.asarray(carry.examples), np.asarray(carry.update_norm),
                np.asarray(carry.phase_flops),
                np.asarray(carry.collective_bytes),
                np.asarray(carry.quant_error_norm),
                np.asarray(carry.collective_bytes_client),
                np.asarray(carry.collective_bytes_stage),
                np.asarray(carry.collective_bytes_model))


def obs_population_rows(carry: ObsCarry, losses) -> List[Dict[str, float]]:
    """Materialize a population-stacked ObsCarry into per-round rows.

    ``carry`` leaves are ``(P,)`` (one round, P members) or ``(P, K)``
    (fused block); ``losses`` matches.  Float fields that are identical
    across members (steps/clients/examples, the static byte models)
    collapse trivially under the member mean; ``update_norm`` /
    ``quant_error_norm`` genuinely differ per member and report the mean.
    Each row additionally carries the member count and the best / worst /
    mean member loss — the population-sweep signal ``fedtrace summarize``
    surfaces (docs/PRIMITIVES.md)."""
    losses = np.asarray(losses)
    fused = losses.ndim == 2   # (P, K) block leaves vs (P,) single round
    if not fused:
        losses = losses[:, None]
    p, k = losses.shape

    def col(a, j):
        a = np.asarray(a)
        if fused:   # (P, K, ...) -> this round's (P, ...) slice
            a = a[:, j]
        return a.mean(axis=0)

    rows = []
    for j in range(k):
        row = _row(col(carry.steps, j), col(carry.clients, j),
                   col(carry.examples, j), col(carry.update_norm, j),
                   col(carry.phase_flops, j), col(carry.collective_bytes, j),
                   col(carry.quant_error_norm, j),
                   col(carry.collective_bytes_client, j),
                   col(carry.collective_bytes_stage, j),
                   col(carry.collective_bytes_model, j))
        row["members"] = float(p)
        row["member_loss_best"] = float(losses[:, j].min())
        row["member_loss_worst"] = float(losses[:, j].max())
        row["member_loss_mean"] = float(losses[:, j].mean())
        # byte models are trace-time statics shared by every member (one
        # compiled program); a nonzero spread means members somehow traced
        # different programs — fedtrace pins this at 0
        cb = np.asarray(carry.collective_bytes)
        cb = cb[:, j] if fused else cb
        row["member_bytes_spread"] = float(cb.max() - cb.min())
        rows.append(row)
    return rows


def obs_host_rows(carry: ObsCarry) -> List[Dict[str, float]]:
    """Materialize a block-stacked ``(K,)`` ObsCarry into K row dicts
    (one host copy per field, then pure indexing)."""
    steps = np.asarray(carry.steps)
    clients = np.asarray(carry.clients)
    examples = np.asarray(carry.examples)
    norm = np.asarray(carry.update_norm)
    pf = np.asarray(carry.phase_flops)
    cb = np.asarray(carry.collective_bytes)
    qe = np.asarray(carry.quant_error_norm)
    cbc = np.asarray(carry.collective_bytes_client)
    cbs = np.asarray(carry.collective_bytes_stage)
    cbm = np.asarray(carry.collective_bytes_model)
    if steps.ndim == 0:
        return [_row(steps, clients, examples, norm, pf, cb, qe, cbc, cbs,
                     cbm)]
    return [_row(steps[j], clients[j], examples[j], norm[j], pf[j],
                 cb[j], qe[j], cbc[j], cbs[j], cbm[j])
            for j in range(steps.shape[0])]
