"""fedslo native histograms — fixed-boundary, log-bucketed, mergeable.

The serving tier's latency telemetry was gauge-shaped (``serve.latency_
p99_ms`` computed by one load harness over one engine): correct for a
single stream, useless for a fleet — percentiles do not average, and the
per-adapter counter *names* (``serve.requests.<adapter>``) grew one
metric series per registered adapter (PR 9's cardinality bug).  This
module fixes both with the Prometheus classic-histogram contract:

- **Fixed log-spaced boundaries.**  Every engine in a fleet shares the
  same compiled-in bucket edges, so two engines' histograms merge by
  plain bucket-wise addition — the only aggregation that keeps fleet
  percentiles correct (``tools/serve_load.py --multi``).
- **``_bucket``/``_sum``/``_count`` exposition.**  Rendered onto the
  existing ``/metrics`` text dump, cumulative ``le`` buckets ending at
  ``+Inf``, parseable by a real Prometheus scraper and round-tripped by
  :func:`~fedml_tpu.obs.metricsd.parse_prometheus_text`.
- **Bounded labels.**  Per-adapter series go through
  :class:`BoundedLabels`: the first K distinct adapters (K ≈ top-K by
  traffic under a Zipf mix, since heavy adapters arrive first and keep
  arriving) get their own label; everything past K collapses into
  ``other``.  Series count is bounded by construction, not by hoping the
  adapter population stays small.
- **Host floats only.**  ``record()`` takes already-materialized host
  values on the engine/HTTP threads; nothing here may ever touch a
  traced value (``fedlint`` jit-host-sync flags histogram sinks fed
  traced arguments, same as tracer/health sinks).

Quantile estimation (:func:`quantile_from_buckets`) is the standard
linear-interpolation-within-bucket estimate; its error is bounded by one
bucket width, which is the acceptance tolerance the fleet-merge bench
pins (``bench.py --serve-slo``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .tracer import escape_label_value, sanitize_metric_name

#: the overflow label every adapter past the cap collapses into
OVERFLOW_LABEL = "other"


def log_boundaries(lo: float, hi: float, per_decade: int = 5
                   ) -> Tuple[float, ...]:
    """Log₁₀-spaced bucket upper bounds from ``lo`` up to (at least)
    ``hi``.  Rounded to 6 significant digits so the rendered ``le``
    strings are byte-identical across hosts — merge keys on them."""
    if not (lo > 0 and hi > lo and per_decade >= 1):
        raise ValueError(f"bad boundary spec lo={lo} hi={hi} "
                         f"per_decade={per_decade}")
    out: List[float] = []
    i = 0
    while True:
        b = float(f"{lo * 10 ** (i / per_decade):.6g}")
        out.append(b)
        if b >= hi:
            return tuple(out)
        i += 1


#: latency-shaped quantities (seconds): 1 ms … 60 s, 5 buckets/decade
LATENCY_BOUNDARIES_S = log_boundaries(0.001, 60.0, per_decade=5)
#: rate-shaped quantities (tokens/s): 1 … 10k, 3 buckets/decade
RATE_BOUNDARIES = log_boundaries(1.0, 10000.0, per_decade=3)


def format_le(bound: float) -> str:
    """Canonical ``le`` label value for a bucket bound (``+Inf`` for the
    overflow bucket)."""
    if bound == float("inf"):
        return "+Inf"
    return f"{bound:.6g}"


class BoundedLabels:
    """First-K label minting with an ``other`` overflow lane.

    Tracks cumulative traffic per *raw* name (host dict — exact, cheap)
    while bounding the *minted* label set: the first ``k`` distinct
    names each get their own series; later names resolve to
    :data:`OVERFLOW_LABEL`.  Under the Zipf-mix traffic serving actually
    sees, arrival order ≈ traffic order, so first-K ≈ top-K by traffic;
    a label once minted never moves (a re-ranking mid-run would break
    the monotone-bucket contract merges rely on).  ``top()`` reports the
    exact traffic ranking for dashboards regardless of minting."""

    def __init__(self, k: int = 8):
        self.k = max(1, int(k))
        self._minted: Dict[str, bool] = {}
        self._counts: Dict[str, int] = {}      # raw name -> requests
        self._label_counts: Dict[str, int] = {}  # label -> requests
        self._lock = threading.Lock()

    def resolve(self, name: str, count: bool = True) -> Tuple[str, int]:
        """Label for ``name`` plus that label's cumulative request
        count; ``count=True`` (the submit path) also charges one
        request to it."""
        name = str(name)
        with self._lock:
            if name in self._minted:
                label = name
            elif len(self._minted) < self.k:
                self._minted[name] = True
                label = name
            else:
                label = OVERFLOW_LABEL
            if count:
                self._counts[name] = self._counts.get(name, 0) + 1
                self._label_counts[label] = \
                    self._label_counts.get(label, 0) + 1
            return label, self._label_counts.get(label, 0)

    def top(self, n: Optional[int] = None) -> List[Tuple[str, int]]:
        """Exact per-raw-name traffic ranking (not capped)."""
        with self._lock:
            rows = sorted(self._counts.items(),
                          key=lambda kv: (-kv[1], kv[0]))
        return rows if n is None else rows[:n]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


class Histogram:
    """One fixed-boundary histogram family with bounded labels.

    Thread-safe; all methods take host floats.  ``record`` /
    ``observe_latency`` are the fedlint-recognized sink names — never
    feed them a traced value from jit-reachable code."""

    def __init__(self, name: str, boundaries: Sequence[float] =
                 LATENCY_BOUNDARIES_S, label_key: str = "adapter",
                 labels: Optional[BoundedLabels] = None,
                 max_labels: int = 8):
        self.name = sanitize_metric_name(name)
        self.boundaries = tuple(float(b) for b in boundaries)
        if list(self.boundaries) != sorted(set(self.boundaries)):
            raise ValueError(f"{name}: boundaries must be strictly "
                             "increasing")
        self.label_key = label_key
        self.labels = labels if labels is not None \
            else BoundedLabels(max_labels)
        # label -> [per-bucket counts (len = len(bounds)+1 incl +Inf),
        #           sum, count]
        self._series: Dict[str, List[Any]] = {}
        self._lock = threading.Lock()

    def _bucket_index(self, value: float) -> int:
        lo, hi = 0, len(self.boundaries)
        while lo < hi:                     # first bound >= value
            mid = (lo + hi) // 2
            if value <= self.boundaries[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo                          # == len(boundaries) -> +Inf

    def record(self, value: float, label: Optional[str] = None) -> str:
        """Observe one host float; returns the (possibly capped) label
        the sample landed under."""
        value = float(value)
        lbl = (self.labels.resolve(label, count=False)[0]
               if label is not None else "base")
        idx = self._bucket_index(value)
        with self._lock:
            row = self._series.get(lbl)
            if row is None:
                row = [[0] * (len(self.boundaries) + 1), 0.0, 0]
                self._series[lbl] = row
            row[0][idx] += 1
            row[1] += value
            row[2] += 1
        return lbl

    #: alias — the latency-flavored sink name fedlint also knows
    observe_latency = record

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """``{label: {"buckets": [(le_str, cumulative)], "sum", "count"}}``
        — the same shape :func:`buckets_from_samples` parses back out of
        an exposition, so in-process and scraped paths share the
        quantile/merge code."""
        with self._lock:
            series = {lbl: ([list(row[0])], row[1], row[2])
                      for lbl, row in self._series.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for lbl, (counts_w, total, n) in series.items():
            counts = counts_w[0]
            cum, cbuckets = 0, []
            for b, c in zip(self.boundaries, counts):
                cum += c
                cbuckets.append((format_le(b), cum))
            cbuckets.append((format_le(float("inf")), cum + counts[-1]))
            out[lbl] = {"buckets": cbuckets, "sum": total, "count": n}
        return out

    def merge_from(self, other: "Histogram") -> None:
        """Bucket-wise addition (fleet aggregation). Boundaries must be
        identical — that is the fixed-boundary contract."""
        if other.boundaries != self.boundaries:
            raise ValueError(f"{self.name}: cannot merge histograms with "
                             "different boundaries")
        with other._lock:
            rows = {lbl: [list(r[0]), r[1], r[2]]
                    for lbl, r in other._series.items()}
        with self._lock:
            for lbl, (counts, total, n) in rows.items():
                row = self._series.get(lbl)
                if row is None:
                    self._series[lbl] = [counts, total, n]
                else:
                    row[0] = [a + b for a, b in zip(row[0], counts)]
                    row[1] += total
                    row[2] += n

    def quantile(self, q: float, label: Optional[str] = None
                 ) -> Optional[float]:
        """Estimated quantile over one label (or all labels merged)."""
        snap = self.snapshot()
        if label is not None:
            entry = snap.get(label)
            return quantile_from_buckets(entry, q) if entry else None
        merged = merge_bucket_entries(list(snap.values()))
        return quantile_from_buckets(merged, q) if merged else None

    def render_prometheus(self) -> str:
        """Classic-histogram text exposition: cumulative ``_bucket``
        series ending at ``+Inf``, plus ``_sum``/``_count`` — every line
        shaped to survive :func:`parse_prometheus_text`."""
        snap = self.snapshot()
        if not snap:
            return ""
        lines = [f"# TYPE {self.name} histogram"]
        key = sanitize_metric_name(self.label_key)
        for lbl in sorted(snap):
            entry = snap[lbl]
            esc = escape_label_value(lbl)
            for le, cum in entry["buckets"]:
                lines.append(f'{self.name}_bucket{{{key}="{esc}",'
                             f'le="{le}"}} {cum}')
            lines.append(f'{self.name}_sum{{{key}="{esc}"}} '
                         f'{entry["sum"]:.9g}')
            lines.append(f'{self.name}_count{{{key}="{esc}"}} '
                         f'{entry["count"]}')
        return "\n".join(lines) + "\n"


# -- bucket-entry algebra (shared by in-process + scraped paths) -----------

def _le_key(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def merge_bucket_entries(entries: Iterable[Optional[Dict[str, Any]]]
                         ) -> Optional[Dict[str, Any]]:
    """Merge ``snapshot()``-shaped entries by bucket addition.  Entries
    must share the same ``le`` grid (fixed boundaries); ``None`` entries
    are skipped."""
    acc: Optional[Dict[str, Any]] = None
    for e in entries:
        if e is None:
            continue
        if acc is None:
            acc = {"buckets": [list(b) for b in e["buckets"]],
                   "sum": float(e["sum"]), "count": int(e["count"])}
            continue
        if [b[0] for b in acc["buckets"]] != [b[0] for b in e["buckets"]]:
            raise ValueError("cannot merge histograms with different "
                             "bucket boundaries")
        for row, (_le, cum) in zip(acc["buckets"], e["buckets"]):
            row[1] += cum
        acc["sum"] += float(e["sum"])
        acc["count"] += int(e["count"])
    if acc is not None:
        acc["buckets"] = [tuple(b) for b in acc["buckets"]]
    return acc


def diff_bucket_entries(after: Dict[str, Any],
                        before: Optional[Dict[str, Any]]
                        ) -> Dict[str, Any]:
    """Windowed delta between two scrapes of the same cumulative
    histogram (the Prometheus ``rate()`` discipline): subtract
    ``before``'s buckets/sum/count from ``after``'s.  ``before=None``
    returns ``after`` unchanged (first scrape); clamps at zero so a
    counter reset degrades to the raw ``after`` values rather than
    going negative."""
    if before is None:
        return after
    if [b[0] for b in after["buckets"]] != [b[0] for b in
                                            before["buckets"]]:
        raise ValueError("cannot diff histograms with different "
                         "bucket boundaries")
    if after["count"] < before["count"]:   # counter reset between scrapes
        return after
    return {"buckets": [(le, max(cum - b_cum, 0)) for (le, cum),
                        (_le, b_cum) in zip(after["buckets"],
                                            before["buckets"])],
            "sum": max(float(after["sum"]) - float(before["sum"]), 0.0),
            "count": int(after["count"]) - int(before["count"])}


def quantile_from_buckets(entry: Dict[str, Any], q: float
                          ) -> Optional[float]:
    """Linear-interpolation quantile estimate from cumulative buckets
    (the Prometheus ``histogram_quantile`` rule): error ≤ one bucket
    width; samples in the ``+Inf`` bucket clamp to the last finite
    bound."""
    buckets = sorted(entry["buckets"], key=lambda b: _le_key(b[0]))
    total = buckets[-1][1] if buckets else 0
    if total <= 0:
        return None
    rank = max(0.0, min(1.0, float(q))) * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        bound = _le_key(le)
        if cum >= rank:
            if bound == float("inf"):
                return prev_le          # clamp: last finite bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (bound - prev_le) * frac
        prev_le, prev_cum = bound, cum
    return prev_le


def bucket_width_at(entry: Dict[str, Any], value: float) -> float:
    """Width of the bucket containing ``value`` — the estimate's error
    bound at that point (the fleet-merge acceptance tolerance)."""
    prev = 0.0
    for le, _cum in sorted(entry["buckets"], key=lambda b: _le_key(b[0])):
        bound = _le_key(le)
        if bound == float("inf"):
            return float("inf")
        if value <= bound:
            return bound - prev
        prev = bound
    return float("inf")


def buckets_from_samples(samples: Iterable[Tuple[str, Dict[str, str],
                                                 float]],
                         name: str, label_key: str = "adapter"
                         ) -> Dict[str, Dict[str, Any]]:
    """Reassemble histogram entries out of
    :func:`~fedml_tpu.obs.metricsd.parse_prometheus_text` output:
    ``{label: {"buckets": [(le, cum)], "sum", "count"}}`` — the inverse
    of :meth:`Histogram.render_prometheus`."""
    name = sanitize_metric_name(name)
    out: Dict[str, Dict[str, Any]] = {}
    for metric, labels, value in samples:
        if not metric.startswith(name + "_"):
            continue
        lbl = labels.get(label_key, "base")
        entry = out.setdefault(lbl, {"buckets": [], "sum": 0.0,
                                     "count": 0})
        if metric == name + "_bucket" and "le" in labels:
            entry["buckets"].append((labels["le"], int(value)))
        elif metric == name + "_sum":
            entry["sum"] = float(value)
        elif metric == name + "_count":
            entry["count"] = int(value)
    for entry in out.values():
        entry["buckets"].sort(key=lambda b: _le_key(b[0]))
    return out


# -- the serving bundle -----------------------------------------------------

#: (attr, metric name, boundaries) for every request-lifecycle quantity
SERVE_HISTOGRAMS = (
    ("ttft", "serve_ttft_seconds", LATENCY_BOUNDARIES_S),
    ("e2e", "serve_e2e_seconds", LATENCY_BOUNDARIES_S),
    ("queue_wait", "serve_queue_wait_seconds", LATENCY_BOUNDARIES_S),
    ("prefill", "serve_prefill_seconds", LATENCY_BOUNDARIES_S),
    ("decode", "serve_decode_seconds", LATENCY_BOUNDARIES_S),
    ("decode_tok_s", "serve_decode_tok_per_s", RATE_BOUNDARIES),
)


class ServeHistograms:
    """The engine's request-lifecycle histogram set, one shared
    :class:`BoundedLabels` across all six families so "top-K adapters"
    means the same adapters everywhere."""

    def __init__(self, max_labels: int = 8):
        self.labels = BoundedLabels(max_labels)
        for attr, metric, bounds in SERVE_HISTOGRAMS:
            setattr(self, attr, Histogram(metric, bounds,
                                          labels=self.labels))

    def record_request(self, label: str, *, queue_s: float,
                       prefill_s: float, e2e_s: float,
                       ttft_s: Optional[float] = None,
                       decode_s: Optional[float] = None,
                       output_tokens: int = 0) -> None:
        """One finished request's host-measured phase breakdown."""
        self.queue_wait.record(queue_s, label)
        self.prefill.record(prefill_s, label)
        self.e2e.record(e2e_s, label)
        if ttft_s is not None:
            self.ttft.record(ttft_s, label)
        if decode_s is not None:
            self.decode.record(decode_s, label)
            if decode_s > 0 and output_tokens > 1:
                # first token belongs to prefill; rate covers the rest
                self.decode_tok_s.record((output_tokens - 1) / decode_s,
                                         label)

    def histograms(self) -> List[Histogram]:
        return [getattr(self, attr) for attr, _m, _b in SERVE_HISTOGRAMS]

    def render_prometheus(self) -> str:
        return "".join(h.render_prometheus() for h in self.histograms())

    def merge_from(self, other: "ServeHistograms") -> None:
        for attr, _m, _b in SERVE_HISTOGRAMS:
            getattr(self, attr).merge_from(getattr(other, attr))
