"""obs.Tracer — host-side spans and counters behind the fedtrace plane.

Design constraints (the whole point of this module):

- **Disabled means free.** Every public method early-returns on one
  attribute check; ``span()`` returns a shared no-op context manager, so
  call sites on the round hot path cost a branch when tracing is off.
- **Enabled means sync-free.** The tracer only ever reads host clocks and
  host ints; it never touches a device value.  Device-side telemetry
  arrives through :mod:`.carry` at the driver's existing log-round sync
  (:meth:`Tracer.round_obs`), never through a tracer-initiated transfer.
- **Chrome trace-event output.** ``export_chrome`` writes the JSON object
  format (``{"traceEvents": [...]}``) with paired ``B``/``E`` duration
  events per thread, ``C`` counter events, and ``M`` metadata — loadable
  in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.  Events sort by
  timestamp at export; still-open spans get a synthesized end so the file
  is always well-formed.
- **Prometheus-style aggregates.** ``export_prometheus`` renders the
  running span totals and counters as a text-format dump for scrape-style
  consumption without parsing the full trace.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: device phases attributed from the ObsCarry FLOP weights, in the order
#: they appear in ``ObsCarry.phase_flops``
DEVICE_PHASES = ("gather", "client_steps", "merge", "server_update")
#: full per-round phase set (staging is host-measured via real spans)
PHASES = ("staging",) + DEVICE_PHASES

#: synthetic thread lane for retroactive XLA-compile spans (a compile's
#: duration arrives after the fact; emitting it on the caller thread would
#: cross-nest with whatever span is open there)
COMPILE_TID = -2


class _NullSpan:
    """Shared no-op context manager returned when tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_cat", "_args")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._tracer.begin(self._name, cat=self._cat, **self._args)
        return self

    def __exit__(self, *exc):
        self._tracer.end(self._name)
        return False


class Tracer:
    """Thread-safe trace-event recorder (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        # tid -> stack of (name, ts_us) for B/E pairing
        self._open: Dict[int, List[tuple]] = {}
        # name -> [count, total_seconds] for the prometheus aggregate
        self._span_agg: Dict[str, List[float]] = {}
        self._counters: Dict[str, float] = {}
        self.enabled = False
        self.path: Optional[str] = None
        self.dropped_ends = 0
        self._origin = time.perf_counter()
        self._pid = os.getpid()

    # -- clock -------------------------------------------------------------
    def _ts(self) -> float:
        """Microseconds since tracer origin (Chrome trace ts unit)."""
        return (time.perf_counter() - self._origin) * 1e6

    def reset(self):
        with self._lock:
            self._events.clear()
            self._open.clear()
            self._span_agg.clear()
            self._counters.clear()
            self.dropped_ends = 0
            self._origin = time.perf_counter()

    # -- spans -------------------------------------------------------------
    def begin(self, name: str, cat: str = "host", **args):
        if not self.enabled:
            return
        ts = self._ts()
        tid = threading.get_ident()
        ev: Dict[str, Any] = {"name": name, "ph": "B", "ts": ts,
                              "pid": self._pid, "tid": tid, "cat": cat}
        clean = {k: v for k, v in args.items() if v is not None}
        if clean:
            ev["args"] = clean
        with self._lock:
            self._events.append(ev)
            self._open.setdefault(tid, []).append((name, ts))

    def end(self, name: str, **args) -> Optional[float]:
        """Close the most recent open span named ``name`` on this thread;
        returns its duration in seconds, or None if no matching begin
        exists (the unmatched end is dropped, keeping exports paired)."""
        if not self.enabled:
            return None
        ts = self._ts()
        tid = threading.get_ident()
        with self._lock:
            stack = self._open.get(tid, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == name:
                    _, t0 = stack.pop(i)
                    break
            else:
                self.dropped_ends += 1
                return None
            ev: Dict[str, Any] = {"name": name, "ph": "E", "ts": ts,
                                  "pid": self._pid, "tid": tid}
            if args:
                ev["args"] = dict(args)
            self._events.append(ev)
            dur = (ts - t0) / 1e6
            agg = self._span_agg.setdefault(name, [0, 0.0])
            agg[0] += 1
            agg[1] += dur
            return dur

    def span(self, name: str, cat: str = "host", **args):
        """Context-manager span; a shared no-op object when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, cat, args)

    def complete(self, name: str, duration_s: float, cat: str = "host",
                 tid: int = COMPILE_TID, **args):
        """Retroactive B/E pair on a synthetic lane — for events whose
        duration is only known after the fact (XLA compiles)."""
        if not self.enabled:
            return
        ts1 = self._ts()
        ts0 = max(ts1 - float(duration_s) * 1e6, 0.0)
        base = {"name": name, "pid": self._pid, "tid": tid, "cat": cat}
        b: Dict[str, Any] = {**base, "ph": "B", "ts": ts0}
        if args:
            b["args"] = dict(args)
        e: Dict[str, Any] = {"name": name, "ph": "E", "ts": ts1,
                             "pid": self._pid, "tid": tid}
        with self._lock:
            self._events.extend((b, e))
            agg = self._span_agg.setdefault(name, [0, 0.0])
            agg[0] += 1
            agg[1] += float(duration_s)

    # -- counters ----------------------------------------------------------
    def counter(self, name: str, value: float, **args):
        """Gauge-style counter sample (Chrome ``C`` event)."""
        if not self.enabled:
            return
        a: Dict[str, Any] = {"value": value}
        a.update(args)
        ev = {"name": name, "ph": "C", "ts": self._ts(), "pid": self._pid,
              "tid": threading.get_ident(), "args": a}
        with self._lock:
            self._events.append(ev)
            try:
                self._counters[name] = float(value)
            except (TypeError, ValueError):
                pass

    def add_bytes(self, name: str, n: int):
        """Cumulative byte counter (device_put/get probes)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "C", "ts": self._ts(), "pid": self._pid,
              "tid": threading.get_ident()}
        with self._lock:
            total = self._counters.get(name, 0.0) + float(n)
            self._counters[name] = total
            ev["args"] = {"value": total}
            self._events.append(ev)

    def round_obs(self, round_idx: int, round_time_s: float,
                  obs: Dict[str, float]):
        """One per-round device-telemetry record.  Called from the driver's
        existing log-round flush with ALREADY-materialized host floats —
        the tracer itself never syncs the device."""
        if not self.enabled:
            return
        args: Dict[str, Any] = {"round": int(round_idx),
                                "round_time_s": float(round_time_s)}
        for k, v in obs.items():
            args[k] = float(v)
        ev = {"name": "obs.round", "ph": "C", "ts": self._ts(),
              "pid": self._pid, "tid": threading.get_ident(), "args": args}
        with self._lock:
            self._events.append(ev)

    # -- export ------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Snapshot: ts-sorted events with synthesized ends for any span
        still open, so every B has a matching E."""
        with self._lock:
            evs = list(self._events)
            open_copy = {tid: list(st) for tid, st in self._open.items()
                         if st}
        ts = self._ts()
        for tid, stack in open_copy.items():
            for name, _t0 in reversed(stack):
                evs.append({"name": name, "ph": "E", "ts": ts,
                            "pid": self._pid, "tid": tid,
                            "args": {"synthesized_end": True}})
        evs.sort(key=lambda e: e.get("ts", 0.0))
        return evs

    def export_chrome(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace-event JSON object; written to ``path`` (or the
        configured default path) when one is given."""
        trace = {
            "traceEvents": [
                {"name": "thread_name", "ph": "M", "ts": 0.0,
                 "pid": self._pid, "tid": COMPILE_TID,
                 "args": {"name": "xla-compile"}},
            ] + self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"exporter": "fedml_tpu.obs",
                          "dropped_ends": self.dropped_ends},
        }
        path = path or self.path
        if path:
            with open(path, "w") as fh:
                json.dump(trace, fh)
        return trace

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "spans": {n: {"count": int(c), "total_s": t}
                          for n, (c, t) in sorted(self._span_agg.items())},
                "counters": dict(self._counters),
                "dropped_ends": self.dropped_ends,
            }

    def export_prometheus(self, path: Optional[str] = None) -> str:
        """Prometheus text-format aggregate of span totals + counters."""
        s = self.summary()
        lines = ["# TYPE fedtrace_span_seconds_total counter",
                 "# TYPE fedtrace_span_count counter",
                 "# TYPE fedtrace_counter gauge"]
        for name, row in s["spans"].items():
            lines.append(f'fedtrace_span_seconds_total{{name="{name}"}} '
                         f'{row["total_s"]:.9f}')
            lines.append(f'fedtrace_span_count{{name="{name}"}} '
                         f'{row["count"]}')
        for name, v in sorted(s["counters"].items()):
            lines.append(f'fedtrace_counter{{name="{name}"}} {v:g}')
        text = "\n".join(lines) + "\n"
        if path:
            with open(path, "w") as fh:
                fh.write(text)
        return text


# -- global tracer ---------------------------------------------------------
_TRACER = Tracer()
_jax_uninstall = None


def get_tracer() -> Tracer:
    return _TRACER


def trace_enabled() -> bool:
    return _TRACER.enabled


def configure(enabled: Optional[bool] = None, path: Optional[str] = None,
              reset: bool = False, jax_hooks: bool = True) -> Tracer:
    """Configure the global tracer.

    Enabling subscribes the tracer to the shared jax monitoring hub
    (XLA compile events) and wraps ``jax.device_put``/``device_get`` with
    byte counters (:mod:`.jaxhooks`); disabling restores both.  The hooks
    never add a transfer, a sync, or a compile — the CI smoke pins
    ``JaxRuntimeAudit`` counter equality between traced and untraced runs.
    """
    global _jax_uninstall
    tr = _TRACER
    if path is not None:
        tr.path = path
    if reset:
        tr.reset()
    if enabled is None:
        return tr
    if enabled and not tr.enabled:
        tr.enabled = True
        if jax_hooks and _jax_uninstall is None:
            from . import jaxhooks
            _jax_uninstall = jaxhooks.install_tracer_hooks(tr)
    elif not enabled and tr.enabled:
        tr.enabled = False
        if _jax_uninstall is not None:
            _jax_uninstall()
            _jax_uninstall = None
    return tr
