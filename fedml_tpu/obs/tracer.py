"""obs.Tracer — host-side spans and counters behind the fedtrace plane.

Design constraints (the whole point of this module):

- **Disabled means free.** Every public method early-returns on one
  attribute check; ``span()`` returns a shared no-op context manager, so
  call sites on the round hot path cost a branch when tracing is off.
- **Enabled means sync-free.** The tracer only ever reads host clocks and
  host ints; it never touches a device value.  Device-side telemetry
  arrives through :mod:`.carry` at the driver's existing log-round sync
  (:meth:`Tracer.round_obs`), never through a tracer-initiated transfer.
- **Chrome trace-event output.** ``export_chrome`` writes the JSON object
  format (``{"traceEvents": [...]}``) with paired ``B``/``E`` duration
  events per thread, ``C`` counter events, and ``M`` metadata — loadable
  in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.  Events sort by
  timestamp at export; still-open spans get a synthesized end so the file
  is always well-formed.
- **Prometheus-style aggregates.** ``export_prometheus`` renders the
  running span totals and counters as a text-format dump for scrape-style
  consumption without parsing the full trace.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from . import context as trace_context

#: device phases attributed from the ObsCarry FLOP weights, in the order
#: they appear in ``ObsCarry.phase_flops``
DEVICE_PHASES = ("gather", "client_steps", "merge", "server_update")
#: full per-round phase set (staging is host-measured via real spans)
PHASES = ("staging",) + DEVICE_PHASES

#: synthetic thread lane for retroactive XLA-compile spans (a compile's
#: duration arrives after the fact; emitting it on the caller thread would
#: cross-nest with whatever span is open there)
COMPILE_TID = -2

_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """A legal Prometheus metric name: every reserved character folds to
    ``_`` and a leading digit gains one (``serve.tokens/s`` →
    ``serve_tokens_s``).  The historical dump interpolated raw names —
    a counter or span named outside ``[a-zA-Z0-9_:]`` emitted a line a
    Prometheus parser rejects."""
    name = _PROM_NAME_BAD.sub("_", str(name))
    if not name or not _PROM_NAME_OK.match(name):
        name = "_" + name
    return name


def escape_label_value(value) -> str:
    """Prometheus label-value escaping: backslash, double-quote and
    newline (the three characters the text format reserves — adapter
    names / span args containing ``"`` previously broke the dump)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _NullSpan:
    """Shared no-op context manager returned when tracing is disabled."""

    __slots__ = ()
    span_id = None       # mirror _SpanCtx so call sites read them freely
    duration_s = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_cat", "_args", "span_id",
                 "duration_s")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self.span_id: Optional[str] = None
        self.duration_s: Optional[float] = None

    def __enter__(self):
        self.span_id = self._tracer.begin(self._name, cat=self._cat,
                                          **self._args)
        return self

    def __exit__(self, *exc):
        self.duration_s = self._tracer.end(self._name)
        return False


class Tracer:
    """Thread-safe trace-event recorder (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        # tid -> stack of (name, ts_us, span_id) for B/E pairing and the
        # thread's current-span parentage (fedscope ids)
        self._open: Dict[int, List[tuple]] = {}
        # name -> [count, total_seconds] for the prometheus aggregate
        self._span_agg: Dict[str, List[float]] = {}
        self._counters: Dict[str, float] = {}
        self.enabled = False
        self.path: Optional[str] = None
        self.dropped_ends = 0
        self._origin = time.perf_counter()
        # wall-clock anchor captured at the SAME instant as the perf
        # origin: ``fedtrace merge`` maps every process's relative ts onto
        # unix time through it before the handshake refinement
        self._origin_unix_us = time.time() * 1e6
        self._pid = os.getpid()
        self.host = socket.gethostname()
        #: human label for the merged timeline ("server" / "silo2" ...)
        self.label: Optional[str] = None
        #: W3C 128-bit trace id — one per process session; adopted ids
        #: would arrive through configure(trace_id=...)
        self.trace_id = trace_context.new_trace_id()
        self._dirty = False

    # -- identity ----------------------------------------------------------
    @property
    def pid(self) -> int:
        return self._pid

    def current_span_id(self) -> Optional[str]:
        """Span id of the innermost open span on the calling thread (the
        parent every injected outbound context names)."""
        with self._lock:
            stack = self._open.get(threading.get_ident())
            return stack[-1][2] if stack else None

    def current_traceparent(self) -> str:
        return trace_context.format_traceparent(
            self.trace_id, self.current_span_id() or "0" * 16)

    # -- clock -------------------------------------------------------------
    def _ts(self) -> float:
        """Microseconds since tracer origin (Chrome trace ts unit)."""
        return (time.perf_counter() - self._origin) * 1e6

    def reset(self):
        with self._lock:
            self._events.clear()
            self._open.clear()
            self._span_agg.clear()
            self._counters.clear()
            self.dropped_ends = 0
            self._origin = time.perf_counter()
            self._origin_unix_us = time.time() * 1e6
            self.trace_id = trace_context.new_trace_id()
            self._dirty = False

    # -- spans -------------------------------------------------------------
    def begin(self, name: str, cat: str = "host", **args) -> Optional[str]:
        """Open a span; returns its fedscope span id.  The B event is
        tagged with pid/host plus ``span_id`` / ``parent`` args so a
        merged multi-process timeline keeps full parentage."""
        if not self.enabled:
            return None
        ts = self._ts()
        tid = threading.get_ident()
        span_id = trace_context.new_span_id()
        ev: Dict[str, Any] = {"name": name, "ph": "B", "ts": ts,
                              "pid": self._pid, "tid": tid, "cat": cat,
                              "host": self.host}
        clean = {k: v for k, v in args.items() if v is not None}
        clean["span_id"] = span_id
        with self._lock:
            stack = self._open.setdefault(tid, [])
            if stack:
                clean.setdefault("parent", stack[-1][2])
            ev["args"] = clean
            self._events.append(ev)
            self._dirty = True
            stack.append((name, ts, span_id))
        return span_id

    def end(self, name: str, **args) -> Optional[float]:
        """Close the most recent open span named ``name`` on this thread;
        returns its duration in seconds, or None if no matching begin
        exists (the unmatched end is dropped, keeping exports paired)."""
        if not self.enabled:
            return None
        ts = self._ts()
        tid = threading.get_ident()
        with self._lock:
            stack = self._open.get(tid, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == name:
                    _, t0, _sid = stack.pop(i)
                    break
            else:
                self.dropped_ends += 1
                return None
            ev: Dict[str, Any] = {"name": name, "ph": "E", "ts": ts,
                                  "pid": self._pid, "tid": tid,
                                  "host": self.host}
            if args:
                ev["args"] = dict(args)
            self._events.append(ev)
            self._dirty = True
            dur = (ts - t0) / 1e6
            agg = self._span_agg.setdefault(name, [0, 0.0])
            agg[0] += 1
            agg[1] += dur
            return dur

    def span(self, name: str, cat: str = "host", **args):
        """Context-manager span; a shared no-op object when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, cat, args)

    def complete(self, name: str, duration_s: float, cat: str = "host",
                 tid: int = COMPILE_TID, end_s_ago: float = 0.0, **args):
        """Retroactive B/E pair on a synthetic lane — for events whose
        duration is only known after the fact (XLA compiles; the fedslo
        request span tree emitted at request finish).  ``end_s_ago``
        shifts the pair back so finish-time emission can place child
        phases (queue/prefill/decode) at their true host-clock offsets;
        ``None``-valued args are dropped, mirroring ``begin``."""
        if not self.enabled:
            return
        ts1 = max(self._ts() - float(end_s_ago) * 1e6, 0.0)
        ts0 = max(ts1 - float(duration_s) * 1e6, 0.0)
        base = {"name": name, "pid": self._pid, "tid": tid, "cat": cat,
                "host": self.host}
        b: Dict[str, Any] = {**base, "ph": "B", "ts": ts0}
        b["args"] = dict(
            {k: v for k, v in args.items() if v is not None},
            span_id=trace_context.new_span_id())
        e: Dict[str, Any] = {"name": name, "ph": "E", "ts": ts1,
                             "pid": self._pid, "tid": tid,
                             "host": self.host}
        with self._lock:
            self._events.extend((b, e))
            self._dirty = True
            agg = self._span_agg.setdefault(name, [0, 0.0])
            agg[0] += 1
            agg[1] += float(duration_s)

    # -- counters ----------------------------------------------------------
    def counter(self, name: str, value: float, **args):
        """Gauge-style counter sample (Chrome ``C`` event)."""
        if not self.enabled:
            return
        a: Dict[str, Any] = {"value": value}
        a.update(args)
        ev = {"name": name, "ph": "C", "ts": self._ts(), "pid": self._pid,
              "tid": threading.get_ident(), "host": self.host, "args": a}
        with self._lock:
            self._events.append(ev)
            self._dirty = True
            try:
                self._counters[name] = float(value)
            except (TypeError, ValueError):
                pass

    def add_bytes(self, name: str, n: int):
        """Cumulative byte counter (device_put/get probes)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "C", "ts": self._ts(), "pid": self._pid,
              "tid": threading.get_ident(), "host": self.host}
        with self._lock:
            total = self._counters.get(name, 0.0) + float(n)
            self._counters[name] = total
            ev["args"] = {"value": total}
            self._events.append(ev)
            self._dirty = True

    def round_obs(self, round_idx: int, round_time_s: float,
                  obs: Dict[str, float]):
        """One per-round device-telemetry record.  Called from the driver's
        existing log-round flush with ALREADY-materialized host floats —
        the tracer itself never syncs the device."""
        if not self.enabled:
            return
        args: Dict[str, Any] = {"round": int(round_idx),
                                "round_time_s": float(round_time_s)}
        for k, v in obs.items():
            args[k] = float(v)
        ev = {"name": "obs.round", "ph": "C", "ts": self._ts(),
              "pid": self._pid, "tid": threading.get_ident(),
              "host": self.host, "args": args}
        with self._lock:
            self._events.append(ev)
            self._dirty = True

    # -- export ------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Snapshot: ts-sorted events with synthesized ends for any span
        still open, so every B has a matching E."""
        with self._lock:
            evs = list(self._events)
            open_copy = {tid: list(st) for tid, st in self._open.items()
                         if st}
        ts = self._ts()
        for tid, stack in open_copy.items():
            for name, _t0, _sid in reversed(stack):
                evs.append({"name": name, "ph": "E", "ts": ts,
                            "pid": self._pid, "tid": tid,
                            "host": self.host,
                            "args": {"synthesized_end": True}})
        evs.sort(key=lambda e: e.get("ts", 0.0))
        return evs

    def process_label(self) -> str:
        return self.label or f"{self.host}:{self._pid}"

    def export_chrome(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace-event JSON object; written to ``path`` (or the
        configured default path) when one is given.  ``otherData``
        carries the process identity + the unix clock anchor ``fedtrace
        merge`` aligns multi-process captures on."""
        # identity/clock anchor snapshot under the tracer lock: a round
        # flush racing reset() (or an end() bumping dropped_ends) must not
        # tear the (trace_id, origin) pair the multi-process merge aligns
        # on.  Taken BEFORE events(), which acquires the lock itself.
        with self._lock:
            other = {"exporter": "fedml_tpu.obs",
                     "dropped_ends": self.dropped_ends,
                     "host": self.host, "pid": self._pid,
                     "label": self.process_label(),
                     "trace_id": self.trace_id,
                     "origin_unix_us": self._origin_unix_us}
        trace = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "ts": 0.0,
                 "pid": self._pid, "tid": 0,
                 "args": {"name": self.process_label()}},
                {"name": "thread_name", "ph": "M", "ts": 0.0,
                 "pid": self._pid, "tid": COMPILE_TID,
                 "args": {"name": "xla-compile"}},
            ] + self.events(),
            "displayTimeUnit": "ms",
            "otherData": other,
        }
        path = path or self.path
        if path:
            with open(path, "w") as fh:
                json.dump(trace, fh)
            with self._lock:
                self._dirty = False
        return trace

    def close(self):
        """Flush the trace to ``path`` if anything new was recorded.
        Idempotent — safe from ``atexit``, a crash handler, AND a normal
        driver exit in any order; a silo process that dies mid-round
        still leaves a mergeable partial trace (open spans get
        synthesized ends)."""
        if not self.path:
            return
        with self._lock:
            if not self._dirty:
                return
        try:
            self.export_chrome(self.path)
        except OSError:  # interpreter teardown may have lost the dir
            pass

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "spans": {n: {"count": int(c), "total_s": t}
                          for n, (c, t) in sorted(self._span_agg.items())},
                "counters": dict(self._counters),
                "dropped_ends": self.dropped_ends,
            }

    def export_prometheus(self, path: Optional[str] = None) -> str:
        """Prometheus text-format aggregate of span totals + counters.

        Span / counter names ride as label VALUES (escaped — names like
        ``serve.requests.cohort-"1"`` are data here, not metric names),
        and the metric names themselves pass ``sanitize_metric_name`` so
        every emitted line survives a real Prometheus parser
        (round-tripped in tests via
        :func:`~fedml_tpu.obs.metricsd.parse_prometheus_text`)."""
        s = self.summary()
        m_total = sanitize_metric_name("fedtrace_span_seconds_total")
        m_count = sanitize_metric_name("fedtrace_span_count")
        m_gauge = sanitize_metric_name("fedtrace_counter")
        lines = [f"# TYPE {m_total} counter",
                 f"# TYPE {m_count} counter",
                 f"# TYPE {m_gauge} gauge"]
        for name, row in s["spans"].items():
            lbl = escape_label_value(name)
            lines.append(f'{m_total}{{name="{lbl}"}} '
                         f'{row["total_s"]:.9f}')
            lines.append(f'{m_count}{{name="{lbl}"}} {row["count"]}')
        for name, v in sorted(s["counters"].items()):
            lines.append(f'{m_gauge}{{name="{escape_label_value(name)}"}} '
                         f'{v:g}')
        text = "\n".join(lines) + "\n"
        if path:
            with open(path, "w") as fh:
                fh.write(text)
        return text


# -- global tracer ---------------------------------------------------------
_TRACER = Tracer()
_jax_uninstall = None
_atexit_registered = False


def get_tracer() -> Tracer:
    return _TRACER


def trace_enabled() -> bool:
    return _TRACER.enabled


def configure(enabled: Optional[bool] = None, path: Optional[str] = None,
              reset: bool = False, jax_hooks: bool = True,
              label: Optional[str] = None) -> Tracer:
    """Configure the global tracer.

    Enabling subscribes the tracer to the shared jax monitoring hub
    (XLA compile events) and wraps ``jax.device_put``/``device_get`` with
    byte counters (:mod:`.jaxhooks`); disabling restores both.  The hooks
    never add a transfer, a sync, or a compile — the CI smoke pins
    ``JaxRuntimeAudit`` counter equality between traced and untraced runs.

    ``label`` names this process's lane on a merged multi-process
    timeline ("server", "silo2", ...).  Enabling with a ``path`` also
    registers an (idempotent) atexit flush, so a process that exits —
    cleanly or via an uncaught exception — still leaves a mergeable
    trace file behind.
    """
    global _jax_uninstall, _atexit_registered
    tr = _TRACER
    if path is not None:
        tr.path = path
    if label is not None:
        tr.label = label
    if reset:
        tr.reset()
    if enabled is None:
        return tr
    if enabled and not tr.enabled:
        tr.enabled = True
        if not _atexit_registered:
            atexit.register(tr.close)
            _atexit_registered = True
        if jax_hooks and _jax_uninstall is None:
            from . import jaxhooks
            _jax_uninstall = jaxhooks.install_tracer_hooks(tr)
    elif not enabled and tr.enabled:
        tr.enabled = False
        if _jax_uninstall is not None:
            _jax_uninstall()
            _jax_uninstall = None
    return tr
