"""fedmon live export — a threaded /metrics · /healthz · /debug/health
endpoint over the global tracer + :class:`~fedml_tpu.obs.health.HealthMonitor`.

Design constraints (mirror the tracer's):

- **Read-only and off the hot path.**  The HTTP threads only snapshot
  host-side aggregates (tracer counters / span totals, fedmon gauges);
  they never touch a device value, never block the train loop beyond the
  tracer's existing lock.
- **Prometheus text format, for real parsers.**  The tracer's historical
  dump emitted unescaped label values (adapter names and span args with
  ``"`` broke scrapes); export here goes through
  :func:`sanitize_metric_name` / :func:`escape_label_value`, and
  :func:`parse_prometheus_text` is the round-trip witness the unit tests
  and ``tools/serve_load.py --scrape-metrics`` both use.
- **Port discipline.**  ``port=0`` binds an ephemeral port (tests,
  bench); multi-process drivers pass ``port + rank`` so silo/worker
  ranks on one host never collide.  Loopback by default — the endpoint
  is unauthenticated.

``/healthz`` returns the declarative-SLO verdict (``ok | degraded |
unhealthy`` — HTTP 200 for ok/degraded, 503 for unhealthy) evaluated
over tracer counters merged with fedmon gauges; ``/debug/health``
returns the recent flag events as JSON.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from .health import DEFAULT_SLO_RULES, HealthMonitor, evaluate_slos
from .tracer import (Tracer, escape_label_value, get_tracer,
                     sanitize_metric_name)

log = logging.getLogger(__name__)


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt,
                                                             "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prometheus_text(text: str
                          ) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse Prometheus text format into ``(metric, labels, value)``
    samples.  Strict about the sample shape (that is the point — the
    round-trip test feeds the tracer's own dump back through here), and
    raises ``ValueError`` on a malformed non-comment line."""
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: not a prometheus sample: "
                             f"{line!r}")
        name, labelstr, value = m.groups()
        labels: Dict[str, str] = {}
        if labelstr:
            consumed = 0
            for lm in _LABEL_RE.finditer(labelstr):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed = lm.end()
            rest = labelstr[consumed:].strip().strip(",")
            if rest:
                raise ValueError(f"line {lineno}: bad label block "
                                 f"{labelstr!r}")
        samples.append((name, labels, float(value)))
    return samples


def prom_value(samples, metric: str, **labels) -> Optional[float]:
    """First sample matching ``metric`` whose labels include ``labels``."""
    for name, lbl, value in samples:
        if name == metric and all(lbl.get(k) == v
                                  for k, v in labels.items()):
            return value
    return None


def render_gauges(gauges: Dict[str, float],
                  metric: str = "fedmon_gauge") -> str:
    """Extra gauges (fedmon health plane) appended to the tracer dump —
    same escaped ``{name="..."}`` label convention."""
    lines = [f"# TYPE {sanitize_metric_name(metric)} gauge"]
    for name, v in sorted(gauges.items()):
        lines.append(f'{sanitize_metric_name(metric)}'
                     f'{{name="{escape_label_value(name)}"}} {v:g}')
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Threaded HTTP endpoint serving the fedmon surface.

    ``monitor`` is optional (a serving engine exports tracer counters
    only); ``slo_rules`` defaults to the monitor's rules, else
    :data:`~fedml_tpu.obs.health.DEFAULT_SLO_RULES`."""

    def __init__(self, tracer: Optional[Tracer] = None,
                 monitor: Optional[HealthMonitor] = None,
                 slo_rules: Optional[List[Dict[str, Any]]] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 extra_text=None, objectives=None):
        self.tracer = tracer or get_tracer()
        self.monitor = monitor
        self.slo_rules = slo_rules
        self.host = host
        self.port = int(port)
        # fedslo extensions: ``extra_text`` — zero-arg callables whose
        # text appends to /metrics (the serving engine passes its
        # ServeHistograms exposition); ``objectives`` — rule-name →
        # ObjectiveWindow streams so /healthz evaluates burn-rate rules,
        # not just point checks
        self.extra_text = list(extra_text or [])
        self.objectives = objectives
        self._server: Optional[ThreadingHTTPServer] = None

    # -- payloads (also unit-testable without a socket) ---------------------
    def metrics_text(self) -> str:
        text = self.tracer.export_prometheus()
        if self.monitor is not None:
            text += render_gauges(self.monitor.gauges())
        for provider in self.extra_text:
            text += provider()
        return text

    def healthz(self) -> Dict[str, Any]:
        counters = self.tracer.summary()["counters"]
        if self.monitor is not None:
            rules = self.slo_rules or self.monitor.slo_rules
            metrics = dict(counters)
            metrics.update(self.monitor.gauges())
        else:
            rules = self.slo_rules or DEFAULT_SLO_RULES
            metrics = counters
        return evaluate_slos(rules, metrics, objectives=self.objectives)

    def debug_health(self) -> Dict[str, Any]:
        if self.monitor is None:
            return {"flagged": [], "recent_flags": [], "gauges": {}}
        return {"flagged": self.monitor.flag_details(),
                "recent_flags": self.monitor.recent_flags(),
                "gauges": self.monitor.gauges()}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # no stderr chatter per scrape
                pass

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = outer.metrics_text().encode()
                        ctype, code = ("text/plain; version=0.0.4", 200)
                    elif path == "/healthz":
                        v = outer.healthz()
                        body = json.dumps(v).encode()
                        ctype = "application/json"
                        code = 503 if v["status"] == "unhealthy" else 200
                    elif path == "/debug/health":
                        body = json.dumps(outer.debug_health()).encode()
                        ctype, code = ("application/json", 200)
                    else:
                        body, ctype, code = (b"not found", "text/plain",
                                             404)
                except Exception as e:   # a broken scrape must not 500-loop
                    body = json.dumps({"error": repr(e)}).encode()
                    ctype, code = ("application/json", 500)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        log.info("fedmon metrics endpoint on %s:%d (/metrics /healthz "
                 "/debug/health)", self.host, self.port)
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def start_from_args(args, monitor: Optional[HealthMonitor] = None,
                    rank: Optional[int] = None) -> Optional[MetricsServer]:
    """The drivers' one-liner: start an endpoint when ``args.metrics_port``
    is set (``0`` = ephemeral; nonzero ports offset by ``rank`` so the
    multi-process silo/async drivers' ranks never collide on one host).
    A bind failure degrades to a warning — monitoring must never kill
    training."""
    port = getattr(args, "metrics_port", None)
    if port is None or port is False:
        return None
    port = int(port)
    if port > 0:
        port += int(rank if rank is not None
                    else getattr(args, "rank", 0) or 0)
    rules = None
    slo_path = getattr(args, "health_slo_path", None)
    if slo_path and monitor is None:
        from .health import load_slo_rules
        rules = load_slo_rules(slo_path)
    server = MetricsServer(get_tracer(), monitor=monitor,
                           slo_rules=rules, port=port)
    try:
        server.start()
    except OSError as e:
        log.warning("fedmon: could not bind metrics endpoint on port %d "
                    "(%s); continuing without live export", port, e)
        return None
    return server
