"""fedscope trace-context propagation — W3C-style ids across processes.

One federation run spans many OS processes (server, silo workers, edge
clients) exchanging :class:`~fedml_tpu.core.distributed.communication.
message.Message` objects.  Without shared ids, each process's fedtrace
capture is an island: a ``comm.send`` span on the sender has no
relationship to the handler span on the receiver, so ``tools/fedtrace.py
merge`` could align clocks but never *link* work.  This module closes
that gap with the W3C Trace Context wire format
(https://www.w3.org/TR/trace-context/: ``traceparent =
"00-<32 hex trace id>-<16 hex span id>-<2 hex flags>"``) carried inside
message params under ``fedscope.*`` keys:

- :func:`inject` stamps an outbound carrier dict with the current
  traceparent (trace id + the *sending span's* id), plus the sender's
  host/pid so the receiver can tag its handler span with the true remote
  identity even before a merge.
- :func:`extract` reads those keys back on the receiver; the comm
  manager opens its ``comm.recv`` span with ``parent_span=<sender span
  id>`` — the cross-process edge ``fedtrace critical-path`` walks.

Pure stdlib; safe to import from comm managers that never touch jax.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Optional

#: message-params keys the context rides in (flat strings so every
#: backend — msgpack, JSON-over-MQTT, filestore blobs — carries them
#: unchanged)
KEY_TRACEPARENT = "fedscope.traceparent"
KEY_HOST = "fedscope.host"
KEY_PID = "fedscope.pid"
#: one id per LOGICAL message, stamped by FedMLCommManager.send_message
#: ABOVE the backend (and above fault injection), so every duplicated
#: delivery of one send carries the same id — ``fedproto check-trace``
#: matches sends to recvs through it and flags re-deliveries
KEY_MSG_ID = "fedscope.msg_id"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random span id, 16 lowercase hex chars."""
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: str) -> Optional[Dict[str, str]]:
    """``traceparent`` string → ``{"trace_id", "span_id"}`` or None."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value)
    if not m:
        return None
    return {"trace_id": m.group(1), "span_id": m.group(2)}


def inject(carrier: Dict[str, Any], tracer=None) -> Dict[str, Any]:
    """Stamp ``carrier`` (message params dict) with the current trace
    context.  No-op when tracing is disabled — untraced runs put zero
    extra bytes on the wire."""
    if tracer is None:
        from .tracer import get_tracer
        tracer = get_tracer()
    if not tracer.enabled:
        return carrier
    span_id = tracer.current_span_id() or "0" * 16
    carrier[KEY_TRACEPARENT] = format_traceparent(tracer.trace_id, span_id)
    carrier[KEY_HOST] = tracer.host
    carrier[KEY_PID] = tracer.pid
    return carrier


def extract(carrier: Any) -> Optional[Dict[str, Any]]:
    """Read an injected context back out of message params.

    ``carrier`` may be a plain mapping or anything with ``.get`` (the
    ``Message`` object).  Returns ``{"trace_id", "span_id", "host",
    "pid"}`` or None when no (valid) context rides the message."""
    get = carrier.get if hasattr(carrier, "get") else None
    if get is None:
        return None
    parsed = parse_traceparent(get(KEY_TRACEPARENT))
    if parsed is None:
        return None
    out: Dict[str, Any] = dict(parsed)
    out["host"] = get(KEY_HOST)
    pid = get(KEY_PID)
    out["pid"] = int(pid) if pid is not None else None
    return out


# -- topology tier classification ------------------------------------------

#: rank 0 is the server in every FedML topology (cross_silo FSMs, the
#: hierarchy driver); traffic touching it crosses the silo→server DCN
#: tier, everything else stays inside a silo
TIER_SILO_SERVER = "silo_server"
TIER_INTRA_SILO = "intra_silo"


def comm_tier(sender: Any, receiver: Any, server_rank: int = 0) -> str:
    """Classify one message edge for the per-tier byte/latency counters
    (``comm.bytes.<tier>`` / ``comm.rtt.<tier>``) — the measured twin of
    fedverify's modeled byte census, split the way arXiv:2604.10859
    splits cross-silo cost: silo→server DCN vs intra-silo traffic."""
    try:
        s, r = int(sender), int(receiver)
    except (TypeError, ValueError):
        return TIER_INTRA_SILO
    return TIER_SILO_SERVER if server_rank in (s, r) else TIER_INTRA_SILO
