"""fedslo canary verdicts — promote | rollback | extend, with receipts.

ROADMAP item 4's promotion loop needs exactly one decision function: a
freshly aggregated adapter is serving a traffic slice next to the
incumbent; somebody has to look at the two metric streams and say
*promote* (candidate is fine), *rollback* (candidate regressed), or
*extend* (not enough evidence yet).  :class:`CanaryJudge` is that
function, built on the fedslo primitives:

- **Burn-rate comparison** (:mod:`.slo`): each objective rule's bad
  fraction is computed for both streams at bucket resolution; the
  candidate *violates* a rule when it both blows the rule's own error
  budget (by ``burn_min``×) AND is materially worse than the baseline
  (``ratio_min``× the baseline's bad fraction plus an absolute floor —
  a baseline already on fire must not launder the candidate).
- **Bucket-level two-sample test**: a chi-square homogeneity test over
  the (merged-label) histogram buckets of the primary objective metric,
  so a latency *shift* shows up even when both streams stay inside the
  SLO.  The p-value uses the Wilson–Hilferty normal approximation
  (stdlib ``math.erfc``) — exact enough at these counts, zero deps.
- **Audit log**: every verdict appends one JSONL record (timestamp,
  verdict, per-rule evidence, the test statistic, both streams' counts)
  — the machine-readable trail an operator replays when a rollback is
  questioned.  :func:`validate_audit_log` is the schema witness tests
  and the bench both run.

Decision table: any violated rule with a significant shift ⇒
``rollback``; no violations and enough traffic ⇒ ``promote``
(a significant but *favorable or in-budget* shift does not block);
otherwise ⇒ ``extend``.  Pure stdlib, host floats only.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, Iterable, List, Optional

from .histogram import Histogram, _le_key, merge_bucket_entries
from .slo import objective_budget, validate_objective

#: audit-record schema — every JSONL line carries exactly these keys
AUDIT_KEYS = ("ts", "verdict", "adapter", "metric", "baseline",
              "candidate", "rules", "shift", "meta")
VERDICTS = ("promote", "rollback", "extend")


def _norm_stream(stream) -> Dict[str, Any]:
    """Accept a :class:`Histogram`, a snapshot map, or a single bucket
    entry; return one merged-across-labels bucket entry."""
    if isinstance(stream, Histogram):
        stream = stream.snapshot()
    if isinstance(stream, dict) and "buckets" in stream:
        return stream
    if isinstance(stream, dict):
        merged = merge_bucket_entries(list(stream.values()))
        if merged is None:
            return {"buckets": [], "sum": 0.0, "count": 0}
        return merged
    raise TypeError(f"cannot read metric stream of type {type(stream)}")


def _bad_fraction(entry: Dict[str, Any], threshold: float
                  ) -> Optional[float]:
    """Fraction of samples above ``threshold``, at bucket resolution
    (good = cumulative count at the smallest bound ≥ threshold)."""
    total = int(entry.get("count", 0))
    if total <= 0:
        return None
    good = 0
    for le, cum in sorted(entry["buckets"], key=lambda b: _le_key(b[0])):
        if _le_key(le) >= threshold:
            good = cum
            break
    return (total - good) / total


def chi2_two_sample(a: Dict[str, Any], b: Dict[str, Any]
                    ) -> Dict[str, Any]:
    """Chi-square homogeneity test over two bucket entries sharing one
    ``le`` grid.  Adjacent sparse buckets pool until every expected cell
    ≥ 5 (the textbook validity rule); returns the statistic, degrees of
    freedom, and a Wilson–Hilferty p-value."""
    les_a = [le for le, _c in a["buckets"]]
    les_b = [le for le, _c in b["buckets"]]
    if les_a != les_b:
        raise ValueError("two-sample test needs identical boundaries")
    def widths(entry):
        out, prev = [], 0
        for _le, cum in sorted(entry["buckets"],
                               key=lambda x: _le_key(x[0])):
            out.append(cum - prev)
            prev = cum
        return out
    ca, cb = widths(a), widths(b)
    na, nb = sum(ca), sum(cb)
    if na == 0 or nb == 0:
        return {"stat": 0.0, "df": 0, "p_value": 1.0, "cells": 0}
    # pool adjacent buckets until each pooled column's total expected
    # count supports the approximation
    pooled: List[List[int]] = []
    run = [0, 0]
    for xa, xb in zip(ca, cb):
        run[0] += xa
        run[1] += xb
        tot = run[0] + run[1]
        if tot * na / (na + nb) >= 5 and tot * nb / (na + nb) >= 5:
            pooled.append(run)
            run = [0, 0]
    if run != [0, 0]:
        if pooled:
            pooled[-1][0] += run[0]
            pooled[-1][1] += run[1]
        else:
            pooled.append(run)
    if len(pooled) < 2:
        return {"stat": 0.0, "df": 0, "p_value": 1.0,
                "cells": len(pooled)}
    stat = 0.0
    for xa, xb in pooled:
        tot = xa + xb
        ea = tot * na / (na + nb)
        eb = tot * nb / (na + nb)
        stat += (xa - ea) ** 2 / ea + (xb - eb) ** 2 / eb
    df = len(pooled) - 1
    # Wilson–Hilferty: ((X/df)^(1/3) - (1 - 2/(9df))) / sqrt(2/(9df)) ~ N(0,1)
    z = (((stat / df) ** (1.0 / 3.0) - (1.0 - 2.0 / (9.0 * df)))
         / math.sqrt(2.0 / (9.0 * df)))
    p = 0.5 * math.erfc(z / math.sqrt(2.0))
    return {"stat": round(stat, 4), "df": df,
            "p_value": round(min(max(p, 0.0), 1.0), 6),
            "cells": len(pooled)}


class CanaryJudge:
    """The promote/rollback/extend decision function (module docstring
    has the decision table)."""

    def __init__(self, rules: Iterable[Dict[str, Any]],
                 audit_path: Optional[str] = None,
                 min_count: int = 20, alpha: float = 0.01,
                 burn_min: float = 1.0, ratio_min: float = 2.0,
                 abs_floor: float = 0.02, clock=time.time):
        self.rules = [r for r in rules if r.get("objective")]
        if not self.rules:
            raise ValueError("CanaryJudge needs at least one "
                             "objective-style rule")
        for r in self.rules:
            validate_objective(r["objective"],
                               where=r.get("name", "rule"))
        self.audit_path = audit_path
        self.min_count = int(min_count)
        self.alpha = float(alpha)
        self.burn_min = float(burn_min)
        self.ratio_min = float(ratio_min)
        self.abs_floor = float(abs_floor)
        self._clock = clock

    def judge(self, baseline, candidate, adapter: str = "candidate",
              meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Compare two metric streams for the primary objective metric
        (``baseline``/``candidate``: :class:`Histogram`, snapshot map,
        or bucket entry) and return the verdict record (also appended
        to the audit log when one is configured)."""
        base = _norm_stream(baseline)
        cand = _norm_stream(candidate)
        rule_rows: List[Dict[str, Any]] = []
        violated = False
        for rule in self.rules:
            obj = rule["objective"]
            budget = objective_budget(obj)
            thr = float(obj["threshold"])
            bf_base = _bad_fraction(base, thr)
            bf_cand = _bad_fraction(cand, thr)
            row: Dict[str, Any] = {
                "name": rule.get("name", obj["metric"]),
                "metric": obj["metric"], "threshold": thr,
                "budget": budget, "baseline_bad_fraction": bf_base,
                "candidate_bad_fraction": bf_cand,
                "baseline_burn": (bf_base / budget
                                  if bf_base is not None else None),
                "candidate_burn": (bf_cand / budget
                                   if bf_cand is not None else None),
            }
            v = (bf_cand is not None
                 and bf_cand > budget * self.burn_min
                 and bf_cand > ((bf_base or 0.0) * self.ratio_min
                                + self.abs_floor))
            row["violated"] = bool(v)
            violated = violated or v
            rule_rows.append(row)

        shift = chi2_two_sample(base, cand) if base["buckets"] \
            and cand["buckets"] else {"stat": 0.0, "df": 0,
                                      "p_value": 1.0, "cells": 0}
        significant = shift["p_value"] < self.alpha
        enough = (int(base.get("count", 0)) >= self.min_count
                  and int(cand.get("count", 0)) >= self.min_count)

        if violated and (significant or not enough):
            # a budget blowout with a confirmed distribution shift is a
            # regression; a blowout on thin evidence still must not
            # promote — keep the canary and keep watching
            verdict = "rollback" if significant else "extend"
        elif violated:
            verdict = "rollback"
        elif not enough:
            verdict = "extend"
        else:
            verdict = "promote"

        record = {
            "ts": float(self._clock()),
            "verdict": verdict,
            "adapter": str(adapter),
            "metric": self.rules[0]["objective"]["metric"],
            "baseline": {"count": int(base.get("count", 0)),
                         "sum": float(base.get("sum", 0.0))},
            "candidate": {"count": int(cand.get("count", 0)),
                          "sum": float(cand.get("sum", 0.0))},
            "rules": rule_rows,
            "shift": dict(shift, alpha=self.alpha,
                          significant=significant),
            "meta": dict(meta or {}),
        }
        if self.audit_path:
            append_audit(self.audit_path, record)
        return record


def append_audit(path: str, record: Dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True) + "\n")


def validate_audit_log(path: str) -> List[Dict[str, Any]]:
    """Load + schema-check a JSONL audit log; raises ``ValueError`` on
    the first malformed record.  Returns the records."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}")
            missing = [k for k in AUDIT_KEYS if k not in rec]
            if missing:
                raise ValueError(f"{path}:{lineno}: audit record "
                                 f"missing {missing}")
            if rec["verdict"] not in VERDICTS:
                raise ValueError(f"{path}:{lineno}: unknown verdict "
                                 f"{rec['verdict']!r}")
            if not isinstance(rec["rules"], list) or not rec["rules"]:
                raise ValueError(f"{path}:{lineno}: empty rules "
                                 "evidence")
            out.append(rec)
    return out
