"""Process-wide jax monitoring hub + transfer probes.

jax's ``monitoring.register_event_duration_secs_listener`` has no public
unregister, so every consumer registering its own listener leaks one per
scope (the pre-ISSUE-4 ``JaxRuntimeAudit`` worked around this with a
private helper).  This module registers ONE listener lazily and fans out
to subscribers — the runtime auditor (:mod:`fedml_tpu.analysis.runtime`)
and the fedtrace tracer both attach here, so audits and traces observe
the identical compile stream.

The transfer probe wraps ``jax.device_put``/``jax.device_get`` to count
bytes.  Wrapping intercepts — it never ADDS a transfer or a sync, which
is what keeps ``JaxRuntimeAudit`` counters identical between traced and
untraced runs (pinned in ``tests/test_fedtrace.py``).
"""

from __future__ import annotations

import threading
from typing import Callable, List

#: fires once per XLA backend compile (cache misses only)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_subscribers: List[Callable] = []
_registered = False
_lock = threading.Lock()


def _dispatch(event: str, duration: float, **kw):
    for fn in list(_subscribers):
        try:
            fn(event, duration)
        except Exception:  # a broken subscriber must not break compilation
            pass


def subscribe(fn: Callable[[str, float], None]):
    """Attach ``fn(event, duration)`` to the duration-event stream.  The
    underlying jax listener registers once per process and stays
    registered (dispatching to an empty list when all subscribers leave —
    safe and inert)."""
    global _registered
    import jax

    with _lock:
        if not _registered:
            jax.monitoring.register_event_duration_secs_listener(_dispatch)
            _registered = True
        if fn not in _subscribers:
            _subscribers.append(fn)


def unsubscribe(fn: Callable):
    with _lock:
        if fn in _subscribers:
            _subscribers.remove(fn)


def tree_nbytes(x) -> int:
    """Total buffer bytes across the pytree's array leaves (raw
    ``bytes`` leaves — fedwire chunk frames — count at their length)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(x):
        if isinstance(leaf, (bytes, bytearray)):
            total += len(leaf)
        else:
            total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def install_tracer_hooks(tracer) -> Callable[[], None]:
    """Subscribe ``tracer`` to compile events and install the transfer
    byte probes; returns an uninstall callable restoring both."""
    import jax

    def on_event(event: str, duration: float):
        if event == BACKEND_COMPILE_EVENT:
            tracer.complete("xla_compile", duration, cat="compile")

    subscribe(on_event)
    orig_put, orig_get = jax.device_put, jax.device_get

    def traced_put(x, *a, **kw):
        tracer.add_bytes("device_put_bytes", tree_nbytes(x))
        return orig_put(x, *a, **kw)

    def traced_get(x, *a, **kw):
        tracer.add_bytes("device_get_bytes", tree_nbytes(x))
        return orig_get(x, *a, **kw)

    jax.device_put, jax.device_get = traced_put, traced_get

    def uninstall():
        unsubscribe(on_event)
        jax.device_put, jax.device_get = orig_put, orig_get

    return uninstall
