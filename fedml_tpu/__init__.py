"""fedml_tpu — a TPU-native federated learning + MLOps framework.

Capability parity with the reference FedML (``/root/reference``), rebuilt
idiomatically for TPU: clients are a mesh axis, local SGD is a scanned jitted
step, aggregation is ``psum`` over ICI, and the message-passing layer is a
thin WAN shim instead of the core (see SURVEY.md §7 design stance).

Public surface parity (reference ``python/fedml/__init__.py``):
``init / run_simulation / run_cross_silo_server / run_cross_silo_client /
run_hierarchical_cross_silo_* / run_mnn_server``, plus the ``device``,
``data``, ``model``, ``mlops`` modules.
"""

from __future__ import annotations

import logging
import os
import random
from typing import Optional

import numpy as np

__version__ = "0.1.0"

# Platform override that actually works on images whose TPU PJRT plugin
# re-forces jax_platforms at import time (JAX_PLATFORMS env alone doesn't
# stick there): FEDML_TPU_PLATFORM=cpu [FEDML_TPU_NUM_CPU_DEVICES=8] must be
# applied through jax.config BEFORE any backend initialization.
_plat = os.environ.get("FEDML_TPU_PLATFORM")
if _plat:
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _plat)
        _n = os.environ.get("FEDML_TPU_NUM_CPU_DEVICES")
        if _n:
            _jax.config.update("jax_num_cpu_devices", int(_n))
    except Exception:  # backend already initialized: leave it alone
        logging.getLogger(__name__).warning(
            "FEDML_TPU_PLATFORM=%s ignored (jax backend already "
            "initialized)", _plat)

# Persistent XLA compilation cache: TPU compiles over the tunnel backend run
# 20-40s+ each and every process pays them again otherwise.  TPU-path only —
# XLA:CPU AOT cache entries embed compile-machine features and reload with
# SIGILL warnings on feature mismatch, and CPU compiles are cheap anyway.
# Opt out with FEDML_TPU_NO_COMPILE_CACHE=1; explicit
# JAX_COMPILATION_CACHE_DIR wins.
_jax_plat_env = os.environ.get("JAX_PLATFORMS", "")
_cpu_only = ((_plat or "").lower() == "cpu"
             or (_jax_plat_env and all(
                 p.strip().lower() in ("cpu", "")
                 for p in _jax_plat_env.split(","))))


def _tpu_plugin_present() -> bool:
    # only enable the persistent cache when a TPU PJRT plugin could actually
    # serve this process — on plain-CPU hosts the cache would fill with
    # XLA:CPU AOT entries that embed compile-machine features and reload
    # with SIGILL warnings on heterogeneous fleets
    import importlib.util
    return any(importlib.util.find_spec(m) is not None
               for m in ("libtpu", "axon"))


if (not os.environ.get("FEDML_TPU_NO_COMPILE_CACHE") and not _cpu_only
        and _tpu_plugin_present()):
    try:
        import jax as _jax

        _cache = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "fedml_tpu_xla")
        os.makedirs(_cache, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass

from . import compat as _compat  # noqa: E402

_compat.install()

from . import constants  # noqa: E402
from .arguments import Arguments, add_args, load_arguments  # noqa: E402
from .constants import (  # noqa: E402
    FEDML_SIMULATION_TYPE_MESH,
    FEDML_SIMULATION_TYPE_SP,
    FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
    FEDML_TRAINING_PLATFORM_CROSS_SILO,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)

_global_training_type: Optional[str] = None
_global_comm_backend: Optional[str] = None


def init(args: Optional[Arguments] = None, check_env: bool = True,
         should_init_logs: bool = True) -> Arguments:
    """Parity with ``fedml.init`` (reference ``python/fedml/__init__.py:64``):
    load args (YAML + CLI), seed host RNGs, init mlops, dispatch per-mode
    setup.  Device RNG is handled by explicit threefry keys (core/rng.py), so
    host seeding matters only for numpy-side sampling."""
    if args is None:
        args = load_arguments(_global_training_type, _global_comm_backend)
    from .arguments import validate_args
    validate_args(args)
    seed = int(getattr(args, "random_seed", 0))
    random.seed(seed)
    np.random.seed(seed)
    if should_init_logs:
        logging.basicConfig(
            level=logging.INFO,
            format="[fedml_tpu] %(asctime)s %(levelname)s %(name)s: %(message)s")
    from . import mlops
    mlops.init(args)

    t = str(getattr(args, "training_type", FEDML_TRAINING_PLATFORM_SIMULATION))
    if t == FEDML_TRAINING_PLATFORM_CROSS_SILO:
        _update_client_id_list(args)
    return args


def _update_client_id_list(args):
    """Reference ``__init__.py:409``: normalize client_id_list for cross-silo
    runs so the server knows its expected client set."""
    n = int(getattr(args, "client_num_in_total", 0) or 0)
    cur = getattr(args, "client_id_list", None)
    if not cur or cur in ("[]", "None"):
        args.client_id_list = list(range(1, n + 1))
    elif isinstance(cur, str):
        import json
        try:
            args.client_id_list = json.loads(cur)
        except json.JSONDecodeError:
            args.client_id_list = list(range(1, n + 1))


# -- one-line launchers (reference launch_simulation.py / launch_cross_silo*)
def run_simulation(backend: str = FEDML_SIMULATION_TYPE_SP, args=None,
                   client_trainer=None, server_aggregator=None):
    """Parity with ``fedml.run_simulation`` (reference
    ``python/fedml/launch_simulation.py:9``)."""
    global _global_training_type, _global_comm_backend
    _global_training_type = FEDML_TRAINING_PLATFORM_SIMULATION
    _global_comm_backend = backend
    if args is None:
        args = init()
    args.training_type = FEDML_TRAINING_PLATFORM_SIMULATION
    args.backend = backend
    from . import data as data_mod
    from . import device as device_mod
    from . import model as model_mod
    from .runner import FedMLRunner

    dev = device_mod.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, dev, dataset, model, client_trainer,
                         server_aggregator)
    return runner.run()


def _run_cross_silo(role: str, args=None, client_trainer=None,
                    server_aggregator=None, scenario: str = "horizontal"):
    global _global_training_type
    _global_training_type = FEDML_TRAINING_PLATFORM_CROSS_SILO
    if args is None:
        args = init()
    args.training_type = FEDML_TRAINING_PLATFORM_CROSS_SILO
    args.role = role
    args.scenario = getattr(args, "scenario", scenario) or scenario
    from . import data as data_mod
    from . import device as device_mod
    from . import model as model_mod
    from .runner import FedMLRunner

    dev = device_mod.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    return FedMLRunner(args, dev, dataset, model, client_trainer,
                       server_aggregator).run()


def run_cross_silo_server(args=None, server_aggregator=None):
    return _run_cross_silo("server", args, None, server_aggregator)


def run_cross_silo_client(args=None, client_trainer=None):
    return _run_cross_silo("client", args, client_trainer, None)


def run_hierarchical_cross_silo_server(args=None, server_aggregator=None):
    return _run_cross_silo("server", args, None, server_aggregator,
                           scenario="hierarchical")


def run_hierarchical_cross_silo_client(args=None, client_trainer=None):
    return _run_cross_silo("client", args, client_trainer, None,
                           scenario="hierarchical")


def run_mnn_server(args=None, server_aggregator=None):
    """Cross-device server (reference ``fedml.run_mnn_server``)."""
    global _global_training_type
    _global_training_type = FEDML_TRAINING_PLATFORM_CROSS_DEVICE
    if args is None:
        args = init()
    args.training_type = FEDML_TRAINING_PLATFORM_CROSS_DEVICE
    from . import data as data_mod
    from . import device as device_mod
    from . import model as model_mod
    from .runner import FedMLRunner

    dev = device_mod.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    return FedMLRunner(args, dev, dataset, model, None, server_aggregator).run()


def run_model_serving_server(args, end_point_name, model_name,
                             model_version="", dataset=None, model=None,
                             server_aggregator=None):
    """Federated serving server (reference ``fedml.run_model_serving_server``,
    ``__init__.py:520-546`` exports)."""
    from .serving import FedMLModelServingServer
    return FedMLModelServingServer(
        args, end_point_name, model_name, model_version, dataset=dataset,
        model=model, server_aggregator=server_aggregator).run()


def run_model_serving_client(args, end_point_name, model_name,
                             model_version="", dataset=None, model=None,
                             client_trainer=None):
    """Federated serving client (reference ``fedml.run_model_serving_client``)."""
    from .serving import FedMLModelServingClient
    return FedMLModelServingClient(
        args, end_point_name, model_name, model_version, dataset=dataset,
        model=model, client_trainer=client_trainer).run()


# module namespaces mirroring `fedml.data` / `fedml.model` / `fedml.device`
from . import data  # noqa: E402
from . import device  # noqa: E402
from . import mlops  # noqa: E402
from . import model  # noqa: E402

# user metric APIs re-exported at top level (reference __init__.py:547-566)
from .mlops import (log, log_artifact, log_endpoint, log_llm_record,  # noqa: E402
                    log_metric, log_model)

__all__ = [
    "init", "run_simulation", "run_cross_silo_server", "run_cross_silo_client",
    "run_hierarchical_cross_silo_server", "run_hierarchical_cross_silo_client",
    "run_mnn_server", "run_model_serving_server", "run_model_serving_client",
    "Arguments", "add_args", "load_arguments",
    "log", "log_metric", "log_artifact", "log_model", "log_llm_record",
    "log_endpoint",
    "constants", "data", "device", "model", "mlops", "__version__",
]
