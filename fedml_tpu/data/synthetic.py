"""Deterministic synthetic datasets shaped like the reference's dataset zoo.

This build environment has zero network egress, so the torchvision-style
downloads the reference does (``python/fedml/data/data_loader.py`` →
``data/MNIST/...``) are replaced by generators that produce datasets with the
same shapes/cardinalities and a controllable difficulty, deterministic in the
seed.  When real data is present in ``args.data_cache_dir`` the loaders in
:mod:`fedml_tpu.data.data_loader` prefer it.

Generator design: class-conditional Gaussians in a ``latent_dim`` space pushed
through a fixed random affine map into pixel space, plus per-class structured
"digit stroke" patterns so that logistic regression reaches ~0.8+ accuracy
(matching the reference LR/MNIST curve shape) while CNNs do better — the same
qualitative ordering as the real datasets.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..core import hostrng


def _class_gaussian_images(
    n: int, num_classes: int, shape: Tuple[int, ...], seed: int,
    noise: float = 0.35, latent_dim: int = 32,
) -> Tuple[np.ndarray, np.ndarray]:
    rng = hostrng.gen(seed, 0x5E7)
    dim = int(np.prod(shape))
    # fixed class anchors in latent space, well separated
    anchors = rng.standard_normal((num_classes, latent_dim)) * 2.0
    proj = rng.standard_normal((latent_dim, dim)) / np.sqrt(latent_dim)
    y = rng.integers(0, num_classes, size=n)
    z = anchors[y] + rng.standard_normal((n, latent_dim)) * noise
    x = z @ proj + rng.standard_normal((n, dim)) * (noise * 0.5)
    # squash to [0, 1] pixel range like normalized image data
    x = np.tanh(x * 0.5) * 0.5 + 0.5
    return x.reshape((n,) + shape).astype(np.float32), y.astype(np.int64)


def synthetic_image_classification(
    train_n: int, test_n: int, num_classes: int, shape: Tuple[int, ...],
    seed: int, noise: float = 0.35,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    x, y = _class_gaussian_images(train_n + test_n, num_classes, shape, seed, noise)
    return x[:train_n], y[:train_n], x[train_n:], y[train_n:]


def synthetic_lm_tokens(
    train_n: int, test_n: int, vocab: int, seq_len: int, seed: int,
    order: int = 2,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Markov-chain token sequences (for Shakespeare/StackOverflow-style LM
    workloads): a fixed sparse bigram transition matrix gives the model real
    structure to learn.  x = tokens[:-1]-style input, y = next-token target."""
    rng = hostrng.gen(seed, 0x71AB)
    # sparse-ish transition: each token strongly prefers ~4 successors
    succ = rng.integers(0, vocab, size=(vocab, 4))
    n = train_n + test_n
    seqs = np.zeros((n, seq_len + 1), dtype=np.int64)
    seqs[:, 0] = rng.integers(0, vocab, size=n)
    for t in range(seq_len):
        choice = rng.integers(0, 4, size=n)
        noise_tok = rng.integers(0, vocab, size=n)
        use_noise = rng.random(n) < 0.1
        nxt = succ[seqs[:, t], choice]
        seqs[:, t + 1] = np.where(use_noise, noise_tok, nxt)
    x, y = seqs[:, :-1], seqs[:, 1:]
    return x[:train_n], y[:train_n], x[train_n:], y[train_n:]


def synthetic_tabular(train_n: int, test_n: int, classes: int,
                      n_features: int, seed: int = 0, noise: float = 0.6):
    """Class-conditional Gaussian tabular data (stand-in for UCI/lending
    club when no ``data_cache_dir`` file is present — reference downloads
    these; zero-egress builds generate)."""
    rng = np.random.default_rng(seed)
    means = rng.standard_normal((classes, n_features))
    def gen(n):
        y = rng.integers(0, classes, size=n)
        x = means[y] + noise * rng.standard_normal((n, n_features))
        return x.astype(np.float32), y.astype(np.int64)
    tx, ty = gen(train_n)
    vx, vy = gen(test_n)
    return tx, ty, vx, vy


def synthetic_text_classification(train_n: int, test_n: int, classes: int,
                                  vocab: int, seq_len: int, seed: int = 0,
                                  class_signal: float = 0.25,
                                  keyword_width: float = 2.5):
    """Class-dependent unigram token sequences (fednlp/20news stand-in).

    Difficulty knobs (round-4 VERDICT weak #4: the original generator put
    70% of tokens in DISJOINT per-class vocabulary slices, so any unigram
    model saturates at accuracy 1.0 within a few rounds and the accuracy
    curve carries no information):

    - ``class_signal``: fraction of tokens drawn from the class's keyword
      window (the rest are uniform background).  Fewer signal tokens →
      noisier per-document evidence.
    - ``keyword_width``: keyword-window size as a multiple of the disjoint
      slice width ``vocab // classes``.  Values > 1 make ADJACENT classes
      share keywords (windows overlap, wrapping mod vocab), so even a
      Bayes-optimal unigram classifier has irreducible confusion between
      neighbors — the eval cannot saturate at 1.0.

    Defaults are calibrated so a multinomial naive-Bayes unigram probe —
    Bayes-OPTIMAL for this generative model (tokens i.i.d. multinomial
    given class), hence a true accuracy ceiling — scores ~0.74; any
    trained model must plateau in the 0.6–0.8 band, never 1.0 (pinned by
    ``tests/test_datasets_ext.py``).
    """
    rng = np.random.default_rng(seed)
    stride = max(1, vocab // classes)
    width = max(1, int(round(keyword_width * stride)))

    def gen(n):
        y = rng.integers(0, classes, size=n)
        lo = (y * stride)[:, None]
        base = rng.integers(0, width, size=(n, seq_len))
        uniform = rng.integers(0, vocab, size=(n, seq_len))
        use_class = rng.random((n, seq_len)) < class_signal
        x = np.where(use_class, (lo + base) % vocab, uniform)
        return x.astype(np.int32), y.astype(np.int64)

    tx, ty = gen(train_n)
    vx, vy = gen(test_n)
    return tx, ty, vx, vy


def synthetic_vertical_parties(n: int, parties: int, features_per_party,
                               classes: int = 2, seed: int = 0,
                               noise: float = 0.5):
    """Vertically-partitioned features (NUS-WIDE-style: each party holds a
    different feature block for the SAME samples; reference
    ``data/NUS_WIDE/nus_wide_dataset.py`` two-party split)."""
    rng = np.random.default_rng(seed)
    if isinstance(features_per_party, int):
        features_per_party = [features_per_party] * parties
    total = sum(features_per_party)
    means = rng.standard_normal((classes, total))
    y = rng.integers(0, classes, size=n)
    x = means[y] + noise * rng.standard_normal((n, total))
    outs, off = [], 0
    for f in features_per_party:
        outs.append(x[:, off:off + f].astype(np.float32))
        off += f
    return outs, y.astype(np.int64)


def synthetic_segmentation(train_n: int, test_n: int, num_classes: int,
                           shape, seed: int, noise: float = 0.1):
    """Dense per-pixel labels (FeTS2021 / AutonomousDriving fallback;
    reference ``data/FeTS2021/``, ``data/AutonomousDriving/``): blocky class
    regions whose channel intensity encodes the class, so a segmentation net
    can actually learn the mapping."""
    rng = np.random.default_rng(seed ^ 0x5E6)
    n = train_n + test_n
    h, w = int(shape[0]), int(shape[1])
    c = int(shape[2]) if len(shape) > 2 else 1
    # piecewise-constant masks: random low-res label grids upsampled 4x
    gh, gw = max(1, h // 4), max(1, w // 4)
    grid = rng.integers(0, num_classes, size=(n, gh, gw))
    y = np.repeat(np.repeat(grid, (h + gh - 1) // gh, axis=1),
                  (w + gw - 1) // gw, axis=2)[:, :h, :w]
    x = (y[..., None] / max(num_classes - 1, 1)).astype(np.float32)
    x = np.broadcast_to(x, (n, h, w, c)).copy()
    x += noise * rng.standard_normal(x.shape).astype(np.float32)
    return (x[:train_n], y[:train_n].astype(np.int64),
            x[train_n:], y[train_n:].astype(np.int64))


def synthetic_tag_prediction(train_n: int, test_n: int, n_tags: int,
                             n_features: int, seed: int = 0,
                             avg_tags: int = 3):
    """Multi-label tag-prediction data — the stackoverflow_lr stand-in
    (reference ``data/stackoverflow/`` LR task: sparse bag-of-words
    features → multi-hot tag vector, consumed by
    ``ml/trainer/my_model_trainer_tag_prediction.py``).  Labels are the
    ``avg_tags`` highest-scoring tags under a fixed random linear map, so
    the task is learnable by the LR model."""
    rng = np.random.default_rng(seed)
    avg_tags = max(1, min(int(avg_tags), n_tags - 1)) if n_tags > 1 else 1
    w = rng.standard_normal((n_features, n_tags)) / np.sqrt(n_features)

    def features(n):
        return ((rng.random((n, n_features)) < 0.05)
                * rng.exponential(1.0, (n, n_features))).astype(np.float32)

    # ABSOLUTE per-tag thresholds (calibrated so each tag fires on
    # ~avg_tags/n_tags of examples) keep every tag independently linearly
    # separable — a per-row top-k rule would make tag membership depend on
    # the other tags' scores, which no per-tag sigmoid can express
    calib = features(2048) @ w
    thresh = np.quantile(calib, 1.0 - avg_tags / n_tags, axis=0)

    def gen(n):
        x = features(n)
        y = ((x @ w) >= thresh[None, :]).astype(np.float32)
        return x, y

    tx, ty = gen(train_n)
    vx, vy = gen(test_n)
    return tx, ty, vx, vy
