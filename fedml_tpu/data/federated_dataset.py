"""FederatedDataset — the TPU-native data container.

The reference's ``fedml.data.load`` returns an 8-tuple of torch DataLoaders
(``python/fedml/data/data_loader.py:234``):
``(train_num, test_num, train_global, test_global, local_num_dict,
train_local_dict, test_local_dict, class_num)``.  Per-client DataLoaders force
a Python iterator per client — fine for eager torch, hostile to jit.

Here all data lives as two dense device-resident arrays (x, y) plus per-client
*index arrays*; batches are materialized by gather, so:
- the SP engine slices per-client batches with ``jnp.take`` (no host loop),
- the mesh engine builds a padded ``(clients, steps, batch, ...)`` cohort
  tensor in one gather and feeds it straight into ``shard_map``+``scan``,
- ragged client sizes are handled by padding to the cohort max and masking
  (the policy SURVEY §7 "hard parts" calls for; replaces the reference's
  ``SeqTrainScheduler`` Python-side balancing).

``as_reference_tuple`` reproduces the legacy 8-tuple for API parity.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from ..core import hostrng

from ..core.data.noniid_partition import partition, record_data_stats


@dataclasses.dataclass
class FederatedDataset:
    train_x: np.ndarray          # (N, ...) model-ready features
    train_y: np.ndarray          # (N,) int labels (or (N, seq) token targets)
    test_x: np.ndarray
    test_y: np.ndarray
    client_idxs: Dict[int, np.ndarray]   # client -> train indices
    num_classes: int
    test_client_idxs: Optional[Dict[int, np.ndarray]] = None
    # data lineage, stamped by the loader and propagated into every round's
    # metrics record: "real:<source>" (leaf/npz/idx/cifar/hdf5/...) or
    # "synthetic" — an accuracy measured on synthetic fallback pixels must
    # never be mistakable for a real-dataset number downstream (VERDICT r2).
    provenance: str = "unknown"

    @property
    def num_clients(self) -> int:
        return len(self.client_idxs)

    @property
    def train_data_num(self) -> int:
        return len(self.train_x)

    @property
    def test_data_num(self) -> int:
        return len(self.test_x)

    def client_sample_counts(self) -> np.ndarray:
        return np.array([len(self.client_idxs[c]) for c in range(self.num_clients)],
                        dtype=np.int64)

    def stats(self):
        return record_data_stats(self.train_y, self.client_idxs, self.num_classes)

    # -- batching ----------------------------------------------------------
    def client_batches(self, client: int, batch_size: int, seed: int,
                       round_idx: int, epochs: int = 1
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Epoch-shuffled, batch-truncated data for one client: returns
        (epochs*steps, batch, ...) feature and label arrays, one fresh
        permutation per epoch (reference DataLoader-with-shuffle semantics).
        Short clients are padded by repetition up to one full batch so every
        client takes >=1 step."""
        idx = self.client_index_batches(client, batch_size, seed, round_idx,
                                        epochs)
        total = idx.shape[0]
        flat = idx.reshape(-1)
        xb = self.train_x[flat].reshape(
            (total, batch_size) + self.train_x.shape[1:])
        yb = self.train_y[flat].reshape(
            (total, batch_size) + self.train_y.shape[1:])
        return xb, yb

    def client_index_batches(self, client: int, batch_size: int, seed: int,
                             round_idx: int, epochs: int = 1) -> np.ndarray:
        """The ONE per-client batch-schedule implementation: (steps, batch)
        index array, per-(client, epoch) rng stream, so the device-gather
        path (cohort_indices), the host path (client_batches/
        cohort_batches) and the cross-silo trainer all see the same
        schedule for a given client+round."""
        base = self.client_idxs[client]
        all_idx = []
        for e in range(epochs):
            rng = hostrng.gen(seed, round_idx * 1031 + e, client, 1)
            idx = rng.permutation(base)
            if len(idx) < batch_size:
                reps = int(np.ceil(batch_size / max(len(idx), 1)))
                idx = np.tile(idx, reps)[:batch_size]
            steps = len(idx) // batch_size
            all_idx.append(idx[: steps * batch_size])
        idx = np.concatenate(all_idx)
        total = len(idx) // batch_size
        return idx[: total * batch_size].reshape(total, batch_size)

    def cohort_indices(self, clients, batch_size: int, seed: int,
                       round_idx: int, epochs: int = 1,
                       max_steps: Optional[int] = None):
        """Padded cohort INDEX tensor (n_clients, steps, batch) int32 +
        step mask + weights: the device-gather counterpart of
        cohort_batches (padding indices point at row 0, masked out)."""
        per = [self.client_index_batches(c, batch_size, seed, round_idx,
                                         epochs) for c in clients]
        steps = max(p.shape[0] for p in per)
        if max_steps is not None:
            steps = min(steps, max_steps)
        n = len(clients)
        idx = np.zeros((n, steps, batch_size), dtype=np.int32)
        mask = np.zeros((n, steps), dtype=np.float32)
        for i, p in enumerate(per):
            s = min(p.shape[0], steps)
            idx[i, :s], mask[i, :s] = p[:s], 1.0
        w = np.array([len(self.client_idxs[c]) for c in clients],
                     dtype=np.float32)
        return idx, mask, w

    def cohort_batches(self, clients, batch_size: int, seed: int, round_idx: int,
                       epochs: int = 1, max_steps: Optional[int] = None):
        """Padded cohort tensor for the mesh engine.

        Returns ``(x, y, step_mask, weights)`` where x has shape
        ``(n_clients, steps, batch, ...)``; ``step_mask[c, s]`` is 0 for
        padding steps (client c ran out of data) so gradients from padded
        steps are masked inside the scanned train step; ``weights`` are true
        per-client sample counts for the FedAvg merge.
        """
        per = [self.client_batches(c, batch_size, seed, round_idx, epochs)
               for c in clients]
        steps = max(x.shape[0] for x, _ in per)
        if max_steps is not None:
            steps = min(steps, max_steps)
        n = len(clients)
        x = np.zeros((n, steps) + per[0][0].shape[1:], dtype=self.train_x.dtype)
        y = np.zeros((n, steps) + per[0][1].shape[1:], dtype=self.train_y.dtype)
        mask = np.zeros((n, steps), dtype=np.float32)
        for i, (xb, yb) in enumerate(per):
            s = min(xb.shape[0], steps)
            x[i, :s], y[i, :s], mask[i, :s] = xb[:s], yb[:s], 1.0
        w = np.array([len(self.client_idxs[c]) for c in clients], dtype=np.float32)
        return x, y, mask, w

    def test_batches(self, batch_size: int = 256):
        """Full test set batched, ragged tail zero-padded; returns
        (xb, yb, valid_mask) with mask shape (steps, batch) so metrics cover
        every sample (no silent truncation)."""
        n = len(self.test_x)
        steps = -(-n // batch_size)
        pad = steps * batch_size - n
        xp = np.concatenate([self.test_x,
                             np.zeros((pad,) + self.test_x.shape[1:],
                                      self.test_x.dtype)])
        yp = np.concatenate([self.test_y,
                             np.zeros((pad,) + self.test_y.shape[1:],
                                      self.test_y.dtype)])
        m = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
        xb = xp.reshape((steps, batch_size) + self.test_x.shape[1:])
        yb = yp.reshape((steps, batch_size) + self.test_y.shape[1:])
        return xb, yb, m.reshape(steps, batch_size)

    def pack_per_client(self, batch_size: int, split: str = "train"):
        """Pad every client's local split to one common (C, steps, B, ...)
        batch stack with validity masks — the shape per-client evaluation
        programs scan (used by ``FedAvgAPI.evaluate_per_client`` and
        ``FedLLMAPI.evaluate_per_client``).

        Clients with no data in the split are EXCLUDED (LEAF gives
        train-only users empty test lists); raises when nobody has data.
        Returns ``(clients, X, Y, M)`` with X/Y shaped
        ``(C, steps, batch_size, ...)`` and M ``(C, steps, batch_size)``.
        """
        if split == "test" and self.test_client_idxs:
            idxs, data_x, data_y = (self.test_client_idxs, self.test_x,
                                    self.test_y)
        else:
            idxs, data_x, data_y = (self.client_idxs, self.train_x,
                                    self.train_y)
        clients = sorted(c for c in idxs if len(idxs[c]) > 0)
        if not clients:
            raise ValueError(f"no client has data in the {split!r} split")
        counts = [len(idxs[c]) for c in clients]
        steps = max(1, -(-max(counts) // batch_size))
        slot = steps * batch_size
        C = len(clients)
        X = np.zeros((C, slot) + data_x.shape[1:], data_x.dtype)
        Y = np.zeros((C, slot) + data_y.shape[1:], data_y.dtype)
        M = np.zeros((C, slot), np.float32)
        for i, c in enumerate(clients):
            rows = idxs[c]
            X[i, : len(rows)] = data_x[rows]
            Y[i, : len(rows)] = data_y[rows]
            M[i, : len(rows)] = 1.0
        shape = (C, steps, batch_size)
        return (np.asarray(clients), X.reshape(shape + data_x.shape[1:]),
                Y.reshape(shape + data_y.shape[1:]), M.reshape(shape))

    # -- legacy parity -----------------------------------------------------
    def as_reference_tuple(self, batch_size: int):
        """Reproduce the reference 8-tuple (data_loader.py:234 return shape),
        with (x, y) ndarray-batch lists standing in for DataLoaders."""
        def batched(x, y):
            out = []
            for i in range(0, len(x), batch_size):
                out.append((x[i : i + batch_size], y[i : i + batch_size]))
            return out

        train_local_dict = {}
        test_local_dict = {}
        local_num_dict = {}
        test_splits = self.test_client_idxs or {}
        for c, idx in self.client_idxs.items():
            train_local_dict[c] = batched(self.train_x[idx], self.train_y[idx])
            local_num_dict[c] = len(idx)
            tidx = test_splits.get(c)
            test_local_dict[c] = (
                batched(self.test_x[tidx], self.test_y[tidx]) if tidx is not None
                else batched(self.test_x, self.test_y)
            )
        return (
            self.train_data_num,
            self.test_data_num,
            batched(self.train_x, self.train_y),
            batched(self.test_x, self.test_y),
            local_num_dict,
            train_local_dict,
            test_local_dict,
            self.num_classes,
        )


def build_federated(train_x, train_y, test_x, test_y, num_classes: int,
                    client_num: int, method: str, alpha: float, seed: int,
                    provenance: str = "unknown") -> FederatedDataset:
    client_idxs = partition(train_y, client_num, method, alpha, seed)
    return FederatedDataset(train_x, train_y, test_x, test_y, client_idxs,
                            num_classes, provenance=provenance)
