"""``fedml_tpu.data.load(args)`` — dataset dispatcher, parity with
``fedml.data.load`` (reference ``python/fedml/data/data_loader.py:234``).

Dispatches on ``args.dataset`` over the reference's dataset names (mnist,
femnist, cifar10/100, cinic10, fed_cifar100, shakespeare, fed_shakespeare,
stackoverflow_lr/nwp, synthetic_*).  Real data is used when found under
``args.data_cache_dir`` (``.npz`` with train_x/train_y/test_x/test_y, or the
classic MNIST idx-ubyte files); otherwise a deterministic synthetic dataset of
identical shape/cardinality is generated (no-egress environment — see
:mod:`fedml_tpu.data.synthetic`).

Returns ``(dataset, class_num)`` where dataset is a
:class:`FederatedDataset`; call ``.as_reference_tuple(batch_size)`` for the
legacy 8-tuple surface.
"""

from __future__ import annotations

import gzip
import os
import re
import struct
from typing import Optional, Tuple

import numpy as np

from .federated_dataset import FederatedDataset, build_federated, partition
from .leaf import find_leaf_root, load_leaf, load_shakespeare_raw
from .synthetic import (synthetic_image_classification, synthetic_lm_tokens,
                        synthetic_segmentation, synthetic_tabular,
                        synthetic_tag_prediction,
                        synthetic_text_classification,
                        synthetic_vertical_parties)

# (classes, img shape, train_n, test_n) per image dataset, matching reference
# dataset cardinalities (python/fedml/data/<name>/data_loader.py)
_IMAGE_SPECS = {
    "mnist": (10, (28, 28, 1), 60000, 10000),
    "synthetic_mnist": (10, (28, 28, 1), 60000, 10000),
    "femnist": (62, (28, 28, 1), 60000, 10000),
    "fashionmnist": (10, (28, 28, 1), 60000, 10000),
    "emnist": (62, (28, 28, 1), 60000, 10000),
    "cifar10": (10, (32, 32, 3), 50000, 10000),
    "cifar100": (100, (32, 32, 3), 50000, 10000),
    "fed_cifar100": (100, (32, 32, 3), 50000, 10000),
    "cinic10": (10, (32, 32, 3), 90000, 90000),
}

_LM_SPECS = {
    # vocab, seq_len, train_n, test_n
    "shakespeare": (90, 80, 16000, 2000),
    "fed_shakespeare": (90, 80, 16000, 2000),
    "stackoverflow_nwp": (10004, 20, 50000, 5000),
    "reddit": (10004, 20, 50000, 5000),
}

# multi-label tag prediction (reference ``data/stackoverflow/`` LR task:
# 10,000 bag-of-words features → 500 tags, trained by
# ``ml/trainer/my_model_trainer_tag_prediction.py`` with BCE loss).
# name -> (n_tags, n_features, ref_train_n, ref_test_n)
_TAGPRED_SPECS = {
    "stackoverflow_lr": (500, 10000, 50000, 5000),
}

# tabular sets (reference ``data/UCI/``, ``data/lending_club_loan/``):
# name -> (classes, n_features, train_n, test_n)
_TABULAR_SPECS = {
    "uci": (2, 14, 30000, 5000),
    "uci_adult": (2, 14, 30000, 5000),
    "lending_club": (2, 20, 40000, 8000),
    "lending_club_loan": (2, 20, 40000, 8000),
}

# text-classification sets (reference ``data/fednlp/``, 20news/agnews):
# name -> (classes, vocab, seq_len, train_n, test_n,
#          class_signal, keyword_width)
# The last two are the PER-DATASET difficulty calibration (see
# synthetic_text_classification): the Bayes-optimal unigram ceiling
# depends on the class count (keyword windows tile the vocab differently
# for 4 vs 20 classes), so each dataset shape carries its own knobs tuned
# to a 0.6-0.8 ceiling — 20news probes at 0.74, agnews at 0.68.
_TEXTCLS_SPECS = {
    "fednlp": (20, 30000, 128, 11000, 2000, 0.25, 2.5),
    "20news": (20, 30000, 128, 11000, 2000, 0.25, 2.5),
    "agnews": (4, 30000, 64, 12000, 2000, 0.35, 2.0),
    # REAL bytes in-image: installed-package documentation prose
    # (tools/make_real_shards.py; data_shards/realtext/realtext.npz) —
    # the synthetic knobs are the fallback path only
    "realtext": (10, 8192, 128, 2967, 530, 0.25, 2.5),
}

# large-image sets (reference ``data/ImageNet/`` incl. hdf5 variant,
# ``data/Landmarks/`` gld23k/gld160k): full reference cardinalities are kept
# for the real-data path; the synthetic fallback honors
# args.train_size/test_size so the no-egress path stays tractable.
# name -> (classes, img shape, ref_train_n, ref_test_n)
_BIG_IMAGE_SPECS = {
    "imagenet": (1000, (224, 224, 3), 1281167, 50000),
    "imagenet_hdf5": (1000, (224, 224, 3), 1281167, 50000),
    "ilsvrc2012": (1000, (224, 224, 3), 1281167, 50000),
    "landmarks": (203, (224, 224, 3), 23080, 1959),
    "gld23k": (203, (224, 224, 3), 23080, 1959),
    "gld160k": (2028, (224, 224, 3), 164172, 19526),
}

# dense-prediction sets (reference ``data/FeTS2021/`` — 4-modality MRI tumor
# segmentation; ``data/AutonomousDriving/`` — driving-scene segmentation):
# name -> (classes, (H, W, C), train_n, test_n)
_SEG_SPECS = {
    "fets2021": (4, (64, 64, 4), 2000, 400),
    "fets": (4, (64, 64, 4), 2000, 400),
    "autonomous_driving": (19, (64, 128, 3), 3000, 500),
    "cityscapes": (19, (64, 128, 3), 3000, 500),
}


def _cache_provenance(root: str, default: str,
                      name: Optional[str] = None) -> str:
    """Lineage for cache-resident files.  Generators that write
    format-faithful but synthetic-content files (``tools/
    make_format_datasets.py``) drop a ``PROVENANCE`` marker file next to
    them; an absent marker means driver-provided real bytes, so ``default``
    (a ``real:*`` tag) applies.

    Shared cache roots can host several datasets, so a bare ``PROVENANCE``
    marker only applies when its tag mentions ``name`` (a marker written
    for generated cifar files must not mislabel a real mnist.npz dropped
    beside them); ``PROVENANCE.<name>`` markers are always dataset-scoped.
    """
    candidates = [f"PROVENANCE.{name}"] if name else []
    candidates.append("PROVENANCE")
    for fname in candidates:
        try:
            with open(os.path.join(root, fname)) as f:
                tag = f.read().strip()
        except OSError:
            continue
        if not tag:
            continue
        if fname == "PROVENANCE" and name and \
                name not in re.split(r"[^a-z0-9_]+", tag.lower()):
            # token match, not substring: a cifar100 marker must not
            # relabel a real cifar10 archive dropped in the same cache
            continue
        return tag
    return default


def _try_load_npz(cache_dir: str, name: str):
    path = os.path.join(cache_dir, f"{name}.npz")
    if os.path.exists(path):
        d = np.load(path)
        return d["train_x"], d["train_y"], d["test_x"], d["test_y"]
    return None


def _try_load_mnist_idx(cache_dir: str):
    """Classic yann-lecun idx-ubyte files, optionally gzipped."""
    def read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, = struct.unpack(">H", f.read(4)[2:])
            ndim = magic & 0xFF
            dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

    base = os.path.join(cache_dir, "MNIST", "raw")
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    found = []
    for n in names:
        for cand in (os.path.join(base, n), os.path.join(base, n + ".gz"),
                     os.path.join(cache_dir, n), os.path.join(cache_dir, n + ".gz")):
            if os.path.exists(cand):
                found.append(cand)
                break
    if len(found) != 4:
        return None
    tx, ty, vx, vy = (read_idx(p) for p in found)
    tx = (tx.astype(np.float32) / 255.0)[..., None]
    vx = (vx.astype(np.float32) / 255.0)[..., None]
    return tx, ty.astype(np.int64), vx, vy.astype(np.int64)


def _try_load_cifar(cache_dir: str, name: str):
    """Real CIFAR-10/100 archives in either standard layout (reference
    ``data/cifar10/data_loader.py`` consumes the python pickle batches):

    - ``cifar-10-batches-py/``: pickled ``data_batch_1..5`` + ``test_batch``
      dicts with ``data`` (N, 3072) uint8 and ``labels``;
    - ``cifar-10-batches-bin/``: ``data_batch_*.bin`` rows of
      ``1 label byte + 3072 pixel bytes`` (``cifar-100-binary``: 2 label
      bytes, fine label second).
    """
    import pickle

    is100 = "100" in name
    py_dir = os.path.join(cache_dir,
                          "cifar-100-python" if is100
                          else "cifar-10-batches-py")
    if os.path.isdir(py_dir):
        label_key = b"fine_labels" if is100 else b"labels"

        def read_batches(names):
            xs, ys = [], []
            for n in names:
                p = os.path.join(py_dir, n)
                if not os.path.exists(p):
                    continue
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.uint8))
                ys.append(np.asarray(d[label_key], np.int64))
            if not xs:
                return None, None
            return np.concatenate(xs), np.concatenate(ys)

        train_names = ["train"] if is100 else [f"data_batch_{i}"
                                              for i in range(1, 6)]
        tx, ty = read_batches(train_names)
        vx, vy = read_batches(["test"] if is100 else ["test_batch"])
        if tx is None or vx is None:
            return None

        def to_img(flat):
            return (flat.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                    .astype(np.float32) / 255.0)

        return to_img(tx), ty, to_img(vx), vy

    bin_dir = os.path.join(cache_dir,
                           "cifar-100-binary" if is100
                           else "cifar-10-batches-bin")
    if os.path.isdir(bin_dir):
        label_bytes = 2 if is100 else 1
        row = label_bytes + 3072

        def read_bin(names):
            xs, ys = [], []
            for n in names:
                p = os.path.join(bin_dir, n)
                if not os.path.exists(p):
                    continue
                raw = np.fromfile(p, dtype=np.uint8)
                raw = raw[: (len(raw) // row) * row].reshape(-1, row)
                ys.append(raw[:, label_bytes - 1].astype(np.int64))
                xs.append(raw[:, label_bytes:])
            if not xs:
                return None, None
            x = np.concatenate(xs)
            x = (x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                 .astype(np.float32) / 255.0)
            return x, np.concatenate(ys)

        train_names = ["train.bin"] if is100 else \
            [f"data_batch_{i}.bin" for i in range(1, 6)]
        tx, ty = read_bin(train_names)
        vx, vy = read_bin(["test.bin"] if is100 else ["test_batch.bin"])
        if tx is None or vx is None:
            return None
        return tx, ty, vx, vy
    return None


def _try_load_hdf5(cache_dir: str, name: str):
    """ImageNet-style hdf5 (reference ``data/ImageNet/.../imagenet_hdf5`` —
    one file with train/val image+label datasets)."""
    candidates = [f"{name}.h5", f"{name}.hdf5"]
    if name.startswith(("imagenet", "ilsvrc")):
        candidates.append("imagenet.hdf5")
    for fname in candidates:
        path = os.path.join(cache_dir, fname)
        if not os.path.exists(path):
            continue
        import h5py
        with h5py.File(path, "r") as f:
            def pick(*keys):
                for k in keys:
                    if k in f:
                        return np.asarray(f[k])
                return None
            tx = pick("train_x", "images_train", "train/images")
            ty = pick("train_y", "labels_train", "train/labels")
            vx = pick("test_x", "images_val", "val/images")
            vy = pick("test_y", "labels_val", "val/labels")
        if tx is None or ty is None:
            continue
        if vx is None or vy is None:
            # no (complete) val split in the file: carve 5% off train
            cut = int(len(tx) * 0.95)
            tx, vx = tx[:cut], tx[cut:]
            ty, vy = ty[:cut], ty[cut:]

        def norm(x):
            return x.astype(np.float32) / 255.0 if x.dtype == np.uint8 \
                else x.astype(np.float32)
        return (norm(tx), ty.astype(np.int64), norm(vx),
                vy.astype(np.int64))
    return None


def _sizes(args, train_n: int, test_n: int,
           cap: Optional[Tuple[int, int]] = None) -> Tuple[int, int]:
    """train/test sample counts for a synthetic fallback: explicit
    ``args.train_size``/``test_size`` win, else the branch default
    (optionally capped for reference-scale cardinalities)."""
    if cap is not None:
        train_n, test_n = min(train_n, cap[0]), min(test_n, cap[1])
    return (int(getattr(args, "train_size", 0) or train_n),
            int(getattr(args, "test_size", 0) or test_n))


def _clamped_cut(args, n: int) -> int:
    """Train/test split point for a FIXED-size real pool: honor train_size
    but never let the test split go empty."""
    cut = int(getattr(args, "train_size", 0)) or int(n * 0.85)
    return min(cut, n - max(1, n // 10))


def _sklearn_tabular(name: str, seed: int):
    """Seed-permuted raw sklearn tabular pool: (x, y, classes, src_name).
    Class count is computed on the FULL pool (pre-slice); normalization is
    left to the caller so train-only stats are possible."""
    from sklearn.datasets import load_breast_cancer, load_wine
    d = load_wine() if name == "wine" else load_breast_cancer()
    x = d.data.astype(np.float32)
    y = d.target.astype(np.int64)
    classes = int(y.max()) + 1
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(x))
    return x[perm], y[perm], classes, (
        "wine" if name == "wine" else "breast-cancer")


def load(args) -> Tuple[FederatedDataset, int]:
    name = str(getattr(args, "dataset", "synthetic_mnist")).lower()
    cache = str(getattr(args, "data_cache_dir", "") or "")
    seed = int(getattr(args, "random_seed", 0))
    client_num = int(getattr(args, "client_num_in_total", 10))
    method = str(getattr(args, "partition_method", "hetero"))
    alpha = float(getattr(args, "partition_alpha", 0.5))

    if name in _IMAGE_SPECS:
        classes, shape, train_n, test_n = _IMAGE_SPECS[name]
        if cache:
            # LEAF layout keeps the NATURAL per-user partition (reference
            # data/MNIST/data_loader.py read_data) — it wins over any
            # partition_method re-split.
            leaf_root = find_leaf_root(cache, name)
            if leaf_root is not None:
                tx, ty, vx, vy, cidx, tidx = load_leaf(
                    leaf_root, input_shape=shape)
                ds = FederatedDataset(tx, ty, vx, vy, cidx, classes,
                                      test_client_idxs=tidx,
                                      provenance=_cache_provenance(leaf_root, "real:leaf", name))
                return ds, classes
        real = _try_load_npz(cache, name) if cache else None
        if real is None and name in ("mnist", "synthetic_mnist") and cache:
            real = _try_load_mnist_idx(cache)
        if real is None and name.startswith(("cifar", "fed_cifar")) and cache:
            real = _try_load_cifar(cache, name)
        if real is not None:
            tx, ty, vx, vy = real
            prov = _cache_provenance(cache, "real:cache", name)
        else:
            noise = float(getattr(args, "synthetic_noise", 0.35))
            # synthetic fallback honors size overrides (full reference
            # cardinality only when none given)
            train_n, test_n = _sizes(args, train_n, test_n)
            tx, ty, vx, vy = synthetic_image_classification(
                train_n, test_n, classes, shape, seed, noise)
            prov = "synthetic"
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed, provenance=prov)
        return ds, classes

    if name in _LM_SPECS:
        vocab, seq_len, train_n, test_n = _LM_SPECS[name]
        seq_len = int(getattr(args, "seq_len", seq_len))
        if cache:
            leaf_root = find_leaf_root(cache, name)
            if leaf_root is not None:
                tx, ty, vx, vy, cidx, tidx = load_leaf(
                    leaf_root, seq_len=seq_len)
                ds = FederatedDataset(tx, ty, vx, vy, cidx, vocab,
                                      test_client_idxs=tidx,
                                      provenance=_cache_provenance(leaf_root, "real:leaf", name))
                return ds, vocab
        real = _try_load_npz(cache, name) if cache else None
        if real is None and cache and "shakespeare" in name:
            # raw corpus file (what the reference's download step fetches
            # before LEAF processing); searched under the same cache/<name>/
            # convention the LEAF path uses, for either dataset alias
            for cand in (os.path.join(cache, "shakespeare.txt"),
                         os.path.join(cache, name, "shakespeare.txt"),
                         os.path.join(cache, "shakespeare",
                                      "shakespeare.txt")):
                if os.path.exists(cand):
                    real = load_shakespeare_raw(cand, seq_len)
                    break
        if real is not None:
            tx, ty, vx, vy = real
            prov = _cache_provenance(cache, "real:cache", name)
        else:
            train_n, test_n = _sizes(args, train_n, test_n)
            tx, ty, vx, vy = synthetic_lm_tokens(train_n, test_n, vocab, seq_len, seed)
            prov = "synthetic"
        ds = build_federated(tx, ty, vx, vy, vocab, client_num, method="homo",
                             alpha=alpha, seed=seed, provenance=prov)
        return ds, vocab

    if name in _TAGPRED_SPECS:
        ref_tags, ref_feats, ref_train_n, ref_test_n = _TAGPRED_SPECS[name]
        real = _try_load_npz(cache, name) if cache else None
        if real is not None:
            tx, ty, vx, vy = real
            for part, lab in (("train", ty), ("test", vy)):
                if lab.ndim != 2 or not np.isin(np.unique(lab), (0, 1)).all():
                    raise ValueError(
                        f"{name}.npz {part} labels must be multi-hot "
                        f"(N, n_tags) 0/1 matrices (tag-prediction task), "
                        f"got shape {lab.shape} dtype {lab.dtype} — old "
                        f"LM-format caches are invalid")
            if ty.shape[1] != vy.shape[1]:
                raise ValueError(
                    f"{name}.npz train/test tag counts differ: "
                    f"{ty.shape[1]} vs {vy.shape[1]}")
            ty, vy = ty.astype(np.float32), vy.astype(np.float32)
            n_tags, n_feats = ty.shape[1], tx.shape[1]
        else:
            # synthetic fallback at a tractable scale (the reference-scale
            # dense matrix would be 50k x 10k floats); overrides restore
            # full cardinality when wanted
            n_tags = int(getattr(args, "tag_count", 0) or min(ref_tags, 100))
            n_feats = int(getattr(args, "feature_dim", 0) or
                          min(ref_feats, 1000))
            train_n, test_n = _sizes(args, ref_train_n, ref_test_n,
                                     cap=(5000, 500))
            tx, ty, vx, vy = synthetic_tag_prediction(
                train_n, test_n, n_tags, n_feats, seed)
        # Dirichlet partition needs scalar labels: use each example's
        # first (lowest-index) set tag as its partition class
        primary = np.argmax(ty, axis=1).astype(np.int64)
        client_idxs = partition(primary, client_num, method, alpha, seed)
        ds = FederatedDataset(tx, ty, vx, vy, client_idxs, n_tags,
                              provenance=_cache_provenance(cache, "real:npz", name) if real is not None
                              else "synthetic")
        if not getattr(args, "input_shape", None):
            args.input_shape = (n_feats,)  # model hub reads this for lr
        # single source of truth for the loss/eval branch: the loader knows
        # the task, the model hub reads it (name fallback kept for callers
        # that build the model before loading data)
        args.task_type = "tag_prediction"
        return ds, n_tags

    if name in _TABULAR_SPECS:
        classes, n_features, train_n, test_n = _TABULAR_SPECS[name]
        real = _try_load_npz(cache, name) if cache else None
        if real is not None:
            tx, ty, vx, vy = real
            prov = _cache_provenance(cache, "real:npz", name)
        else:
            train_n, test_n = _sizes(args, train_n, test_n)
            tx, ty, vx, vy = synthetic_tabular(train_n, test_n, classes,
                                               n_features, seed)
            prov = "synthetic"
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed, provenance=prov)
        return ds, classes

    if name in _TEXTCLS_SPECS:
        (classes, vocab, seq_len, train_n, test_n, cls_signal,
         kw_width) = _TEXTCLS_SPECS[name]
        seq_len = int(getattr(args, "seq_len", seq_len))
        # model/data must agree on the token space: honor overrides so a
        # small-vocab model can train on a matching synthetic set
        vocab = int(getattr(args, "vocab_size", 0) or vocab)
        train_n, test_n = _sizes(args, train_n, test_n)
        real = _try_load_npz(cache, name) if cache else None
        if real is not None:
            tx, ty, vx, vy = real
            prov = _cache_provenance(cache, "real:npz", name)
        else:
            # difficulty defaults come from the spec table (calibrated per
            # dataset shape, see _TEXTCLS_SPECS); configs may override to
            # ease the task for fast model-smoke tests, while the BASELINE
            # row runs the calibration (plateau 0.6-0.8, never 1.0)
            tx, ty, vx, vy = synthetic_text_classification(
                train_n, test_n, classes, vocab, seq_len, seed,
                class_signal=float(getattr(args, "text_class_signal",
                                           cls_signal)),
                keyword_width=float(getattr(args, "text_keyword_width",
                                            kw_width)))
            prov = "synthetic"
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed, provenance=prov)
        return ds, classes

    if name in _BIG_IMAGE_SPECS:
        classes, shape, ref_train_n, ref_test_n = _BIG_IMAGE_SPECS[name]
        real = _try_load_npz(cache, name) if cache else None
        if real is None and cache:
            real = _try_load_hdf5(cache, name)
        if real is not None:
            tx, ty, vx, vy = real
        else:
            # synthetic fallback at a tractable scale (reference
            # cardinalities would be ~770GB of pixels)
            train_n, test_n = _sizes(args, ref_train_n, ref_test_n,
                                     cap=(20000, 2000))
            shape = tuple(getattr(args, "input_shape", None) or shape)
            tx, ty, vx, vy = synthetic_image_classification(
                train_n, test_n, classes, shape, seed)
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed,
                             provenance=_cache_provenance(cache, "real:cache", name) if real is not None
                             else "synthetic")
        return ds, classes

    if name in _SEG_SPECS:
        classes, shape, train_n, test_n = _SEG_SPECS[name]
        train_n, test_n = _sizes(args, train_n, test_n)
        shape = tuple(getattr(args, "input_shape", None) or shape)
        real = _try_load_npz(cache, name) if cache else None
        if real is not None:
            tx, ty, vx, vy = real
        else:
            tx, ty, vx, vy = synthetic_segmentation(
                train_n, test_n, classes, shape, seed)
        # Dirichlet partition needs ONE label per sample; use each image's
        # dominant class (reference FeTS partitions by institution, which
        # correlates with tumor morphology — dominant-class is the synthetic
        # stand-in for that skew).
        dominant = np.array([np.bincount(m.reshape(-1),
                                         minlength=classes).argmax()
                             for m in ty])
        client_idxs = partition(dominant, client_num, method, alpha, seed)
        ds = FederatedDataset(tx, ty, vx, vy, client_idxs, classes,
                              provenance=_cache_provenance(cache, "real:npz", name) if real is not None
                              else "synthetic")
        return ds, classes

    if name in ("edge_case_examples", "edge_case"):
        # Reference ``data/edge_case_examples/``: CIFAR-10 plus a pool of
        # out-of-distribution "edge case" images (southwest airplanes etc.)
        # used by the edge-case backdoor attack. The pool rides on the
        # dataset object as ``edge_x``/``edge_y`` (attacker-chosen target).
        classes = 10
        shape = tuple(getattr(args, "input_shape", None) or (32, 32, 3))
        train_n = int(getattr(args, "train_size", 0) or 10000)
        test_n = int(getattr(args, "test_size", 0) or 2000)
        edge_n = int(getattr(args, "edge_case_size", 512))
        tx, ty, vx, vy = synthetic_image_classification(
            train_n, test_n, classes, shape, seed)
        ex, _, _, _ = synthetic_image_classification(
            edge_n, 1, classes, shape, seed ^ 0xED6E, noise=0.9)
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed, provenance="synthetic")
        ds.edge_x = ex
        ds.edge_y = np.full((edge_n,),
                            int(getattr(args, "edge_case_target", 9)),
                            np.int64)
        return ds, classes

    if name in ("breast_cancer", "wine", "uci_real"):
        # REAL tabular bytes without egress (sklearn built-ins) — stand-ins
        # for the reference's UCI/lending_club tabular rows (which need
        # downloads): breast_cancer 569x30 2-class, wine 178x13 3-class.
        x, y, classes, src = _sklearn_tabular(name, seed)
        cut = _clamped_cut(args, len(x))
        # normalization stats from the train split only (no test leakage)
        mu, sd = x[:cut].mean(0), x[:cut].std(0)
        x = (x - mu) / (sd + 1e-8)
        tx, ty, vx, vy = x[:cut], y[:cut], x[cut:], y[cut:]
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed,
                             provenance=f"real:sklearn-{src}")
        return ds, classes

    if name == "digits":
        # REAL data available without egress: sklearn's handwritten-digits
        # set (1797 8x8 grayscale images, 10 classes) — the in-image stand-in
        # for MNIST accuracy-parity runs (MNIST pixels cannot be downloaded
        # here; the idx/LEAF parsers above handle them when provided).
        # A LEAF shard in the cache (tools/make_real_shards.py writes
        # data_shards/digits) wins: same real bytes, but with the NATURAL
        # per-user partition the BASELINE row exercises.  Either way the
        # provenance is real — digits never falls back to synthetic.
        if cache:
            leaf_root = find_leaf_root(cache, "digits")
            if leaf_root is not None:
                tx, ty, vx, vy, cidx, tidx = load_leaf(
                    leaf_root, input_shape=(8, 8, 1))
                ds = FederatedDataset(
                    tx, ty, vx, vy, cidx, 10, test_client_idxs=tidx,
                    provenance=_cache_provenance(leaf_root,
                                                 "real:leaf", "digits"))
                return ds, 10
        from sklearn.datasets import load_digits
        d = load_digits()
        x = (d.data.astype(np.float32) / 16.0).reshape(-1, 8, 8, 1)
        y = d.target.astype(np.int64)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(x))
        x, y = x[perm], y[perm]
        cut = _clamped_cut(args, len(x))
        tx, ty, vx, vy = x[:cut], y[:cut], x[cut:], y[cut:]
        ds = build_federated(tx, ty, vx, vy, 10, client_num, method, alpha,
                             seed, provenance="real:sklearn-digits")
        return ds, 10

    if name.startswith("synthetic"):
        # synthetic_<classes>_<dim...> generic fallback
        classes = int(getattr(args, "num_classes", 10))
        shape = tuple(getattr(args, "input_shape", (28, 28, 1)))
        tx, ty, vx, vy = synthetic_image_classification(
            int(getattr(args, "train_size", 10000)),
            int(getattr(args, "test_size", 2000)), classes, shape, seed)
        ds = build_federated(tx, ty, vx, vy, classes, client_num, method,
                             alpha, seed, provenance="synthetic")
        return ds, classes

    raise ValueError(f"unknown dataset {name!r}")


def load_vertical(args):
    """Vertically-partitioned load (reference NUS-WIDE / classical VFL
    examples): returns (party_feature_arrays, labels, classes)."""
    name = str(getattr(args, "dataset", "nus_wide")).lower()
    parties = int(getattr(args, "vfl_parties", 2))
    seed = int(getattr(args, "random_seed", 0))
    n = int(getattr(args, "train_size", 4000))
    if name in ("breast_cancer", "wine", "uci_real"):
        # REAL vertical split: sklearn tabular features divided contiguously
        # across parties (the classical-VFL setting on real bytes).  Class
        # count comes from the full pool (a small train_size slice may miss
        # a class); normalization is over the returned slice — callers that
        # re-split should treat the stats as jointly computed (the usual
        # VFL preprocessing assumption).
        x, labels, classes, _ = _sklearn_tabular(name, seed)
        x, labels = x[:n], labels[:n]
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        splits = np.array_split(np.arange(x.shape[1]), parties)
        feats = [x[:, idx] for idx in splits]
        return feats, labels, classes
    if name in ("nus_wide", "nuswide"):
        # reference split: party A 634 image features, party B 1000 text tags
        fpp = [634, 1000][:parties] if parties <= 2 else [634, 1000] + \
            [128] * (parties - 2)
        classes = int(getattr(args, "num_classes", 2))
    else:
        fpp = int(getattr(args, "features_per_party", 16))
        classes = int(getattr(args, "num_classes", 2))
    feats, labels = synthetic_vertical_parties(n, parties, fpp, classes, seed)
    return feats, labels, classes
