"""LEAF-format federated dataset ingestion.

The reference parses LEAF json splits for MNIST/FEMNIST/Shakespeare et al.
(``python/fedml/data/MNIST/data_loader.py`` ``read_data``: every file in
``train_path``/``test_path`` is a json with keys ``users``, ``num_samples``,
``user_data`` = {user: {"x": [...], "y": [...]}}), keeping the NATURAL
per-user client partition instead of re-splitting.

This module reproduces that format contract: :func:`read_leaf_dir` merges
every ``*.json`` under a split directory, :func:`load_leaf` assembles both
splits into dense arrays + per-client index maps (the
:class:`~fedml_tpu.data.federated_dataset.FederatedDataset` layout — data as
two device-ready arrays, clients as index arrays).

Character data (Shakespeare/Sent140 x as strings) is encoded with the
reference's letter table (``utils/language_utils.py`` ``ALL_LETTERS``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

# reference python/fedml/data/fed_shakespeare/../utils/language_utils.py
ALL_LETTERS = (
    "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "[]abcdefghijklmnopqrstuvwxyz}"
)
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(ALL_LETTERS)}  # 0 = unk/pad


def encode_chars(text: str, seq_len: Optional[int] = None) -> List[int]:
    ids = [_CHAR_TO_ID.get(c, 0) for c in text]
    if seq_len is not None:
        ids = (ids + [0] * seq_len)[:seq_len]
    return ids


def read_leaf_dir(split_dir: str) -> Tuple[List[str], Dict[str, dict]]:
    """Merge every ``*.json`` in ``split_dir`` → (users, user_data)."""
    users: List[str] = []
    user_data: Dict[str, dict] = {}
    files = sorted(f for f in os.listdir(split_dir) if f.endswith(".json"))
    if not files:
        raise FileNotFoundError(f"no LEAF json files under {split_dir}")
    for fname in files:
        with open(os.path.join(split_dir, fname)) as f:
            blob = json.load(f)
        users.extend(blob["users"])
        user_data.update(blob["user_data"])
    return users, user_data


def _to_arrays(users, user_data, input_shape, seq_len):
    xs, ys, client_idxs = [], [], {}
    cursor = 0
    for ci, u in enumerate(users):
        ux, uy = user_data[u]["x"], user_data[u]["y"]
        enc_x = []
        for row in ux:
            if isinstance(row, str):
                enc_x.append(encode_chars(row, seq_len))
            else:
                enc_x.append(row)
        n = len(enc_x)
        xs.extend(enc_x)
        ys.extend([encode_chars(r, seq_len)[0] if isinstance(r, str) else r
                   for r in uy])
        client_idxs[ci] = np.arange(cursor, cursor + n, dtype=np.int64)
        cursor += n
    x = np.asarray(xs)
    if x.dtype == object:
        raise ValueError("ragged LEAF x rows; provide fixed-length samples "
                         "or a seq_len to pad/truncate to")
    if input_shape is not None and x.ndim == 2 \
            and int(np.prod(input_shape)) == x.shape[1]:
        x = x.reshape((-1,) + tuple(input_shape))
    y = np.asarray(ys)
    if np.issubdtype(x.dtype, np.floating):
        x = x.astype(np.float32)
    if np.issubdtype(y.dtype, np.integer) or y.dtype == np.bool_:
        y = y.astype(np.int64)
    return x, y, client_idxs


def load_leaf(root: str, input_shape=None, seq_len: Optional[int] = None):
    """Load a LEAF dataset rooted at ``root`` (containing ``train/`` and
    ``test/`` split dirs of json shards).

    Returns ``(train_x, train_y, test_x, test_y, client_idxs,
    test_client_idxs)`` with the natural per-user partition.  Users present
    only in one split get an empty index list in the other (reference
    behavior: train/test jsons share the user list).
    """
    tr_users, tr_data = read_leaf_dir(os.path.join(root, "train"))
    te_users, te_data = read_leaf_dir(os.path.join(root, "test"))
    tx, ty, tr_idxs = _to_arrays(tr_users, tr_data, input_shape, seq_len)
    # test clients keyed by the TRAIN user order so client i means the same
    # participant in both splits
    order = {u: i for i, u in enumerate(tr_users)}
    vx_list, vy_list, te_idxs = [], [], {i: [] for i in range(len(tr_users))}
    cursor = 0
    for u in te_users:
        ux = te_data[u]["x"]
        enc = [encode_chars(r, seq_len) if isinstance(r, str) else r
               for r in ux]
        uy = [encode_chars(r, seq_len)[0] if isinstance(r, str) else r
              for r in te_data[u]["y"]]
        vx_list.extend(enc)
        vy_list.extend(uy)
        ci = order.get(u)
        if ci is not None:
            te_idxs[ci] = list(range(cursor, cursor + len(enc)))
        cursor += len(enc)
    vx = np.asarray(vx_list)
    if input_shape is not None and vx.ndim == 2 \
            and int(np.prod(input_shape)) == vx.shape[1]:
        vx = vx.reshape((-1,) + tuple(input_shape))
    vy = np.asarray(vy_list)
    if np.issubdtype(vx.dtype, np.floating):
        vx = vx.astype(np.float32)
    if np.issubdtype(vy.dtype, np.integer):
        vy = vy.astype(np.int64)
    te_idxs = {c: np.asarray(v, dtype=np.int64) for c, v in te_idxs.items()}
    return tx, ty, vx, vy, tr_idxs, te_idxs


def find_leaf_root(cache_dir: str, name: str) -> Optional[str]:
    """Locate a LEAF layout for dataset ``name`` under the cache dir:
    ``<cache>/<name>/{train,test}`` or ``<cache>/{train,test}``."""
    for root in (os.path.join(cache_dir, name), cache_dir):
        if (os.path.isdir(os.path.join(root, "train"))
                and os.path.isdir(os.path.join(root, "test"))):
            train = os.path.join(root, "train")
            if any(f.endswith(".json") for f in os.listdir(train)):
                return root
    return None


def load_shakespeare_raw(path: str, seq_len: int, max_windows: int = 60000,
                         test_frac: float = 0.1, stride: int = None):
    """Raw-text Shakespeare ingestion (the file the reference's
    ``data/shakespeare`` download step fetches before LEAF processing):
    char-encode the whole corpus with the LEAF alphabet, cut it into
    ``seq_len + 1`` windows, and split train/test by position.

    Returns ``(train_x, train_y, test_x, test_y)`` with x = chars[:-1],
    y = chars[1:] next-char targets (same layout as the synthetic LM
    generator and the LEAF loader)."""
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    ids = np.asarray(encode_chars(text), np.int64)
    stride = int(stride or seq_len)
    if len(ids) < 2 * (seq_len + 1):
        raise ValueError(
            f"{path}: corpus too short for a train AND a test "
            f"{seq_len + 1}-char window ({len(ids)} chars)")
    n_win = min(max(2, (len(ids) - seq_len - 1) // stride), max_windows)
    windows = np.lib.stride_tricks.sliding_window_view(
        ids, seq_len + 1)[::stride][:n_win]
    n_win = len(windows)
    x, y = windows[:, :-1], windows[:, 1:]
    n_test = min(max(1, int(n_win * test_frac)), n_win - 1)
    # materialize: sliding views are read-only/non-contiguous, unlike every
    # other loader's owned arrays
    return (np.ascontiguousarray(x[:-n_test]),
            np.ascontiguousarray(y[:-n_test]),
            np.ascontiguousarray(x[-n_test:]),
            np.ascontiguousarray(y[-n_test:]))
