from .data_loader import load
from .federated_dataset import FederatedDataset, build_federated

__all__ = ["load", "FederatedDataset", "build_federated"]
