"""Decentralized (serverless) cross-silo federation — gossip averaging over
a peer topology with NO coordinator.

The reference has decentralized FL only as simulations
(``simulation/sp/decentralized`` DSGD/push-sum and the MPI
``decentralized_framework``); its cross-silo mode is always server-centric.
Here every silo is a peer: per round it trains locally, sends its model to
its out-neighbors (topology from ``core/distributed/topology``), waits for
its in-neighbors, and applies the mixing-matrix weighted average (DSGD /
gossip averaging).  Rounds are tagged so a slow peer's stale gossip can't
corrupt the next round.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict

from ..core import rng as rng_util
from ..core import tree as tree_util
from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..core.distributed.topology.topology_manager import (
    SymmetricTopologyManager)
from ..ml.trainer.local_trainer import LocalTrainer

log = logging.getLogger(__name__)

MSG_TYPE_P2P_MODEL = 601
ARG_MODEL = "p2p_model_params"
ARG_ROUND = "p2p_round_idx"


class DecentralizedWorkerManager(FedMLCommManager):
    """One peer.  ``rank`` ∈ [0, size): ALL ranks are workers (no rank-0
    server).  Topology indices == comm ranks."""

    def __init__(self, args, dataset, model, comm=None, rank=0, size=0,
                 backend="local", topology=None):
        super().__init__(args, comm, rank, size, backend)
        self.topology = topology or SymmetricTopologyManager(
            size, int(getattr(args, "topology_neighbor_num", 2)))
        if getattr(self.topology, "topology", None) is None:
            self.topology.generate_topology()
        self.dataset = dataset
        self.model = model
        self.trainer = LocalTrainer(model, args)
        self.rounds = int(getattr(args, "comm_round", 5))
        self.seed = int(getattr(args, "random_seed", 0))
        self.batch_size = int(getattr(args, "batch_size", 32))
        self.epochs = int(getattr(args, "epochs", 1))
        key = rng_util.root_key(self.seed)
        self.params = model.init(rng_util.purpose_key(key, "init"))
        self.round_idx = 0
        self._inbox: Dict[int, Dict[int, Any]] = {}
        self._lock = threading.Lock()
        self._local_train = None

    # -- FSM ----------------------------------------------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_CONNECTION_IS_READY, self._on_ready)
        self.register_message_receive_handler(
            MSG_TYPE_P2P_MODEL, self._on_peer_model)

    def _on_ready(self, _msg):
        self._step_round()

    def _train_local(self):
        clients = [self.rank % self.dataset.num_clients]
        xb, yb, mask, _w = self.dataset.cohort_batches(
            clients, self.batch_size, self.seed, self.round_idx, self.epochs)
        rng = rng_util.client_key(rng_util.root_key(self.seed),
                                  self.round_idx, self.rank)
        if self._local_train is None:
            self._local_train = self.trainer.make_local_train()
        from ..simulation.round_engine import make_server_ctx
        from ..ml.aggregator.agg_operator import ServerOptimizer
        ctx = make_server_ctx(self.trainer,
                              ServerOptimizer(self.args).init(self.params))
        out = self._local_train(self.params, xb[0], yb[0], mask[0], rng,
                                ctx, None)
        self.params = out.params

    def _step_round(self):
        """Train, gossip to out-neighbors, then wait for in-neighbors."""
        self._train_local()
        for peer in self.topology.get_out_neighbor_idx_list(self.rank):
            if peer == self.rank:
                continue
            msg = Message(MSG_TYPE_P2P_MODEL, self.rank, int(peer))
            msg.add_params(ARG_MODEL, self.params)
            msg.add_params(ARG_ROUND, self.round_idx)
            self.send_message(msg)
        self._maybe_mix()

    def _on_peer_model(self, msg):
        sender = msg.get_sender_id()
        rnd = int(msg.get(ARG_ROUND))
        with self._lock:
            self._inbox.setdefault(rnd, {})[sender] = msg.get(ARG_MODEL)
        self._maybe_mix()

    def _maybe_mix(self):
        with self._lock:
            expected = [int(p) for p in
                        self.topology.get_in_neighbor_idx_list(self.rank)
                        if int(p) != self.rank]
            box = self._inbox.get(self.round_idx, {})
            if not all(p in box for p in expected):
                return
            weights = self.topology.get_in_neighbor_weights(self.rank)
            mixed = tree_util.tree_scale(self.params,
                                         float(weights[self.rank]))
            for p in expected:
                mixed = tree_util.tree_add(
                    mixed, tree_util.tree_scale(box[p], float(weights[p])))
            self.params = mixed
            self._inbox.pop(self.round_idx, None)
            self.round_idx += 1
            done = self.round_idx >= self.rounds
        if done:
            self.finish()
        else:
            self._step_round()


__all__ = ["DecentralizedWorkerManager", "MSG_TYPE_P2P_MODEL"]
