"""LightSecAgg cross-silo message constants (reference
``python/fedml/cross_silo/lightsecagg/lsa_message_define.py``).

Protocol (reference docstring, kept verbatim in structure):
    1 (server initializes the model parameters)
 -> 5 (clients send encoded mask shares to other clients via the server)
 -> 2 (the server routes each encoded mask share to its target client)
 ========= the client is doing the model training =========
 -> 6 (send the trained, masked model to the server)
 -> 4 (the server asks the active users to upload the aggregate mask)
 -> 7 (clients send the aggregate of their received mask shares)
 =========           model aggregation            =========
 -> 3 (the server sends the aggregated model to all clients)
"""


class MyMessage:
    MSG_TYPE_CONNECTION_IS_READY = 0

    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_ENCODED_MASK_TO_CLIENT = 2
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 3
    MSG_TYPE_S2C_SEND_TO_ACTIVE_CLIENT = 4
    MSG_TYPE_S2C_FINISH = 10

    # client -> server
    MSG_TYPE_C2S_SEND_ENCODED_MASK_TO_SERVER = 5
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 6
    MSG_TYPE_C2S_SEND_MASK_TO_SERVER = 7
    MSG_TYPE_C2S_CLIENT_STATUS = 8

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MASKED_PARAMS = "masked_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_ENCODED_MASK = "encoded_mask"
    MSG_ARG_KEY_ACTIVE_CLIENTS = "active_clients"
    MSG_ARG_KEY_AGGREGATE_ENCODED_MASK = "aggregate_encoded_mask"
    MSG_ARG_KEY_CLIENT_ID = "client_id"
