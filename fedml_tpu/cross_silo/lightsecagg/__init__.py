"""LightSecAgg cross-silo federation (reference
``python/fedml/cross_silo/lightsecagg/`` — ``lsa_fedml_api.py`` surface)."""

from .lsa_fedml_client_manager import LSAClientManager
from .lsa_fedml_server_manager import LSAServerManager
from .lsa_message_define import MyMessage

__all__ = ["LSAClientManager", "LSAServerManager", "MyMessage"]
