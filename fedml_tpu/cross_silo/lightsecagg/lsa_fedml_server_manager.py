"""LightSecAgg server FSM (reference
``cross_silo/lightsecagg/lsa_fedml_server_manager.py`` +
``lsa_fedml_aggregator.py``).

The server is an untrusted router + field-arithmetic aggregator: it routes
encoded mask shares between clients, sums masked uploads, and after
collecting U aggregate shares decodes ONLY the sum of masks
(``decode_aggregate_mask``) — individual updates stay hidden.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.lightsecagg import decode_aggregate_mask
from ...core.mpc.secagg import P, dequantize
from ...core.tree import tree_flatten_1d, tree_unflatten_1d
from .lsa_fedml_client_manager import lsa_dims
from .lsa_message_define import MyMessage

log = logging.getLogger(__name__)


class LSAServerManager(FedMLCommManager):
    def __init__(self, args, global_params, comm=None, rank=0, size=0,
                 backend="local", on_round_done=None):
        super().__init__(args, comm, rank, size, backend)
        self.global_params = global_params
        self.client_num = size - 1
        self.N, self.U, self.T = lsa_dims(self.client_num, args)
        self.round_idx = 0
        self.num_rounds = int(getattr(args, "comm_round", 1))
        self.on_round_done = on_round_done
        self._online = set()
        self._started = False
        self._masked: Dict[int, np.ndarray] = {}
        self._weights: Dict[int, float] = {}
        self._agg_shares: Dict[int, np.ndarray] = {}
        self._active_announced = False

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._handle_client_status)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_ENCODED_MASK_TO_SERVER,
            self._handle_encoded_mask)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._handle_model)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MASK_TO_SERVER, self._handle_agg_share)

    # -- onboarding --------------------------------------------------------
    def _handle_client_status(self, msg: Message):
        self._online.add(msg.get_sender_id())
        if not self._started and len(self._online) == self.client_num:
            self._started = True
            self._broadcast(MyMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _broadcast(self, msg_type):
        for rank in range(1, self.client_num + 1):
            m = Message(msg_type, 0, rank)
            m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self.global_params)
            m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            self.send_message(m)

    # -- share routing -----------------------------------------------------
    def _handle_encoded_mask(self, msg: Message):
        dest = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_ID))
        m = Message(MyMessage.MSG_TYPE_S2C_ENCODED_MASK_TO_CLIENT, 0, dest)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_ID, msg.get_sender_id())
        m.add_params(MyMessage.MSG_ARG_KEY_ENCODED_MASK,
                     msg.get(MyMessage.MSG_ARG_KEY_ENCODED_MASK))
        self.send_message(m)

    # -- aggregation -------------------------------------------------------
    def _handle_model(self, msg: Message):
        # same stale-round guard the aggregate-share path has: a delayed
        # round-r masked upload carries round r's z_i mask and can never
        # be unmasked by round r+1's decoded mask sum
        if int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0) != self.round_idx:
            return
        sender = msg.get_sender_id()
        self._masked[sender] = np.asarray(
            msg.get(MyMessage.MSG_ARG_KEY_MASKED_PARAMS), dtype=np.int64)
        self._weights[sender] = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES))
        if len(self._masked) == self.client_num and not self._active_announced:
            self._active_announced = True
            active = sorted(self._masked.keys())
            for rank in range(1, self.client_num + 1):
                m = Message(MyMessage.MSG_TYPE_S2C_SEND_TO_ACTIVE_CLIENT, 0, rank)
                m.add_params(MyMessage.MSG_ARG_KEY_ACTIVE_CLIENTS, active)
                self.send_message(m)

    def _handle_agg_share(self, msg: Message):
        # a late round-r share must not count toward round r+1's U threshold
        if int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0) != self.round_idx:
            return
        self._agg_shares[msg.get_sender_id()] = np.asarray(
            msg.get(MyMessage.MSG_ARG_KEY_AGGREGATE_ENCODED_MASK),
            dtype=np.int64)
        if len(self._agg_shares) >= self.U:
            self._finish_round()

    def _finish_round(self):
        flat = np.asarray(tree_flatten_1d(self.global_params))
        d = flat.size
        k = self.U - self.T
        total_masked = np.zeros(d, dtype=np.int64)
        for y in self._masked.values():
            total_masked = (total_masked + y) % P
        G = decode_aggregate_mask(dict(self._agg_shares), d, self.U)
        sum_mask = G[:k].reshape(-1)[:d]
        total = (total_masked - sum_mask) % P
        total_w = sum(self._weights.values())
        avg = dequantize(total) / max(total_w, 1e-12)
        self.global_params = tree_unflatten_1d(
            np.asarray(avg, dtype=np.float32), self.global_params)
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, self.global_params)
        log.info("lightsecagg round %d aggregated (%d clients, U=%d T=%d)",
                 self.round_idx, len(self._masked), self.U, self.T)
        self._masked.clear()
        self._weights.clear()
        self._agg_shares.clear()
        self._active_announced = False
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            for rank in range(1, self.client_num + 1):
                self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, rank))
            self.finish()
        else:
            self._broadcast(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
