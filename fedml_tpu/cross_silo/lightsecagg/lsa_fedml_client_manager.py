"""LightSecAgg client FSM (reference
``cross_silo/lightsecagg/lsa_fedml_client_manager.py:21``).

Per round: generate a private field mask z_i, MDS-encode it into N shares
(``core/mpc/lightsecagg.mask_encoding``), ship share j to client j via the
server; train; upload quantize(w_i · params) + z_i; when the server announces
the active set, upload the SUM of the shares received from active sources.
The server never sees an unmasked update.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.hostrng import gen as hostgen
from ...core.mpc.lightsecagg import aggregate_shares, mask_encoding
from ...core.mpc.secagg import P, quantize
from ...core.tree import tree_flatten_1d, tree_unflatten_1d
from .lsa_message_define import MyMessage

log = logging.getLogger(__name__)


def lsa_dims(n_clients: int, args) -> tuple:
    """(N, U, T) — N clients, decode threshold U, privacy T (reference args
    ``worker_num`` / ``targeted_number_active_clients`` /
    ``privacy_guarantee``)."""
    N = n_clients
    T = int(getattr(args, "privacy_guarantee", max(1, N // 4)))
    U = int(getattr(args, "targeted_number_active_clients", N - 1 if N > 2 else N))
    U = max(U, T + 1)
    return N, min(U, N), T


class LSAClientManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.client_num = size - 1
        self.N, self.U, self.T = lsa_dims(self.client_num, args)
        self.round_idx = 0
        self.num_rounds = int(getattr(args, "comm_round", 1))
        self._received_shares: Dict[int, np.ndarray] = {}
        self._mask: np.ndarray = None
        self._dim = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._handle_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_ENCODED_MASK_TO_CLIENT, self._handle_encoded_mask)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._handle_sync_model)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SEND_TO_ACTIVE_CLIENT, self._handle_active_set)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._handle_finish)

    # -- round body --------------------------------------------------------
    def _handle_init(self, msg: Message):
        # adopt the server's round index on init too (it broadcasts it on
        # both paths) so the round-bound upload always matches
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0)
        params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        self._round(params)

    def _handle_sync_model(self, msg: Message):
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX))
        self._round(msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS))

    def _round(self, global_params):
        self._received_shares.clear()
        flat = np.asarray(tree_flatten_1d(global_params))
        d = flat.size
        k = self.U - self.T
        self._dim = (-(-d // k)) * k  # padded dimension
        # 1) private mask + encoded shares, share j -> client j via server
        rng = hostgen(int(getattr(self.args, "random_seed", 0)) + self.rank,
                      0x15A, self.round_idx)
        self._mask = rng.integers(0, P, size=self._dim, dtype=np.int64)
        shares = mask_encoding(self._dim, self.N, self.U, self.T, self._mask,
                               seed=int(rng.integers(0, 2**31)))
        for j, share in shares.items():
            m = Message(MyMessage.MSG_TYPE_C2S_SEND_ENCODED_MASK_TO_SERVER,
                        self.rank, 0)
            m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_ID, j)
            m.add_params(MyMessage.MSG_ARG_KEY_ENCODED_MASK, share)
            self.send_message(m)
        # 2) local training; upload masked, weight-scaled params
        new_params, num_samples = self.trainer.train(global_params,
                                                     self.round_idx)
        upd = np.asarray(tree_flatten_1d(new_params), dtype=np.float64)
        masked = (quantize(upd * float(num_samples)) + self._mask[:d]) % P
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_MASKED_PARAMS, masked)
        m.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, num_samples)
        # round-bind the masked upload like the aggregate-share path: the
        # mask z_i is per-round, so a stale upload can never be unmasked
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        self.send_message(m)

    def _handle_encoded_mask(self, msg: Message):
        src = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_ID))
        self._received_shares[src] = np.asarray(
            msg.get(MyMessage.MSG_ARG_KEY_ENCODED_MASK), dtype=np.int64)

    def _handle_active_set(self, msg: Message):
        active = [int(a) for a in msg.get(MyMessage.MSG_ARG_KEY_ACTIVE_CLIENTS)]
        agg = aggregate_shares([self._received_shares[i] for i in active
                                if i in self._received_shares])
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_MASK_TO_SERVER, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_AGGREGATE_ENCODED_MASK, agg)
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        self.send_message(m)

    def _handle_finish(self, msg: Message):
        self.finish()

    def run(self):
        # announce readiness so an MLOps-style server can gate on it
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        self.send_message(msg)
        super().run()
