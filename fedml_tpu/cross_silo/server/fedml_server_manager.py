"""Cross-silo server FSM (reference
``cross_silo/server/fedml_server_manager.py``: client-onboarding handshake →
``send_init_msg:48`` → per-round collect/aggregate/sync →
``handle_message_receive_model_from_client:174``)."""

from __future__ import annotations

import logging
import threading

from ...core.compression import FedMLCompression
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...mlops import log_round_info, log_aggregation_status
from ..message_define import MyMessage

log = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    """Straggler tolerance (absent from the reference — SURVEY §5: a dead
    client stalls ``check_whether_all_receive`` forever): when
    ``aggregation_timeout_s`` > 0, a timer starts at each round's first
    upload; on expiry the round aggregates the partial cohort if at least
    ``min_clients_to_aggregate`` (default 1) results arrived. Uploads carry
    their round index, so a straggler's late result for an already-closed
    round is dropped instead of polluting the next one."""

    def __init__(self, args, aggregator, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 10))
        self.args.round_idx = 0
        self.client_num = size - 1
        self.client_online_set = set()
        self.client_real_ids = list(range(1, size))
        self.client_finished_count = 0
        self.agg_timeout = float(getattr(args, "aggregation_timeout_s", 0))
        self.min_to_aggregate = max(1, int(getattr(
            args, "min_clients_to_aggregate", 1)))
        self._round_lock = threading.Lock()
        self._timer = None
        self._onboard_timer = None
        self._started = False
        self._ckpt = None
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            # round checkpoint/resume — core capability the reference lacks
            # (SURVEY §5: FL rounds had no checkpoint; only S3 artifacts)
            from ...core.checkpoint import RoundCheckpointer
            self._ckpt = RoundCheckpointer(
                str(ckpt_dir), int(getattr(args, "checkpoint_keep", 3)))
            latest = self._ckpt.latest_round()
            if latest is not None:
                state, _ = self._ckpt.restore(
                    template=(self.aggregator.state, None))
                self.aggregator.state = state
                self.args.round_idx = int(latest) + 1
                log.info("server: resumed from round checkpoint %d", latest)

    # -- handshake ---------------------------------------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg_params):
        status = msg_params.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        sender = msg_params.get_sender_id()
        with self._round_lock:
            if status == MyMessage.MSG_CLIENT_STATUS_ONLINE:
                self.client_online_set.add(sender)
                log.info("server: client %d online (%d/%d)", sender,
                         len(self.client_online_set), self.client_num)
                if (self.agg_timeout > 0
                        and len(self.client_online_set) < self.client_num):
                    # straggler tolerance covers onboarding too: never-online
                    # clients must not stall the federation forever. Re-armed
                    # on every arrival, so it measures SILENCE — a slowly but
                    # actively joining cohort is never cut off.
                    self._cancel_onboard_timer()
                    self._onboard_timer = threading.Timer(
                        self.agg_timeout, self._on_onboarding_timeout)
                    self._onboard_timer.daemon = True
                    self._onboard_timer.start()
            if len(self.client_online_set) == self.client_num:
                self._cancel_onboard_timer()
                self.send_init_msg()

    def _cancel_onboard_timer(self):
        if self._onboard_timer is not None:
            self._onboard_timer.cancel()
            self._onboard_timer = None

    def _on_onboarding_timeout(self):
        with self._round_lock:
            self._onboard_timer = None
            online = len(self.client_online_set)
            if self._started:
                return
            if online < self.min_to_aggregate:
                # not enough to start — re-arm so the configured timeout
                # keeps producing progress or visible warnings instead of
                # a silent permanent stall
                log.warning("server: onboarding timeout with only %d/%d "
                            "clients online (need %d); waiting another "
                            "window", online, self.client_num,
                            self.min_to_aggregate)
                self._onboard_timer = threading.Timer(
                    self.agg_timeout, self._on_onboarding_timeout)
                self._onboard_timer.daemon = True
                self._onboard_timer.start()
                return
            log.warning("server: onboarding timeout — starting with %d/%d "
                        "clients online", online, self.client_num)
            self.send_init_msg()

    # -- round machinery ---------------------------------------------------
    def _sampled_client_idxs(self, round_idx):
        return self.aggregator.client_sampling(
            round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            min(int(getattr(self.args, "client_num_per_round", self.client_num)),
                self.client_num),
        )

    def send_init_msg(self):
        """Reference send_init_msg:48 — S2C global model + assigned data idx."""
        if self._started:
            return
        self._started = True
        start_round = int(self.args.round_idx)  # >0 after checkpoint resume
        if start_round >= self.round_num:
            self.send_finish()  # resumed past the last round: nothing to do
            return
        client_idxs = self._sampled_client_idxs(start_round)
        global_params = self.aggregator.get_global_model_params()
        for rank, data_idx in zip(self.client_real_ids, client_idxs):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(data_idx))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, start_round)
            self.send_message(msg)
        self._arm_round_timer()
        log_aggregation_status("RUNNING")

    def _arm_round_timer(self):
        """Caller holds _round_lock (or is in pre-concurrency startup). Armed
        when a round OPENS, so a round with zero uploads still times out."""
        if self.agg_timeout <= 0:
            return
        if self._timer is not None:
            self._timer.cancel()
        self._timer = threading.Timer(self.agg_timeout,
                                      self._on_aggregation_timeout,
                                      args=(self.args.round_idx,))
        self._timer.daemon = True
        self._timer.start()

    def _upload_is_stale(self, msg_params, sender) -> bool:
        msg_round = msg_params.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        if msg_round is not None and int(msg_round) != self.args.round_idx:
            log.warning("server: dropping stale round-%s upload from "
                        "client %d (now at round %d)", msg_round, sender,
                        self.args.round_idx)
            return True
        return False

    def handle_message_receive_model_from_client(self, msg_params):
        sender = msg_params.get_sender_id()
        # require(): a malformed upload fails HERE naming msg_type+sender
        # instead of propagating None into decompress/aggregate
        raw = msg_params.require(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        n = msg_params.require(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        # stale-check + base snapshot under the lock, but run the (per-leaf
        # scatter/reshape) decompression OUTSIDE it so concurrent uploads
        # don't serialize and the timeout handler isn't blocked
        with self._round_lock:
            if self._upload_is_stale(msg_params, sender):
                return
            base = self.aggregator.get_global_model_params()
            snap_round = self.args.round_idx
        params = FedMLCompression.get_instance().maybe_decompress(raw,
                                                                  base=base)
        with self._round_lock:
            # re-verify against the SNAPSHOT round — the round may have
            # advanced (timeout) mid-decompress, and uploads without a
            # ROUND_IDX field would pass _upload_is_stale vacuously
            if (self.args.round_idx != snap_round
                    or self._upload_is_stale(msg_params, sender)):
                return
            self.aggregator.add_local_trained_result(
                self.client_real_ids.index(sender), params, n)
            if not self.aggregator.check_whether_all_receive():
                return
            broadcast = self._finish_round()
        broadcast()  # blocking wire I/O runs after _round_lock is released

    def _on_aggregation_timeout(self, armed_round: int):
        with self._round_lock:
            if armed_round != self.args.round_idx:
                return  # stale callback: that round already closed
            self._timer = None
            received = self.aggregator.received_count
            if received < self.min_to_aggregate:
                log.warning("server: aggregation timeout with only %d/%d "
                            "results; waiting another window", received,
                            self.min_to_aggregate)
                self._arm_round_timer()
                return
            log.warning("server: aggregation timeout — closing round %d "
                        "with %d/%d clients", self.args.round_idx, received,
                        self.client_num)
            self.aggregator.reset_receive_flags()
            broadcast = self._finish_round()
        broadcast()

    def _finish_round(self):
        """Caller holds _round_lock (handler thread or timeout thread).

        Aggregates and advances the round state under the lock, then
        returns a zero-arg callable the caller MUST run after releasing
        it — the callable performs the outbound sends.  Sync-model
        broadcasts are blocking wire I/O; doing them under _round_lock
        would stall every concurrent upload handler and the timeout
        thread for the whole broadcast (and on a reliable backend, for
        its retransmit windows too).  The round timer is armed before the
        lock drops, so an upload racing the broadcast still lands in an
        open, timed round.
        """
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        round_idx = self.args.round_idx
        self.aggregator.aggregate()
        acc = self.aggregator.test_on_server_for_all_clients(round_idx)
        log_round_info(round_idx, {
            "test_acc": acc,
            "dataset_provenance": getattr(
                getattr(self.aggregator, "dataset", None), "provenance",
                "unknown")})
        if self._ckpt is not None:
            freq = int(getattr(self.args, "checkpoint_freq", 10))
            if round_idx % freq == 0 or round_idx == self.round_num - 1:
                self._ckpt.save(round_idx, self.aggregator.state, None)
        self.args.round_idx = round_idx + 1
        if self.args.round_idx >= self.round_num:
            def _finish():
                self.send_finish()
            return _finish
        client_idxs = self._sampled_client_idxs(self.args.round_idx)
        global_params = self.aggregator.get_global_model_params()
        msgs = []
        for rank, data_idx in zip(self.client_real_ids, client_idxs):
            msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                          self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(data_idx))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.args.round_idx)
            msgs.append(msg)
        self._arm_round_timer()

        def _broadcast():
            for msg in msgs:
                self.send_message(msg)
        return _broadcast

    def send_finish(self):
        for rank in self.client_real_ids:
            self.send_message(
                Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
        log_aggregation_status("FINISHED")
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        self.finish()
