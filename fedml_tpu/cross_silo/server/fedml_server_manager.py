"""Cross-silo server FSM (reference
``cross_silo/server/fedml_server_manager.py``: client-onboarding handshake →
``send_init_msg:48`` → per-round collect/aggregate/sync →
``handle_message_receive_model_from_client:174``)."""

from __future__ import annotations

import logging

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...mlops import log_round_info, log_aggregation_status
from ..message_define import MyMessage

log = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 10))
        self.args.round_idx = 0
        self.client_num = size - 1
        self.client_online_set = set()
        self.client_real_ids = list(range(1, size))
        self.client_finished_count = 0

    # -- handshake ---------------------------------------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg_params):
        status = msg_params.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        sender = msg_params.get_sender_id()
        if status == MyMessage.MSG_CLIENT_STATUS_ONLINE:
            self.client_online_set.add(sender)
            log.info("server: client %d online (%d/%d)", sender,
                     len(self.client_online_set), self.client_num)
        if len(self.client_online_set) == self.client_num:
            self.send_init_msg()

    # -- round machinery ---------------------------------------------------
    def _sampled_client_idxs(self, round_idx):
        return self.aggregator.client_sampling(
            round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            min(int(getattr(self.args, "client_num_per_round", self.client_num)),
                self.client_num),
        )

    def send_init_msg(self):
        """Reference send_init_msg:48 — S2C global model + assigned data idx."""
        client_idxs = self._sampled_client_idxs(0)
        global_params = self.aggregator.get_global_model_params()
        for rank, data_idx in zip(self.client_real_ids, client_idxs):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(data_idx))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0)
            self.send_message(msg)
        log_aggregation_status("RUNNING")

    def handle_message_receive_model_from_client(self, msg_params):
        sender = msg_params.get_sender_id()
        params = msg_params.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        n = msg_params.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        self.aggregator.add_local_trained_result(
            self.client_real_ids.index(sender), params, n)
        if not self.aggregator.check_whether_all_receive():
            return
        round_idx = self.args.round_idx
        self.aggregator.aggregate()
        acc = self.aggregator.test_on_server_for_all_clients(round_idx)
        log_round_info(round_idx, {"test_acc": acc})
        self.args.round_idx = round_idx + 1
        if self.args.round_idx >= self.round_num:
            self.send_finish()
            return
        client_idxs = self._sampled_client_idxs(self.args.round_idx)
        global_params = self.aggregator.get_global_model_params()
        for rank, data_idx in zip(self.client_real_ids, client_idxs):
            msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                          self.rank, rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(data_idx))
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.args.round_idx)
            self.send_message(msg)

    def send_finish(self):
        for rank in self.client_real_ids:
            self.send_message(
                Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
        log_aggregation_status("FINISHED")
        self.finish()
