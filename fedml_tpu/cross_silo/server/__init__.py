"""Cross-silo server facade (reference ``cross_silo/fedml_server.py``)."""

from __future__ import annotations

from .async_server_manager import AsyncFedMLServerManager
from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager


class Server:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        client_num = len(getattr(args, "client_id_list", []) or []) or int(
            getattr(args, "client_num_per_round", 2))
        size = client_num + 1
        backend = str(getattr(args, "backend", "local"))
        if backend in ("sp", "mesh", "MPI", "NCCL"):
            backend = "local"
        self.aggregator = FedMLAggregator(args, model, dataset, client_num)
        if server_aggregator is not None:
            self.aggregator.user_aggregator = server_aggregator
        self.server_manager = FedMLServerManager(
            args, self.aggregator, rank=0, size=size, backend=backend)

    def run(self):
        self.server_manager.run()
        return self.aggregator.get_global_model_params()


__all__ = ["Server", "FedMLAggregator", "FedMLServerManager",
           "AsyncFedMLServerManager"]
