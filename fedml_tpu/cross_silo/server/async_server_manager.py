"""Asynchronous cross-silo server (the WAN counterpart of
``simulation/sp/async_fedavg``; the reference has async FL only as an MPI
simulation, ``simulation/mpi/async_fedavg/`` — its cross-silo server always
barriers on the full cohort).

No round barrier: every client upload is mixed into the global model
IMMEDIATELY with a staleness-discounted weight
``α · s(now − τ)``, ``s(t) = (1 + t)^(−a)`` (polynomial discount, same
family as the sp engine), and the fresh global model goes straight back to
that client.  Stragglers therefore never block fast silos; their late
updates still contribute, just discounted.

Termination: after ``comm_round`` total mixed updates, FINISH fans out.
"""

from __future__ import annotations

import logging
import threading

from ...core import tree as tree_util
from ...core.compression import FedMLCompression
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..message_define import MyMessage

log = logging.getLogger(__name__)


class AsyncFedMLServerManager(FedMLCommManager):
    """Server FSM: onboarding handshake → per-upload mix → per-client
    immediate re-dispatch → finish after N updates."""

    def __init__(self, args, aggregator, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.total_updates = int(getattr(args, "comm_round", 10))
        self.mix_alpha = float(getattr(args, "async_alpha", 0.6))
        self.staleness_a = float(getattr(args, "async_staleness_a", 0.5))
        self.client_num = size - 1
        self.updates_done = 0
        #: model version each client last received (for staleness)
        self._dispatched_version = {}
        self._dispatched_params = {}
        self._version = 0
        self._online = set()
        self._started = False
        self._lock = threading.Lock()

    # -- handshake (same shape as the sync server) -------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_status)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_upload)

    def _on_status(self, msg):
        if msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS) != \
                MyMessage.MSG_CLIENT_STATUS_ONLINE:
            return
        with self._lock:
            self._online.add(msg.get_sender_id())
            if len(self._online) < self.client_num or self._started:
                return
            self._started = True
        for rank in range(1, self.client_num + 1):
            self._dispatch(rank, MyMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _dispatch(self, rank: int, mtype) -> None:
        dispatched = self.aggregator.get_global_model_params()
        msg = Message(mtype, self.rank, rank)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, dispatched)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, rank - 1)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self._version)
        self._dispatched_version[rank] = self._version
        # kept so a compressed (delta) upload reconstructs against the exact
        # params this client trained from, not the since-advanced global —
        # one model copy per in-flight client (cross-silo scale)
        self._dispatched_params[rank] = dispatched
        self.send_message(msg)

    # -- async mix ---------------------------------------------------------
    def _staleness_weight(self, staleness: float) -> float:
        return self.mix_alpha * (1.0 + max(staleness, 0.0)) ** \
            (-self.staleness_a)

    def _on_upload(self, msg):
        sender = msg.get_sender_id()
        params = FedMLCompression.get_instance().maybe_decompress(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS),
            base=self._dispatched_params.get(sender))
        with self._lock:
            base_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or
                               self._dispatched_version.get(sender, 0))
            staleness = self._version - base_version
            w = self._staleness_weight(float(staleness))
            mixed = tree_util.tree_add(
                tree_util.tree_scale(
                    self.aggregator.get_global_model_params(), 1.0 - w),
                tree_util.tree_scale(params, w))
            self.aggregator.set_global_model_params(mixed)
            self._version += 1
            self.updates_done += 1
            done = self.updates_done >= self.total_updates
        log.info("async server: mixed update %d from client %d "
                 "(staleness %d, weight %.3f)", self.updates_done, sender,
                 staleness, w)
        if done:
            for rank in range(1, self.client_num + 1):
                self.send_message(Message(
                    MyMessage.MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
        else:
            # only the uploader gets fresh work — no cohort barrier
            self._dispatch(sender, MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)


__all__ = ["AsyncFedMLServerManager"]
