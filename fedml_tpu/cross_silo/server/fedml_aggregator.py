"""Cross-silo server aggregator (reference
``cross_silo/server/fedml_aggregator.py``).

Buffers client updates per round (flag-array ``check_whether_all_receive``
semantics, reference ``mpi/fedavg/FedAVGAggregator.py:61``), then runs the
same jitted merge/server-optimizer the simulators use, plus the trust-stack
hook pipeline (defense → DP → aggregate → post hooks) from the
ServerAggregator frame.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax.numpy as jnp

from ...core import federated
from ...core import rng as rng_util
from ...core import tree as tree_util
from ...core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
from ...core.security.fedml_defender import FedMLDefender
from ...ml.aggregator.agg_operator import ServerOptimizer
from ...ml.trainer.local_trainer import LocalTrainer

log = logging.getLogger(__name__)


class FedMLAggregator:
    def __init__(self, args, model, dataset, client_num: int):
        self.args = args
        self.model = model
        self.dataset = dataset
        self.client_num = int(client_num)
        self.trainer = LocalTrainer(model, args)
        self.server_opt = ServerOptimizer(args)
        key = rng_util.root_key(int(getattr(args, "random_seed", 0)))
        params = model.init(rng_util.purpose_key(key, "init"))
        self.state = self.server_opt.init(params)
        self.model_dict: Dict[int, Any] = {}
        self.partial_dict: Dict[int, Any] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict = {
            i: False for i in range(self.client_num)}
        FedMLDefender.get_instance().init(args)
        FedMLDifferentialPrivacy.get_instance().init(args)

    def get_global_model_params(self):
        return self.state.global_params

    def set_global_model_params(self, params):
        self.state = self.state.replace(global_params=params)

    def add_local_trained_result(self, index: int, model_params, sample_num):
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    # -- two-tier silo->server aggregation (docs/CLIENT_STORE.md) ----------
    def add_local_partial_aggregate(self, index: int, partial,
                                    sample_num):
        """Hierarchical upload path (arXiv:2604.10859): silo ``index``
        ships the PARTIAL aggregate of its whole cohort slice
        (``ServerOptimizer.compute_partial_aggregates``) instead of raw
        per-client models — the server-side payload scales with the silo
        count, not the cohort size.  Rides the same received-flag
        round-barrier as raw uploads."""
        self.partial_dict[index] = partial
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    def aggregate_partials(self):
        """Combine the buffered silo partials exactly
        (``federated.combine_partial_aggregates``) and run the unchanged
        server transition.  Matches :meth:`aggregate` over the union of
        the silos' clients to float-reassociation error."""
        idxs = sorted(self.partial_dict.keys())
        partials = [self.partial_dict[i] for i in idxs]
        agg = federated.combine_partial_aggregates(self.server_opt.spec,
                                                   partials)
        self.state = self.server_opt.update_from_aggregates(self.state, agg)
        self.partial_dict.clear()
        return self.state.global_params

    def check_whether_all_receive(self) -> bool:
        if not all(self.flag_client_model_uploaded_dict.values()):
            return False
        self.reset_receive_flags()
        return True

    @property
    def received_count(self) -> int:
        return sum(self.flag_client_model_uploaded_dict.values())

    def reset_receive_flags(self):
        for i in range(self.client_num):
            self.flag_client_model_uploaded_dict[i] = False

    #: user-supplied alg-frame ServerAggregator (hook pipeline); set by the
    #: Server facade when the caller passes server_aggregator=...
    user_aggregator = None

    def aggregate(self):
        if self.partial_dict and not self.model_dict:
            # hierarchical round: every buffered upload was a silo partial
            # — the server manager's existing all-received -> aggregate()
            # flow needs no changes to run the two-tier topology
            return self.aggregate_partials()
        idxs = sorted(self.model_dict.keys())
        raw_list = [(self.sample_num_dict[i], self.model_dict[i]) for i in idxs]
        if self.user_aggregator is not None:
            return self._aggregate_via_user_hooks(idxs, raw_list)
        defender = FedMLDefender.get_instance()
        dp = FedMLDifferentialPrivacy.get_instance()
        if defender.is_defense_enabled():
            raw_list = defender.defend_before_aggregation(
                raw_list, self.state.global_params)
        if dp.is_global_dp_enabled() and dp.is_clipping():
            raw_list = dp.global_clip(raw_list)
        if defender.is_defense_on_aggregation():
            new_params = defender.defend_on_aggregation(
                raw_list,
                base_aggregation_func=lambda lst: tree_util.weighted_average(
                    [p for _, p in lst], [n for n, _ in lst]))
            self.state = self.state.replace(
                round_idx=self.state.round_idx + 1, global_params=new_params)
        else:
            stacked = tree_util.tree_stack([p for _, p in raw_list])
            weights = jnp.asarray([n for n, _ in raw_list], jnp.float32)
            self.state = self.server_opt.update(self.state, stacked, weights)
        new_params = self.state.global_params
        if defender.is_defense_after_aggregation():
            new_params = defender.defend_after_aggregation(new_params)
        if dp.is_global_dp_enabled():
            new_params = dp.add_global_noise(new_params)
        self.state = self.state.replace(global_params=new_params)
        self.model_dict.clear()
        return new_params

    def _aggregate_via_user_hooks(self, idxs, raw_list):
        """Reference server flow when a user ServerAggregator is given:
        ``on_before_aggregation`` → ``aggregate`` → ``on_after_aggregation``
        → ``assess_contribution`` (``core/alg_frame/server_aggregator.py``)."""
        ua = self.user_aggregator
        ua.set_model_params(self.state.global_params)
        n_before = len(raw_list)
        raw_list, _ = ua.on_before_aggregation(raw_list)
        new_params = ua.aggregate(raw_list)
        new_params = ua.on_after_aggregation(new_params)
        self.state = self.state.replace(
            round_idx=self.state.round_idx + 1, global_params=new_params)
        assessor_on = (getattr(ua, "contribution_assessor_mgr", None)
                       is not None
                       and ua.contribution_assessor_mgr.get_assessor()
                       is not None)
        if assessor_on and self.dataset is not None:
            if len(raw_list) != n_before:
                # a filtering defense changed the list; positional mapping to
                # client ids is gone — crediting would be wrong
                log.warning("skipping contribution assessment: defense "
                            "filtered the cohort (%d -> %d)", n_before,
                            len(raw_list))
            else:
                xb, yb, mb = self.dataset.test_batches()
                val_fn = lambda params: float(self.trainer.evaluate(
                    params, xb, yb, mb)[1])
                ua.assess_contribution(idxs, [p for _, p in raw_list],
                                       new_params, val_fn)
        self.model_dict.clear()
        return new_params

    def client_sampling(self, round_idx: int, client_num_in_total: int,
                        client_num_per_round: int):
        return rng_util.sample_clients(
            int(getattr(self.args, "random_seed", 0)), round_idx,
            client_num_in_total, client_num_per_round).tolist()

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[float]:
        if self.dataset is None:
            return None
        freq = int(getattr(self.args, "frequency_of_the_test", 5))
        rounds = int(getattr(self.args, "comm_round", 0))
        if round_idx % freq != 0 and round_idx != rounds - 1:
            return None
        xb, yb, mb = self.dataset.test_batches()
        loss, acc = self.trainer.evaluate(self.state.global_params, xb, yb, mb)
        log.info("server eval round %d: loss=%.4f acc=%.4f", round_idx, loss, acc)
        return acc
