"""Cross-silo client facade (reference ``cross_silo/fedml_client.py``:
master rank talks to the server; in hierarchical silos, slave ranks join the
intra-silo data-parallel group only)."""

from __future__ import annotations

from .fedml_client_master_manager import ClientMasterManager, TrainerDistAdapter
from .fedml_client_slave_manager import ClientSlaveManager
from .process_group_manager import ProcessGroupManager


class Client:
    def __init__(self, args, device, dataset, model, client_trainer=None):
        client_num = len(getattr(args, "client_id_list", []) or []) or int(
            getattr(args, "client_num_per_round", 2))
        size = client_num + 1
        backend = str(getattr(args, "backend", "local"))
        if backend in ("sp", "mesh", "MPI", "NCCL"):
            backend = "local"
        adapter = TrainerDistAdapter(args, model, dataset)
        if client_trainer is not None:
            adapter.user_trainer = client_trainer
        rank = int(getattr(args, "rank", 1))
        proc_rank_in_silo = int(getattr(args, "proc_rank_in_silo", 0))
        if proc_rank_in_silo > 0:
            # Reference: slave ranks never open a WAN connection.
            self.client_manager = ClientSlaveManager(args, adapter)
        else:
            self.client_manager = ClientMasterManager(
                args, adapter, rank=rank, size=size, backend=backend)

    def run(self):
        self.client_manager.run()


__all__ = ["Client", "ClientMasterManager", "ClientSlaveManager",
           "ProcessGroupManager", "TrainerDistAdapter"]
