"""Silo process launcher (reference ``cross_silo/client/client_launcher.py``
— torchrun-style spawn of the silo's worker processes; and the 3-process
pattern of ``python/tests/cross-silo/run_cross_silo.sh``).

Spawns each participant as a real OS process running a user entry script
with rank/role passed by environment (``FEDML_TPU_RANK`` / ``FEDML_TPU_ROLE``
/ ``FEDML_TPU_RUN_ID``), which is how multi-host deployments launch too —
the entry script calls ``fedml_tpu.init()`` and the comm backend (filestore /
gRPC / MQTT) rendezvouses by run_id.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

log = logging.getLogger(__name__)


class CrossSiloLauncher:
    """Launch a federation (1 server + N clients) as local processes."""

    def __init__(self, entry_script: str, run_id: str,
                 client_ranks: Sequence[int],
                 extra_env: Optional[Dict[str, str]] = None,
                 python: str = sys.executable):
        self.entry_script = entry_script
        self.run_id = str(run_id)
        self.client_ranks = list(client_ranks)
        self.extra_env = dict(extra_env or {})
        self.python = python
        self.procs: List[subprocess.Popen] = []

    def _spawn(self, rank: int, role: str) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.extra_env)
        # children must resolve the same imports as the launcher (the
        # launcher may run from a source tree that isn't pip-installed);
        # merged AFTER extra_env so a caller-supplied PYTHONPATH adds to,
        # not replaces, the sys.path injection
        env["PYTHONPATH"] = os.pathsep.join(
            [p or os.getcwd() for p in sys.path]
            + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        env["FEDML_TPU_RANK"] = str(rank)
        env["FEDML_TPU_ROLE"] = role
        env["FEDML_TPU_RUN_ID"] = self.run_id
        proc = subprocess.Popen([self.python, self.entry_script],
                                env=env)
        log.info("launched %s rank=%d pid=%d", role, rank, proc.pid)
        return proc

    def launch(self) -> None:
        self.procs = [self._spawn(0, "server")] + [
            self._spawn(r, "client") for r in self.client_ranks]

    def wait(self, timeout_s: float = 600.0) -> List[int]:
        """Join all processes; kills the survivors if any participant fails
        or the deadline passes. Returns exit codes in launch order."""
        deadline = time.time() + timeout_s
        codes: List[Optional[int]] = [None] * len(self.procs)
        try:
            while time.time() < deadline:
                pending = False
                for i, p in enumerate(self.procs):
                    if codes[i] is None:
                        codes[i] = p.poll()
                        if codes[i] is None:
                            pending = True
                        elif codes[i] != 0:
                            raise RuntimeError(
                                f"participant {i} exited with {codes[i]}")
                if not pending:
                    return [int(c) for c in codes]
                time.sleep(0.2)
            raise TimeoutError(f"federation did not finish in {timeout_s}s")
        except BaseException:
            self.kill()
            raise

    def kill(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.wait(timeout=10)  # reap: no zombies, no ResourceWarning
            except Exception:
                pass

    def run(self, timeout_s: float = 600.0) -> List[int]:
        self.launch()
        return self.wait(timeout_s)


def env_rank() -> int:
    return int(os.environ.get("FEDML_TPU_RANK", "0"))


def env_role() -> str:
    return os.environ.get("FEDML_TPU_ROLE", "server")


def env_run_id(default: str = "0") -> str:
    return os.environ.get("FEDML_TPU_RUN_ID", default)
