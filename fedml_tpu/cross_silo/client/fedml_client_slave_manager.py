"""Silo slave participant (reference
``cross_silo/client/fedml_client_slave_manager.py`` — slave ranks that never
touch the WAN: they block on ``dist.broadcast_object_list`` for
(round, params, idx) from the master rank, run the DDP train pass, repeat).

In the TPU runtime there is exactly one controller process per host and the
data axis lives inside the compiled step, so slaves only exist for
*multi-host* silos (one jax process per host, multi-controller SPMD). This
manager is that participant: it loops on the master's round broadcast and
joins the sharded train step; in a single-process silo it degenerates to an
immediate no-op, matching how jax absorbs the reference's slave ranks.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)

_FINISH = "finish"


class ClientSlaveManager:
    def __init__(self, args, trainer_adapter):
        self.args = args
        self.trainer_adapter = trainer_adapter
        self.round_idx = 0
        self.num_rounds = int(getattr(args, "comm_round", 10))
        self.finished = False

    def await_sync_process_group(self, src: int = 0):
        """Block until the silo master announces the round; returns
        [round_idx, params, client_index] (round_idx < 0 = finish). The
        slave passes the same zero-filled pytree template the master's
        ``announce_round`` fills (multihost broadcast requires identical
        structure on every process)."""
        pg = getattr(self.trainer_adapter, "process_group_manager", None)
        if pg is None or jax.process_count() <= 1:
            # Single-controller silo: jax's runtime already executed our
            # shard inside the master's jitted step; nothing to wait for.
            return [self.num_rounds, None, None]
        msg = pg.broadcast_object(self.trainer_adapter.sync_template(),
                                  src=src)
        log.info("silo slave got round sync: round=%s", int(msg[0]))
        return msg

    def train(self):
        rnd, params, idx = self.await_sync_process_group()
        self.round_idx = int(rnd)
        if params is None or self.round_idx < 0:
            self.finish()
            return
        self.trainer_adapter.train(params, int(idx), self.round_idx)

    def finish(self):
        self.finished = True
        cleanup = getattr(self.trainer_adapter, "cleanup_pg", None)
        if cleanup is not None:
            cleanup()
        log.info("silo slave finished at round %d", self.round_idx)

    def run(self):
        while not self.finished:
            self.train()
            if self.round_idx >= self.num_rounds:
                self.finish()
