"""Intra-silo process-group analog (reference
``cross_silo/client/process_group_manager.py:8`` — torch
``dist.init_process_group`` NCCL/Gloo; reference
``fedml_client_slave_manager.py:104`` — ``dist.broadcast_object_list`` round
sync).

TPU-native inversion: a silo's "process group" is a named ``data`` axis over
this host's local devices. Data parallelism is expressed by sharding the
batch dimension over that axis inside the jitted local step — XLA/GSPMD
inserts the gradient all-reduce that torch DDP does by hook, and it rides
ICI. Multi-host silos use jax's multi-controller runtime (one process per
host, same program), where `broadcast_object` maps onto
``multihost_utils.broadcast_one_to_all`` rather than a torch broadcast.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.mesh import DATA_AXIS

log = logging.getLogger(__name__)


class ProcessGroupManager:
    """Owns the silo-local data-parallel mesh.

    ``n_proc_in_silo`` (the reference's torchrun world size) bounds how many
    local devices join the data axis; the axis size is clipped to the
    largest divisor of ``batch_size`` so the batch shards evenly (the
    reference instead requires the user to pick matching world sizes).
    """

    def __init__(self, args, devices=None):
        devices = list(devices if devices is not None else jax.local_devices())
        requested = int(getattr(args, "n_proc_in_silo", 0) or 0)
        n = min(len(devices), requested) if requested > 0 else len(devices)
        batch = int(getattr(args, "batch_size", 10))
        while n > 1 and batch % n:
            n -= 1
        self.mesh = Mesh(np.asarray(devices[:n]), (DATA_AXIS,))
        self.batch_sharding = NamedSharding(self.mesh, P(None, DATA_AXIS))
        self.replicated = NamedSharding(self.mesh, P())
        log.info("silo process group: %d-way data parallelism over %s",
                 n, [d.platform for d in devices[:n]])

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size

    def get_process_group(self) -> Mesh:
        return self.mesh

    def broadcast_object(self, obj, src: int = 0):
        """Round-sync broadcast (reference ``sync_process_group:200`` /
        ``await_sync_process_group:104``). Single-controller: identity.
        Multi-controller (one jax process per silo host): broadcast from the
        silo master process over the jax runtime."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.broadcast_one_to_all(
                obj, is_source=jax.process_index() == src)
        return obj

    def cleanup(self) -> None:
        """Parity with the reference's ``destroy_process_group``; meshes are
        plain objects, nothing to tear down."""
