"""Cross-silo client FSM (reference
``cross_silo/client/fedml_client_master_manager.py:22``): online handshake →
receive global model → local training (the jitted LocalTrainer pass) → upload.

The reference's master/slave split (master rank talks MQTT, slaves join a
torch-DDP process group, ``sync_process_group:200``) maps to TPU as: the
client process owns a whole host (all its chips); intra-silo data parallelism
is the mesh ``data`` axis *inside* the jitted train step, so no slave
processes exist — jax's runtime plays the role of the process group.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from ...core import rng as rng_util
from ...core.compression import FedMLCompression
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.security.fedml_attacker import FedMLAttacker
from ...ml.trainer.local_trainer import LocalTrainer, ServerCtx
from ...mlops import log_training_status
from ..message_define import MyMessage

log = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args, trainer_adapter, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer_adapter = trainer_adapter
        self.num_rounds = int(getattr(args, "comm_round", 10))
        FedMLCompression.get_instance().init(args)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_CONNECTION_IS_READY, self.handle_connection_ready)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def handle_connection_ready(self, msg_params):
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                       MyMessage.MSG_CLIENT_STATUS_ONLINE)
        self.send_message(msg)

    def _train_and_send(self, msg_params):
        # require(): a model sync missing its payload raises a KeyError
        # naming the msg_type and sender instead of training on None
        params = msg_params.require(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        data_idx = int(msg_params.require(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        round_idx = int(msg_params.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        log_training_status("TRAINING")
        self.trainer_adapter.announce_round(round_idx, params, data_idx)
        new_params, n = self.trainer_adapter.train(params, data_idx, round_idx)
        comp = FedMLCompression.get_instance()
        if comp.is_compression_enabled():
            # compress the round DELTA against the global params we were
            # sent — sparsifying absolute weights would zero the model
            new_params = comp.compress_upload(new_params, base=params,
                                              client_id=self.rank)
            ratio = comp.ratio_for(self.rank)
            if ratio is not None:
                log.info("client %d upload compressed to %.1f%% of dense",
                         self.rank, 100.0 * ratio)
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, new_params)
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, round_idx)
        self.send_message(msg)

    def handle_message_init(self, msg_params):
        self._train_and_send(msg_params)

    def handle_message_receive_model_from_server(self, msg_params):
        self._train_and_send(msg_params)

    def handle_message_finish(self, msg_params):
        log_training_status("FINISHED")
        self.trainer_adapter.announce_finish()
        self.finish()


class TrainerDistAdapter:
    """Reference ``fedml_trainer_dist_adapter.py:10`` — binds a LocalTrainer
    to this silo's data shard and runs the compiled local pass.

    ``scenario == "hierarchical"`` is the reference's intra-silo DDP (model
    wrapped in ``torch DDP`` at ``fedml_trainer_dist_adapter.py:26``): here
    the batch dimension of every local step is sharded over the silo's
    ``data``-axis mesh (``ProcessGroupManager``) and GSPMD inserts the
    gradient all-reduce — same math, collectives on ICI instead of NCCL."""

    def __init__(self, args, model, dataset):
        self.args = args
        self.model = model
        self.dataset = dataset
        self.trainer = LocalTrainer(model, args)
        # red-team wiring: hand the dataset's edge-example pool (if any) to
        # an edge-case backdoor attacker at startup
        FedMLAttacker.get_instance().provide_edge_pool(dataset)
        self.local_train = jax.jit(self.trainer.make_local_train())
        self.seed = int(getattr(args, "random_seed", 0))
        self.batch_size = int(getattr(args, "batch_size", 10))
        self.epochs = int(getattr(args, "epochs", 1))
        self.process_group_manager = None
        if str(getattr(args, "scenario", "horizontal")) == "hierarchical":
            from .process_group_manager import ProcessGroupManager
            self.process_group_manager = ProcessGroupManager(args)

    def cleanup_pg(self):
        if self.process_group_manager is not None:
            self.process_group_manager.cleanup()

    # -- multi-host silo round sync (reference sync_process_group:200) -----
    def _sync_is_live(self) -> bool:
        return (self.process_group_manager is not None
                and jax.process_count() > 1)

    def sync_template(self):
        """The fixed pytree every silo process passes to the round
        broadcast: [round_idx, params, client_index]. Structure must be
        identical on master and slaves (multihost broadcast contract)."""
        zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self.model.init_abstract())
        return [jnp.zeros((), jnp.int32), zeros, jnp.zeros((), jnp.int32)]

    def announce_round(self, round_idx: int, global_params, data_idx: int):
        if self._sync_is_live():
            self.process_group_manager.broadcast_object(
                [jnp.asarray(round_idx, jnp.int32), global_params,
                 jnp.asarray(data_idx, jnp.int32)])

    def announce_finish(self):
        if self._sync_is_live():
            tmpl = self.sync_template()
            tmpl[0] = jnp.asarray(-1, jnp.int32)
            self.process_group_manager.broadcast_object(tmpl)

    def train(self, global_params, data_idx: int, round_idx: int):
        global_params = jax.tree_util.tree_map(jnp.asarray, global_params)
        xb, yb = self.dataset.client_batches(
            data_idx, self.batch_size, self.seed, round_idx, self.epochs)
        mask = jnp.ones((xb.shape[0],), jnp.float32)
        rng = rng_util.client_key(rng_util.root_key(self.seed), round_idx,
                                  data_idx)
        xb, yb = jnp.asarray(xb), jnp.asarray(yb)
        pg = self.process_group_manager
        if pg is not None and pg.world_size > 1:
            # Intra-silo data parallelism: (steps, batch, ...) sharded on
            # the batch dim; params/rng replicated on the silo mesh.
            xb = jax.device_put(xb, pg.batch_sharding)
            yb = jax.device_put(yb, pg.batch_sharding)
            global_params = jax.device_put(global_params, pg.replicated)
        ctx = ServerCtx(global_params=global_params)
        out = self.local_train(global_params, xb, yb, mask, rng, ctx, None)
        n = len(self.dataset.client_idxs[data_idx])
        return jax.device_get(out.params), n
