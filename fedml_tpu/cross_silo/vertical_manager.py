"""Cross-silo vertical FL (split learning across REAL parties) — the
reference runs vertical FL only inside simulations
(``simulation/sp/classical_vertical_fl``, ``simulation/mpi/``); its
cross-silo mode is horizontal-only.  Here the guest (rank 0: labels + its
feature slice) and host parties (ranks ≥ 1: feature slices only) exchange
ACTIVATIONS and logit-gradients over the message plane — raw features and
labels never leave their owners (the VFL privacy contract).

Per batch: guest announces the (deterministic, seed-derived) batch →
hosts forward their towers and upload partial logits → guest sums, takes
the softmax-CE gradient, broadcasts it → every party updates its own
tower.  SURVEY §2.9 "split learning" row: activations over DCN, same
message protocol as the horizontal FSMs.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..core import hostrng, rng as rng_util
from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..simulation.sp.vertical_fl import VerticalPartyModel

log = logging.getLogger(__name__)

MSG_BATCH = 701          # guest -> hosts: round + batch index list
MSG_PARTIAL = 702        # host -> guest: partial logits
MSG_GRAD = 703           # guest -> hosts: d loss / d logits
MSG_DONE = 704

ARG_ROUND = "vfl_round"
ARG_BATCH = "vfl_batch_idx"
ARG_LOGITS = "vfl_partial_logits"
ARG_GRAD = "vfl_glogit"


class VflGuestManager(FedMLCommManager):
    """Rank 0: label owner + aggregator."""

    def __init__(self, args, features: np.ndarray, labels: np.ndarray,
                 num_classes: int, comm=None, size: int = 0,
                 backend: str = "local"):
        super().__init__(args, comm, 0, size, backend)
        self.x = np.asarray(features, np.float32).reshape(len(labels), -1)
        self.y = np.asarray(labels)
        self.num_classes = int(num_classes)
        self.batch_size = int(getattr(args, "batch_size", 64))
        self.rounds = int(getattr(args, "comm_round", 5))
        self.seed = int(getattr(args, "random_seed", 0))
        lr = float(getattr(args, "learning_rate", 0.1))
        self.model = VerticalPartyModel(
            self.x.shape[1], self.num_classes, lr,
            rng_util.purpose_key(rng_util.root_key(self.seed), "vfl0"))
        self.losses = []
        self._round = 0
        self._batch_i = 0
        self._order = None
        self._partials: Dict[int, np.ndarray] = {}
        self._cur_idx = None
        self._lock = threading.Lock()

        import jax

        @jax.jit
        def guest_grad(logits, y):
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(
                onehot * jax.nn.log_softmax(logits), -1))
            return loss, (jax.nn.softmax(logits) - onehot)

        self._guest_grad = guest_grad

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_CONNECTION_IS_READY, self._on_ready)
        self.register_message_receive_handler(MSG_PARTIAL, self._on_partial)

    def _on_ready(self, _msg):
        self._announce_batch()

    def _announce_batch(self):
        n = len(self.y)
        if self._order is None or self._batch_i + self.batch_size > n:
            if self._order is not None:
                self._round += 1
                if self._round >= self.rounds:
                    for rank in range(1, self.size):
                        self.send_message(Message(MSG_DONE, 0, rank))
                    self.finish()
                    return
            self._order = hostrng.gen(self.seed, 0x7F1,
                                      self._round).permutation(n)
            self._batch_i = 0
        idx = self._order[self._batch_i: self._batch_i + self.batch_size]
        self._batch_i += self.batch_size
        self._cur_idx = idx
        self._partials = {}
        for rank in range(1, self.size):
            msg = Message(MSG_BATCH, 0, rank)
            msg.add_params(ARG_ROUND, self._round)
            msg.add_params(ARG_BATCH, np.asarray(idx, np.int64))
            self.send_message(msg)

    def _on_partial(self, msg):
        sender = msg.get_sender_id()
        with self._lock:
            self._partials[sender] = np.asarray(msg.get(ARG_LOGITS))
            if len(self._partials) < self.size - 1:
                return
            partials = list(self._partials.values())
        idx = self._cur_idx
        own = self.model.forward(jnp.asarray(self.x[idx]))
        logits = own + sum(jnp.asarray(p) for p in partials)
        loss, glogit = self._guest_grad(logits, jnp.asarray(self.y[idx]))
        self.losses.append(float(loss))
        self.model.backward(jnp.asarray(self.x[idx]), glogit)
        for rank in range(1, self.size):
            out = Message(MSG_GRAD, 0, rank)
            out.add_params(ARG_GRAD, np.asarray(glogit))
            self.send_message(out)
        self._announce_batch()


class VflHostManager(FedMLCommManager):
    """Rank ≥ 1: feature-slice owner, no labels ever."""

    def __init__(self, args, features: np.ndarray, num_classes: int,
                 comm=None, rank: int = 1, size: int = 0,
                 backend: str = "local"):
        super().__init__(args, comm, rank, size, backend)
        self.x = np.asarray(features, np.float32).reshape(
            features.shape[0], -1)
        lr = float(getattr(args, "learning_rate", 0.1))
        seed = int(getattr(args, "random_seed", 0))
        self.model = VerticalPartyModel(
            self.x.shape[1], int(num_classes), lr,
            rng_util.purpose_key(rng_util.root_key(seed), f"vfl{rank}"))
        self._cur_idx = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_BATCH, self._on_batch)
        self.register_message_receive_handler(MSG_GRAD, self._on_grad)
        self.register_message_receive_handler(MSG_DONE,
                                              lambda m: self.finish())

    def _on_batch(self, msg):
        idx = np.asarray(msg.get(ARG_BATCH), np.int64)
        self._cur_idx = idx
        logits = self.model.forward(jnp.asarray(self.x[idx]))
        out = Message(MSG_PARTIAL, self.rank, 0)
        out.add_params(ARG_LOGITS, np.asarray(logits))
        self.send_message(out)

    def _on_grad(self, msg):
        glogit = jnp.asarray(np.asarray(msg.get(ARG_GRAD)))
        self.model.backward(jnp.asarray(self.x[self._cur_idx]), glogit)


__all__ = ["VflGuestManager", "VflHostManager"]
