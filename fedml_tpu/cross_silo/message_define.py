"""Message-type constants for the cross-silo FSM (reference
``simulation/mpi/fedavg/message_define.py:7-13`` and
``cross_silo/server/message_define.py``)."""


class MyMessage:
    # server → client
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_S2C_FINISH = 7

    # client → server
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4
    MSG_TYPE_C2S_CLIENT_STATUS = 5

    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"

    MSG_CLIENT_STATUS_ONLINE = "ONLINE"
    MSG_CLIENT_STATUS_IDLE = "IDLE"
