"""SecAgg client FSM (reference
``cross_silo/secagg/sa_fedml_client_manager.py:21``).

Bonawitz-style secure aggregation over the comm layer:
  round r:  DH public key exchange (via server) → pairwise seeds s_ij
         →  Shamir-share the self-mask seed b_i to peers (via server)
         →  train; upload y_i = quantize(w_i·params) + PRG(b_i) + pairwise
         →  on the server's active-client list, reveal the b-shares held
            for surviving peers so the server can strip self-masks.
Pairwise masks cancel in the sum (``core/mpc/secagg.pairwise_mask``
identity); the server never sees an unmasked update.

The DH group is a Mersenne-prime demo group (M89); production deployments
swap in an ECDH suite — the FSM and field arithmetic are unchanged.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.hostrng import gen as hostgen
from ...core.mpc.secagg import P, masked_input, shamir_share
from ...core.tree import tree_flatten_1d
from .sa_message_define import MyMessage

log = logging.getLogger(__name__)

DH_P = (1 << 89) - 1  # Mersenne prime M89 — demo-grade DH group
DH_G = 3


def derive_pair_seed(shared_secret: int) -> int:
    h = hashlib.sha256(str(shared_secret).encode()).digest()
    return int.from_bytes(h[:8], "little")


class SAClientManager(FedMLCommManager):
    def __init__(self, args, trainer, comm=None, rank=0, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.client_num = size - 1
        self.t = int(getattr(args, "secagg_threshold",
                             self.client_num // 2 + 1))
        self.round_idx = 0
        self._sk = None
        self._b_seed = None
        self._pair_seeds: Dict[tuple, int] = {}
        self._held_b_shares: Dict[int, np.ndarray] = {}
        self._pending_global = None

    def register_message_receive_handlers(self):
        M = MyMessage
        self.register_message_receive_handler(M.MSG_TYPE_S2C_INIT_CONFIG,
                                              self._handle_init)
        self.register_message_receive_handler(M.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                                              self._handle_sync)
        self.register_message_receive_handler(M.MSG_TYPE_S2C_OTHER_PK_TO_CLIENT,
                                              self._handle_pk_others)
        self.register_message_receive_handler(M.MSG_TYPE_S2C_OTHER_SS_TO_CLIENT,
                                              self._handle_ss_others)
        self.register_message_receive_handler(M.MSG_TYPE_S2C_ACTIVE_CLIENT_LIST,
                                              self._handle_active)
        self.register_message_receive_handler(M.MSG_TYPE_S2C_FINISH,
                                              self._handle_finish)

    # -- phase 0: receive model, publish DH public key ---------------------
    def _handle_init(self, msg: Message):
        self._start_round(msg)

    def _handle_sync(self, msg: Message):
        self._start_round(msg)

    def _start_round(self, msg: Message):
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0)
        self._pending_global = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        self._held_b_shares.clear()
        self._pair_seeds.clear()
        rng = hostgen(int(getattr(self.args, "random_seed", 0)) + self.rank,
                      0x5A, self.round_idx)
        self._sk = int(rng.integers(2, 1 << 62))
        # b_seed lives in the Shamir field so the server's reconstruction
        # seeds the identical PRG stream
        self._b_seed = int(rng.integers(0, P))
        pk = pow(DH_G, self._sk, DH_P)
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_PK_TO_SERVER, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_PK, str(pk))
        self.send_message(m)

    # -- phase 1: derive pair seeds, Shamir-share b_i ----------------------
    def _handle_pk_others(self, msg: Message):
        pks = {int(k): int(v) for k, v in
               msg.get(MyMessage.MSG_ARG_KEY_PK_OTHERS).items()}
        for j, pk_j in pks.items():
            if j == self.rank:
                continue
            shared = pow(pk_j, self._sk, DH_P)
            self._pair_seeds[tuple(sorted((self.rank, j)))] = \
                derive_pair_seed(shared)
        # Shamir-share the self-mask seed to the N clients (share point j
        # goes to client rank j, routed by the server)
        shares = shamir_share(np.array([self._b_seed % P], dtype=np.int64),
                              n=self.client_num, t=self.t,
                              seed=self._sk & 0x7FFFFFFF)
        for j, share in shares.items():
            m = Message(MyMessage.MSG_TYPE_C2S_SEND_SS_TO_SERVER, self.rank, 0)
            m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_ID, j)
            m.add_params(MyMessage.MSG_ARG_KEY_SS, share)
            self.send_message(m)
        # train + upload the masked model
        new_params, num_samples = self.trainer.train(self._pending_global,
                                                     self.round_idx)
        upd = np.asarray(tree_flatten_1d(new_params), dtype=np.float64)
        peer_ids = list(range(1, self.client_num + 1))
        y = masked_input(upd * float(num_samples), self.rank, peer_ids,
                         self._pair_seeds, self._b_seed)
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_MASKED_PARAMS, y)
        m.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, num_samples)
        # round-bind the masked upload like the reveal path does: a
        # chaos-delayed/duplicated round-r upload must not land in round
        # r+1's sum (fedproto surfaced the asymmetry vs _handle_reveal)
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        self.send_message(m)

    def _handle_ss_others(self, msg: Message):
        src = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_ID))
        self._held_b_shares[src] = np.asarray(
            msg.get(MyMessage.MSG_ARG_KEY_SS), dtype=np.int64)

    # -- phase 2: unmasking — reveal held shares for survivors -------------
    def _handle_active(self, msg: Message):
        active = [int(a) for a in msg.get(MyMessage.MSG_ARG_KEY_ACTIVE_CLIENTS)]
        reveal = {str(i): self._held_b_shares[i] for i in active
                  if i in self._held_b_shares}
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_SS_OTHERS_TO_SERVER,
                    self.rank, 0)
        m.add_params(MyMessage.MSG_ARG_KEY_SS_OTHERS, reveal)
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
        self.send_message(m)

    def _handle_finish(self, msg: Message):
        self.finish()

    def run(self):
        self.send_message(Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                                  self.rank, 0))
        super().run()
