"""SecAgg server FSM (reference
``cross_silo/secagg/sa_fedml_server_manager.py`` + ``sa_fedml_aggregator.py``).

Router + unmasking aggregator: broadcasts the public-key directory, routes
Shamir shares, sums masked uploads (pairwise masks cancel), reconstructs
each survivor's self-mask seed from >= t revealed shares, and strips them
(``core/mpc/secagg.secure_sum``)."""

from __future__ import annotations

import logging
from typing import Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.secagg import dequantize, secure_sum, shamir_reconstruct
from ...core.tree import tree_flatten_1d, tree_unflatten_1d
from .sa_message_define import MyMessage

log = logging.getLogger(__name__)


class SAServerManager(FedMLCommManager):
    def __init__(self, args, global_params, comm=None, rank=0, size=0,
                 backend="local", on_round_done=None):
        super().__init__(args, comm, rank, size, backend)
        self.global_params = global_params
        self.client_num = size - 1
        self.t = int(getattr(args, "secagg_threshold",
                             self.client_num // 2 + 1))
        self.round_idx = 0
        self.num_rounds = int(getattr(args, "comm_round", 1))
        self.on_round_done = on_round_done
        self._online = set()
        self._started = False
        self._pks: Dict[int, str] = {}
        self._masked: Dict[int, np.ndarray] = {}
        self._weights: Dict[int, float] = {}
        self._reveals: Dict[int, Dict[str, np.ndarray]] = {}
        self._active_sent = False

    def register_message_receive_handlers(self):
        M = MyMessage
        self.register_message_receive_handler(M.MSG_TYPE_C2S_CLIENT_STATUS,
                                              self._handle_status)
        self.register_message_receive_handler(M.MSG_TYPE_C2S_SEND_PK_TO_SERVER,
                                              self._handle_pk)
        self.register_message_receive_handler(M.MSG_TYPE_C2S_SEND_SS_TO_SERVER,
                                              self._handle_ss_route)
        self.register_message_receive_handler(M.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                                              self._handle_model)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_SEND_SS_OTHERS_TO_SERVER, self._handle_reveal)

    def _handle_status(self, msg: Message):
        self._online.add(msg.get_sender_id())
        if not self._started and len(self._online) == self.client_num:
            self._started = True
            self._broadcast_model(MyMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _broadcast_model(self, msg_type):
        for rank in range(1, self.client_num + 1):
            m = Message(msg_type, 0, rank)
            m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self.global_params)
            m.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
            self.send_message(m)

    # -- key directory -----------------------------------------------------
    def _handle_pk(self, msg: Message):
        self._pks[msg.get_sender_id()] = str(msg.get(MyMessage.MSG_ARG_KEY_PK))
        if len(self._pks) == self.client_num:
            directory = {str(k): v for k, v in self._pks.items()}
            for rank in range(1, self.client_num + 1):
                m = Message(MyMessage.MSG_TYPE_S2C_OTHER_PK_TO_CLIENT, 0, rank)
                m.add_params(MyMessage.MSG_ARG_KEY_PK_OTHERS, directory)
                self.send_message(m)

    # -- share routing -----------------------------------------------------
    def _handle_ss_route(self, msg: Message):
        dest = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_ID))
        m = Message(MyMessage.MSG_TYPE_S2C_OTHER_SS_TO_CLIENT, 0, dest)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_ID, msg.get_sender_id())
        m.add_params(MyMessage.MSG_ARG_KEY_SS, msg.get(MyMessage.MSG_ARG_KEY_SS))
        self.send_message(m)

    # -- masked uploads ----------------------------------------------------
    def _handle_model(self, msg: Message):
        # same stale-round guard the reveal path has: pairwise masks only
        # cancel within ONE round's cohort — a delayed round-r upload
        # summed into round r+1 can never be unmasked
        if int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0) != self.round_idx:
            return
        self._masked[msg.get_sender_id()] = np.asarray(
            msg.get(MyMessage.MSG_ARG_KEY_MASKED_PARAMS), dtype=np.int64)
        self._weights[msg.get_sender_id()] = float(
            msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES))
        if len(self._masked) == self.client_num and not self._active_sent:
            self._active_sent = True
            active = sorted(self._masked.keys())
            for rank in range(1, self.client_num + 1):
                m = Message(MyMessage.MSG_TYPE_S2C_ACTIVE_CLIENT_LIST, 0, rank)
                m.add_params(MyMessage.MSG_ARG_KEY_ACTIVE_CLIENTS, active)
                self.send_message(m)

    # -- unmasking ---------------------------------------------------------
    def _handle_reveal(self, msg: Message):
        # drop stale reveals from an already-finished round — a late round-r
        # reveal must not count toward round r+1's threshold
        if int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX) or 0) != self.round_idx:
            return
        self._reveals[msg.get_sender_id()] = {
            k: np.asarray(v, dtype=np.int64) for k, v in
            msg.get(MyMessage.MSG_ARG_KEY_SS_OTHERS).items()}
        if len(self._reveals) >= self.t:
            self._finish_round()

    def _finish_round(self):
        active = sorted(self._masked.keys())
        b_seeds = []
        for i in active:
            # holder rank j revealed the share evaluated at point j
            shares = {j: self._reveals[j][str(i)]
                      for j in self._reveals if str(i) in self._reveals[j]}
            b_i = int(shamir_reconstruct(shares)[0])
            b_seeds.append(b_i)
        total = secure_sum([self._masked[i] for i in active], b_seeds)
        total_w = sum(self._weights[i] for i in active)
        avg = dequantize(total) / max(total_w, 1e-12)
        self.global_params = tree_unflatten_1d(
            np.asarray(avg, dtype=np.float32), self.global_params)
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, self.global_params)
        log.info("secagg round %d aggregated (%d clients, t=%d)",
                 self.round_idx, len(active), self.t)
        self._pks.clear()
        self._masked.clear()
        self._weights.clear()
        self._reveals.clear()
        self._active_sent = False
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            for rank in range(1, self.client_num + 1):
                self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, rank))
            self.finish()
        else:
            self._broadcast_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
