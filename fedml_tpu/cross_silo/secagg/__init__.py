"""SecAgg cross-silo federation (reference
``python/fedml/cross_silo/secagg/`` — ``sa_fedml_api.py`` surface)."""

from .sa_fedml_client_manager import SAClientManager
from .sa_fedml_server_manager import SAServerManager
from .sa_message_define import MyMessage

__all__ = ["SAClientManager", "SAServerManager", "MyMessage"]
