"""SecAgg cross-silo message constants (reference
``python/fedml/cross_silo/secagg/sa_message_define.py:16-32``)."""


class MyMessage:
    MSG_TYPE_CONNECTION_IS_READY = 0

    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_S2C_OTHER_PK_TO_CLIENT = 4
    MSG_TYPE_S2C_OTHER_SS_TO_CLIENT = 6
    MSG_TYPE_S2C_ACTIVE_CLIENT_LIST = 10
    MSG_TYPE_S2C_FINISH = 12

    # client -> server
    MSG_TYPE_C2S_SEND_PK_TO_SERVER = 3
    MSG_TYPE_C2S_SEND_SS_TO_SERVER = 5
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 7
    MSG_TYPE_C2S_CLIENT_STATUS = 9
    MSG_TYPE_C2S_SEND_SS_OTHERS_TO_SERVER = 11

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MASKED_PARAMS = "masked_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_PK = "public_key"
    MSG_ARG_KEY_PK_OTHERS = "public_key_others"
    MSG_ARG_KEY_SS = "secret_share"
    MSG_ARG_KEY_SS_OTHERS = "secret_shares_others"
    MSG_ARG_KEY_ACTIVE_CLIENTS = "active_clients"
    MSG_ARG_KEY_CLIENT_ID = "client_id"
