"""FARunner — federated-analytics driver (reference ``fa/runner.py:5`` +
``fa/simulation/sp/simulator.py:9`` ``FASimulatorSingleProcess``).

Dispatches ``args.fa_task`` over the analyzer/aggregator zoo and loops
FA rounds: server init-msg → client local_analyze over their shard →
aggregate.  Data: any per-client list/array dict.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .aggregator.aggregators import (AvgAggregator,
                                     FrequencyEstimationAggregator,
                                     HeavyHitterTrieHHAggregator,
                                     IntersectionAggregator,
                                     KPercentileAggregator, UnionAggregator)
from .local_analyzer.analyzers import (AvgAnalyzer,
                                       FrequencyEstimationAnalyzer,
                                       HeavyHitterTrieHHAnalyzer,
                                       IntersectionAnalyzer,
                                       KPercentileAnalyzer, UnionAnalyzer)

_TASKS = {
    "avg": (AvgAnalyzer, AvgAggregator),
    "union": (UnionAnalyzer, UnionAggregator),
    "intersection": (IntersectionAnalyzer, IntersectionAggregator),
    "k_percentile": (KPercentileAnalyzer, KPercentileAggregator),
    "frequency_estimation": (FrequencyEstimationAnalyzer,
                             FrequencyEstimationAggregator),
    "heavy_hitter": (HeavyHitterTrieHHAnalyzer, HeavyHitterTrieHHAggregator),
    "heavy_hitter_triehh": (HeavyHitterTrieHHAnalyzer,
                            HeavyHitterTrieHHAggregator),
}


class FARunner:
    def __init__(self, args, client_datasets: Dict[int, Sequence]):
        task = str(getattr(args, "fa_task", "avg")).lower()
        if task not in _TASKS:
            raise ValueError(f"unknown fa_task {task!r}; have {sorted(_TASKS)}")
        analyzer_cls, aggregator_cls = _TASKS[task]
        self.args = args
        self.client_datasets = client_datasets
        self.analyzers = {c: analyzer_cls(args) for c in client_datasets}
        for c, a in self.analyzers.items():
            a.set_id(c)
        self.aggregator = aggregator_cls(args)
        self.rounds = int(getattr(args, "fa_round", getattr(args, "comm_round", 1)))

    def run(self):
        result = None
        for r in range(self.rounds):
            submissions = []
            for c, analyzer in self.analyzers.items():
                analyzer.set_init_msg(self.aggregator.get_init_msg())
                analyzer.local_analyze(self.client_datasets[c], self.args)
                submissions.append(
                    (len(self.client_datasets[c]),
                     analyzer.get_client_submission()))
            result = self.aggregator.aggregate(submissions)
        return result
