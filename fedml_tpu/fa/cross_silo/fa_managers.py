"""FA cross-silo server/client FSMs (reference ``fa/cross_silo/``)."""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..runner import _TASKS

log = logging.getLogger(__name__)


class FAMessage:
    MSG_TYPE_S2C_INIT = 101          # server → clients: init msg + round
    MSG_TYPE_C2S_SUBMISSION = 102    # client → server: local submission
    MSG_TYPE_S2C_FINISH = 103
    MSG_TYPE_C2S_ONLINE = 104        # client → server: online handshake

    ARG_INIT_MSG = "fa_init_msg"
    ARG_ROUND = "fa_round_idx"
    ARG_SUBMISSION = "fa_submission"
    ARG_SAMPLE_NUM = "fa_sample_num"
    ARG_RESULT = "fa_result"


def _task_classes(args):
    task = str(getattr(args, "fa_task", "avg")).lower()
    if task not in _TASKS:
        raise ValueError(f"unknown fa_task {task!r}; have {sorted(_TASKS)}")
    return _TASKS[task]


class FACrossSiloServer(FedMLCommManager):
    """Rank 0: broadcast init, collect submissions, aggregate, loop."""

    def __init__(self, args, comm=None, rank=0, size=0, backend="local"):
        super().__init__(args, comm, rank, size, backend)
        _, aggregator_cls = _task_classes(args)
        self.aggregator = aggregator_cls(args)
        self.rounds = int(getattr(args, "fa_round", 1))
        self.round_idx = 0
        self.client_num = size - 1
        self._submissions: Dict[int, Any] = {}
        self.result = None
        self._online = set()
        self._started = False
        self._onboard_timer: Optional[threading.Timer] = None
        self._start_lock = threading.Lock()
        #: submissions needed to close a round (shrinks to the live cohort
        #: on onboarding timeout)
        self._expected = self.client_num

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_CONNECTION_IS_READY, self._handle_ready)
        self.register_message_receive_handler(
            FAMessage.MSG_TYPE_C2S_ONLINE, self._handle_online)
        self.register_message_receive_handler(
            FAMessage.MSG_TYPE_C2S_SUBMISSION, self._handle_submission)

    def _broadcast_round(self):
        for rank in range(1, self.size):
            msg = Message(FAMessage.MSG_TYPE_S2C_INIT, self.rank, rank)
            msg.add_params(FAMessage.ARG_INIT_MSG,
                           self.aggregator.get_init_msg())
            msg.add_params(FAMessage.ARG_ROUND, self.round_idx)
            self.send_message(msg)

    def _handle_ready(self, msg_params):
        # server's own channel is up; round 0 waits for the client-online
        # handshake (mirrors the training FSM — on non-persistent backends
        # a client connecting after the broadcast would miss the init and
        # hang the federation). A timeout guards against lost ONLINEs.
        if self._onboard_timer is None:
            timeout = float(getattr(self.args, "fa_onboarding_timeout_s", 30))
            self._onboard_timer = threading.Timer(
                timeout, self._on_onboarding_timeout)
            self._onboard_timer.daemon = True
            self._onboard_timer.start()

    def _handle_online(self, msg_params):
        sender = msg_params.get_sender_id()
        with self._start_lock:
            self._online.add(sender)
            if len(self._online) >= self.client_num and not self._started:
                self._started = True
                if self._onboard_timer is not None:
                    self._onboard_timer.cancel()
                    self._onboard_timer = None
                self._broadcast_round()

    def _on_onboarding_timeout(self):
        with self._start_lock:
            self._onboard_timer = None
            if self._started:
                return
            # quorum shrinks to the live cohort: without this, starting
            # with a partial cohort converts the visible onboarding stall
            # into a silent mid-round stall in _handle_submission
            self._expected = max(1, len(self._online))
            log.warning(
                "fa server: onboarding timeout — broadcasting round 0 with "
                "%d/%d clients online", len(self._online), self.client_num)
            self._started = True
            self._broadcast_round()

    def _handle_submission(self, msg_params):
        sender = int(msg_params.get(Message.MSG_ARG_KEY_SENDER))
        # round-bind submissions (fedproto: the training FSMs all guard
        # staleness, this one didn't): a duplicated or delayed round-r
        # submission must not count toward — or overwrite data in —
        # round r+1's quorum
        msg_round = msg_params.get(FAMessage.ARG_ROUND)
        if msg_round is not None and int(msg_round) != self.round_idx:
            log.warning("fa server: dropping stale round-%s submission "
                        "from client %d (now at round %d)", msg_round,
                        sender, self.round_idx)
            return
        self._submissions[sender] = (
            float(msg_params.get(FAMessage.ARG_SAMPLE_NUM, 1.0)),
            msg_params.get(FAMessage.ARG_SUBMISSION))
        if len(self._submissions) < self._expected:
            return
        subs = [self._submissions[r] for r in sorted(self._submissions)]
        self.result = self.aggregator.aggregate(subs)
        self._submissions.clear()
        self.round_idx += 1
        if self.round_idx >= self.rounds:
            for rank in range(1, self.size):
                msg = Message(FAMessage.MSG_TYPE_S2C_FINISH, self.rank, rank)
                msg.add_params(FAMessage.ARG_RESULT, None)
                self.send_message(msg)
            self.finish()
        else:
            self._broadcast_round()


class FACrossSiloClient(FedMLCommManager):
    def __init__(self, args, train_data, comm=None, rank=1, size=0,
                 backend="local"):
        super().__init__(args, comm, rank, size, backend)
        analyzer_cls, _ = _task_classes(args)
        self.analyzer = analyzer_cls(args)
        self.analyzer.set_id(rank)
        self.train_data = train_data

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_CONNECTION_IS_READY, self._handle_ready)
        self.register_message_receive_handler(
            FAMessage.MSG_TYPE_S2C_INIT, self._handle_init)
        self.register_message_receive_handler(
            FAMessage.MSG_TYPE_S2C_FINISH, self._handle_finish)

    def _handle_ready(self, msg_params):
        self.send_message(
            Message(FAMessage.MSG_TYPE_C2S_ONLINE, self.rank, 0))

    def _handle_init(self, msg_params):
        self.analyzer.set_init_msg(msg_params.get(FAMessage.ARG_INIT_MSG))
        self.analyzer.local_analyze(self.train_data, self.args)
        msg = Message(FAMessage.MSG_TYPE_C2S_SUBMISSION, self.rank, 0)
        msg.add_params(FAMessage.ARG_SUBMISSION,
                       self.analyzer.get_client_submission())
        msg.add_params(FAMessage.ARG_SAMPLE_NUM, float(len(self.train_data)))
        # echo the round we are answering so the server can drop stale
        # or duplicated submissions
        msg.add_params(FAMessage.ARG_ROUND,
                       int(msg_params.get(FAMessage.ARG_ROUND, 0)))
        self.send_message(msg)

    def _handle_finish(self, msg_params):
        self.finish()
