"""Cross-silo federated analytics (reference ``fa/cross_silo/`` —
``fa_server_manager.py`` / ``fa_client_manager.py``: the FA pass run as a
real federation over the comm plane instead of in-process).

Same FSM skeleton as the training cross-silo managers; the payload is the
analyzer submission (any msgpack-able value) instead of a model pytree.
"""

from .fa_managers import (FACrossSiloClient, FACrossSiloServer,
                          FAMessage)

__all__ = ["FACrossSiloClient", "FACrossSiloServer", "FAMessage"]
