"""Federated analytics frame (reference ``python/fedml/fa/base_frame/``:
``FAClientAnalyzer`` / ``FAServerAggregator`` — the FL-shaped pair for
analytics instead of training)."""

from __future__ import annotations

import abc
from typing import Any, List, Tuple


class FAClientAnalyzer(abc.ABC):
    def __init__(self, args=None):
        self.args = args
        self.client_submission = None
        self.init_msg = None
        self.id = 0

    def set_id(self, analyzer_id):
        self.id = analyzer_id

    def get_client_submission(self):
        return self.client_submission

    def set_client_submission(self, value):
        self.client_submission = value

    def set_init_msg(self, init_msg):
        self.init_msg = init_msg

    def get_init_msg(self):
        return self.init_msg

    @abc.abstractmethod
    def local_analyze(self, train_data, args):
        ...


class FAServerAggregator(abc.ABC):
    def __init__(self, args=None):
        self.args = args
        self.server_data = None
        self.init_msg = None

    def get_server_data(self):
        return self.server_data

    def set_server_data(self, value):
        self.server_data = value

    def get_init_msg(self):
        return self.init_msg

    @abc.abstractmethod
    def aggregate(self, local_submission_list: List[Tuple[float, Any]]):
        ...
