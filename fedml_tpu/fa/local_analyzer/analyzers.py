"""Local analyzers (reference ``python/fedml/fa/local_analyzer/*.py``):
per-client computations whose submissions the server aggregates.

Heavy numeric paths (histograms, percentile counts) are jnp ops so a
many-client simulation vmaps them on-device.
"""

from __future__ import annotations

import numpy as np

from ..base_frame import FAClientAnalyzer


class AvgAnalyzer(FAClientAnalyzer):
    """avg.py: submit (sum, count)."""

    def local_analyze(self, train_data, args):
        x = np.asarray(train_data, dtype=np.float64)
        self.set_client_submission((float(x.sum()), int(x.size)))


class UnionAnalyzer(FAClientAnalyzer):
    """union.py: submit the set of local values."""

    def local_analyze(self, train_data, args):
        self.set_client_submission(set(np.asarray(train_data).ravel().tolist()))


class IntersectionAnalyzer(FAClientAnalyzer):
    """intersection.py (PSI building block): submit the local value set;
    the server intersects.  The private variant hashes values first."""

    def local_analyze(self, train_data, args):
        self.set_client_submission(set(np.asarray(train_data).ravel().tolist()))


class KPercentileAnalyzer(FAClientAnalyzer):
    """k_percentile.py: given the server's candidate value (init msg),
    submit counts (n_below, n_total) for the distributed k-percentile
    bisection."""

    def local_analyze(self, train_data, args):
        x = np.asarray(train_data, dtype=np.float64).ravel()
        candidate = self.get_init_msg()
        if candidate is None:
            self.set_client_submission((float(x.min()), float(x.max())))
        else:
            self.set_client_submission(
                (int((x <= candidate).sum()), int(x.size)))


class FrequencyEstimationAnalyzer(FAClientAnalyzer):
    """frequency_estimation.py: submit a local histogram over the domain;
    with ``fa_ldp_epsilon`` set, each count is randomized-response perturbed
    (local DP)."""

    def local_analyze(self, train_data, args):
        x = np.asarray(train_data, dtype=np.int64).ravel()
        domain = int(getattr(args, "fa_domain_size", int(x.max()) + 1))
        hist = np.bincount(x, minlength=domain).astype(np.float64)
        eps = float(getattr(args, "fa_ldp_epsilon", 0.0) or 0.0)
        if eps > 0:
            # randomized response on the one-hot reports
            p = np.exp(eps) / (np.exp(eps) + domain - 1)
            q = (1.0 - p) / (domain - 1)
            n = x.size
            noisy = np.random.default_rng(
                int(getattr(args, "random_seed", 0)) + self.id
            ).binomial(n=1, p=np.clip(p * hist / max(n, 1) + q, 0, 1),
                       size=domain)
            hist = noisy * n
        self.set_client_submission(hist)


class HeavyHitterTrieHHAnalyzer(FAClientAnalyzer):
    """heavy_hitter_triehh.py: submit prefixes (length = server-announced
    trie depth) of local strings that extend the server's current trie."""

    def local_analyze(self, train_data, args):
        depth, trie = self.get_init_msg() or (1, {""})
        votes = {}
        for s in train_data:
            s = str(s)
            if len(s) >= depth and s[: depth - 1] in trie:
                prefix = s[:depth]
                votes[prefix] = votes.get(prefix, 0) + 1
        self.set_client_submission(votes)
